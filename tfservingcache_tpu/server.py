"""Process wiring: build and run a cache node (+ router when discovery is
configured).

Reference equivalent: cmd/taskhandler/main.go:20-113 — serveCache always
runs; serveProxy only when ``discovery.type`` is set (main.go:88-105:
single-node "cache-only" mode otherwise); a 30 s health loop pushes status
into every gRPC health server (main.go:35-42).
"""

from __future__ import annotations

import asyncio
import os
import signal

from tfservingcache_tpu.cache.disk_cache import ModelDiskCache
from tfservingcache_tpu.cache.manager import CacheManager
from tfservingcache_tpu.cache.providers import create_provider
from tfservingcache_tpu.cluster.status import StatusCollector
from tfservingcache_tpu.config import Config
from tfservingcache_tpu.protocol.grpc_server import GrpcServingServer
from tfservingcache_tpu.protocol.local_backend import LocalServingBackend
from tfservingcache_tpu.protocol.rest import RestServingServer
from tfservingcache_tpu.utils.accounting import LEDGER
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.metrics import Metrics
from tfservingcache_tpu.utils.net import outbound_ip
from tfservingcache_tpu.utils.tracing import TRACER

log = get_logger("server")

HEALTH_LOOP_PERIOD_S = 30.0  # reference main.go:41


class ServingGroup:
    """One chip group's full serving stack: group mesh -> runtime -> manager
    -> backend -> its own REST/gRPC server pair. A group is a ring member
    (SURVEY.md §7 step 8: the ring assigns models to chip GROUPS, not hosts;
    the group's distinct ports make (host, group) addressable by peers)."""

    def __init__(self, index: int, manager: CacheManager, backend, rest, grpc) -> None:
        self.index = index
        self.manager = manager
        self.backend = backend
        self.rest = rest
        self.grpc = grpc
        self.rest_port = 0
        self.grpc_port = 0
        self.status: StatusCollector | None = None  # fleet status plane


class CacheNode:
    """One serving host: provider + disk cache shared across its chip-group
    runtimes, each group behind its own REST/gRPC protocol servers."""

    def __init__(self, cfg: Config, runtime=None) -> None:
        self.cfg = cfg
        self.metrics = Metrics(
            model_labels=cfg.metrics.model_labels,
            max_model_labels=cfg.metrics.max_model_labels,
        )
        provider = create_provider(cfg.model_provider)
        if cfg.cluster.peer_fetch:
            # peer param distribution: front the store with the peer path
            # (cache/providers/peer.py). Constructed UNBOUND — pure
            # pass-through — until a Router arms it with the fleet view
            # (single-node deployments never bind, and lose nothing).
            from tfservingcache_tpu.cache.providers.peer import PeerProvider

            provider = PeerProvider(
                provider,
                chunk_bytes=cfg.cluster.peer_fetch_chunk_bytes,
                timeout_s=cfg.cluster.peer_fetch_timeout_s,
                max_message_bytes=cfg.proxy.grpc_max_message_bytes,
            )
        self.provider = provider
        disk_cache = ModelDiskCache(cfg.cache.base_dir, cfg.cache.disk_capacity_bytes)
        self.disk_cache = disk_cache

        self.work_handler = None   # follower work service (cross-host groups)
        self.work_server = None
        self._follower_managers: list[CacheManager] = []
        if runtime is not None:
            runtimes = [(0, runtime)]
        else:
            from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime

            if cfg.mesh.coordinator and cfg.mesh.num_processes > 1:
                # multi-controller deployment: rendezvous BEFORE any backend
                # use so jax.devices() sees the whole slice (probing
                # jax.process_count() first would itself init the backend)
                import jax

                if (cfg.serving.platform or os.environ.get(
                        "JAX_PLATFORMS", "")).startswith("cpu"):
                    # the CPU backend only runs cross-process programs over
                    # gloo collectives, and jax no longer defaults to them —
                    # without this every partitioned op in a CPU group fails
                    # with "Multiprocess computations aren't implemented"
                    jax.config.update(
                        "jax_cpu_collectives_implementation", "gloo"
                    )
                try:
                    jax.distributed.initialize(
                        cfg.mesh.coordinator,
                        num_processes=cfg.mesh.num_processes,
                        process_id=cfg.mesh.process_id,
                    )
                except RuntimeError as e:
                    if "already initialized" not in str(e).lower():
                        raise
            if cfg.mesh.chips_per_group > 1:
                import numpy as np

                import jax
                from jax.sharding import Mesh

                from tfservingcache_tpu.parallel.mesh import chip_groups

                devices = jax.devices()
                me = jax.process_index()
                runtimes = []
                followers_of: dict[int, TPUModelRuntime] = {}
                for gi, gdevs in enumerate(chip_groups(devices, cfg.mesh.chips_per_group)):
                    procs = sorted({d.process_index for d in gdevs})
                    if me not in procs:
                        continue  # this process owns none of the group's chips
                    mesh = Mesh(np.array(gdevs), ("model",))
                    leader = gdevs[0].process_index
                    if leader == me and len(procs) > 1:
                        from tfservingcache_tpu.parallel.multihost import (
                            MultiHostGroupRuntime,
                        )

                        addrs = [cfg.mesh.worker_addrs[p] for p in procs if p != me]
                        runtimes.append((gi, MultiHostGroupRuntime(
                            cfg.serving, self.metrics, mesh=mesh, group=gi,
                            followers=addrs, group_index=gi,
                        )))
                    elif leader == me:
                        runtimes.append((gi, TPUModelRuntime(
                            cfg.serving, self.metrics, mesh=mesh, group=gi
                        )))
                    else:
                        # follower: participate in the group's collectives via
                        # the work service; the LEADER is the ring member
                        followers_of[gi] = TPUModelRuntime(
                            cfg.serving, self.metrics, mesh=mesh, group=gi
                        )
                if followers_of:
                    from tfservingcache_tpu.parallel.multihost import (
                        GroupWorkHandler,
                        GroupWorkServer,
                    )

                    self.work_handler = GroupWorkHandler()
                    for gi, rt in followers_of.items():
                        mgr = CacheManager(
                            provider, disk_cache, rt, self.metrics,
                            load_timeout_s=cfg.serving.load_timeout_s,
                            version_labels=cfg.serving.version_labels,
                        )
                        self.work_handler.register(gi, mgr, rt)
                        self._follower_managers.append(mgr)
                    self.work_server = GroupWorkServer(self.work_handler)
            else:
                # host tier is single-chip only (mesh runtimes above keep the
                # deterministic full-load path, so the knob is not plumbed)
                runtimes = [(0, TPUModelRuntime(
                    cfg.serving, self.metrics,
                    host_tier_bytes=cfg.cache.host_tier_bytes,
                ))]

        self.groups: list[ServingGroup] = []
        for pos, (i, rt) in enumerate(runtimes):
            manager = CacheManager(
                provider, disk_cache, rt, self.metrics,
                load_timeout_s=cfg.serving.load_timeout_s,
                version_labels=cfg.serving.version_labels,
            )
            backend = LocalServingBackend(
                manager,
                batch_window_ms=cfg.serving.batch_window_ms,
                batch_max_size=cfg.serving.batch_max_size,
                generate_engine=cfg.serving.generate_engine,
                generate_slots=cfg.serving.generate_slots,
                generate_chunk_tokens=cfg.serving.generate_chunk_tokens,
                kv_page_tokens=cfg.serving.kv_page_tokens,
                kv_arena_pages=cfg.serving.kv_arena_pages,
                kv_share_prefix_bytes=cfg.serving.kv_share_prefix_bytes,
                kv_paged_kernel=cfg.serving.kv_paged_kernel,
                kv_arena_dtype=cfg.serving.kv_arena_dtype,
                spec_draft_model=cfg.serving.spec_draft_model,
                spec_tokens=cfg.serving.spec_tokens,
                generate_recovery=cfg.serving.generate_recovery,
                generate_max_recoveries=cfg.serving.generate_max_recoveries,
                conversation_kv_bytes=cfg.serving.conversation_kv_bytes,
                conversation_kv_disk_bytes=cfg.serving.conversation_kv_disk_bytes,
                conversation_kv_dir=cfg.serving.conversation_kv_dir,
                prefill_chunk_tokens=cfg.serving.prefill_chunk_tokens,
            )
            # every group records into the SHARED Metrics registry (request/
            # error/latency counters must cover all groups); only the first
            # local group mounts the /metrics exposition endpoint for the host
            rest = RestServingServer(
                backend,
                self.metrics,
                require_version=False,
                metrics_path=cfg.metrics.path if pos == 0 else None,
                metrics_scrape_targets=cfg.metrics.scrape_targets,
                metrics_sum_counters=cfg.metrics.scrape_sum_counters,
            )
            grpc = GrpcServingServer(
                backend, self.metrics, cfg.proxy.grpc_max_message_bytes
            )
            if cfg.cluster.peer_fetch:
                # outbound half of the peer path: serve this group's
                # host-tier packed entries to cold peers (the handler
                # answers NOT_FOUND when the tier is off or empty)
                from tfservingcache_tpu.protocol.peer_transfer import PeerSource

                grpc.peer_source = PeerSource(
                    rt,
                    chunk_bytes=cfg.cluster.peer_fetch_chunk_bytes,
                    max_inflight_per_peer=cfg.cluster.peer_fetch_max_inflight_per_peer,
                )
            # conversation KV migration (ISSUE 18): expose this group's
            # parked decode state over FetchParkedConversation so a peer
            # that inherits a conversation after a ring rebalance resumes
            # it with O(new tokens) prefill instead of a cold re-prefill
            gen_tier = getattr(
                getattr(backend, "_generator", None), "conversation_tier", None
            )
            if gen_tier is not None:
                grpc.conversation_tier = gen_tier
            group = ServingGroup(i, manager, backend, rest, grpc)
            if cfg.cluster.status_exchange:
                # per-group status collector for the fleet exchange; built
                # with a placeholder ident (ports aren't bound yet) that the
                # Router rebinds to the ring ident once they are
                group.status = StatusCollector(
                    f"group{i}", manager, metrics=self.metrics,
                    byte_cap=cfg.cluster.status_byte_cap,
                    max_models=cfg.cluster.status_max_models,
                    min_interval_s=cfg.cluster.status_min_interval_s,
                    max_tenants=cfg.cluster.status_max_tenants,
                )
                rest.status_collector = group.status
                grpc.status_collector = group.status
            self.groups.append(group)
        self._health_task: asyncio.Task | None = None

    # group-0 aliases: the single-group shape most callers/tests use
    @property
    def manager(self) -> CacheManager:
        return self.groups[0].manager

    @property
    def backend(self):
        return self.groups[0].backend

    async def start(self) -> tuple[int, int]:
        """Start every group's servers. Group i binds base_port + i (or an
        ephemeral port when the base is 0). Returns the first local group's
        ports (0, 0 for a pure-follower process)."""
        for g in self.groups:
            rest_base = self.cfg.cache_node.rest_port
            grpc_base = self.cfg.cache_node.grpc_port
            g.rest_port = await g.rest.start(rest_base + g.index if rest_base else 0)
            g.grpc_port = await g.grpc.start(grpc_base + g.index if grpc_base else 0)
            if g.status is not None:
                # rebind the placeholder ident to the ring ident peers will
                # see — a standalone node (no colocated Router) must still
                # advertise a routable identity in its piggybacked status
                host = ("127.0.0.1" if self.cfg.discovery.prefer_localhost
                        else outbound_ip())
                g.status.ident = f"{host}:{g.rest_port}:{g.grpc_port}"
        if self.work_server is not None:
            # follower work endpoint: advertised to leaders via
            # mesh.worker_addrs[process_id]
            me = self.cfg.mesh.process_id
            addrs = self.cfg.mesh.worker_addrs
            port = 0
            if me < len(addrs) and ":" in addrs[me]:
                port = int(addrs[me].rsplit(":", 1)[1])
            bound = await self.work_server.start(port)
            log.info("group work service on :%d (follower groups %s)",
                     bound, self.work_handler.group_indexes)
        self._health_task = asyncio.create_task(self._health_loop())
        if not self.groups:
            return 0, 0
        return self.groups[0].rest_port, self.groups[0].grpc_port

    def is_healthy(self) -> bool:
        return all(g.manager.is_healthy() for g in self.groups)

    async def _health_loop(self) -> None:
        while True:
            healthy = await asyncio.get_running_loop().run_in_executor(None, self.is_healthy)
            for g in self.groups:
                g.grpc.set_health(healthy)
            await asyncio.sleep(HEALTH_LOOP_PERIOD_S)

    async def close(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
        for g in self.groups:
            g.backend.close()
            await g.rest.close()
            await g.grpc.close()
            g.manager.close()
        if self.work_server is not None:
            await self.work_server.close()
        for mgr in self._follower_managers:
            mgr.close()
        close_provider = getattr(self.provider, "close", None)
        if close_provider is not None:
            close_provider()


async def serve(cfg: Config) -> None:
    # the process-wide tracer is configured once at server startup (tests
    # construct Tracer instances directly and never pass through here)
    TRACER.configure(
        capacity=cfg.tracing.capacity,
        slow_threshold_s=cfg.tracing.slow_threshold_ms / 1000.0,
        slow_capacity=cfg.tracing.slow_capacity,
    )
    # flight-recorder rings are always on; anomaly dumps arm here, and every
    # slow-retained root (SLO breach) now also snapshots the engine
    RECORDER.configure(
        flight_dir=cfg.observability.flight_dir or None,
        ring_entries=cfg.observability.ring_entries,
        max_dumps=cfg.observability.max_dumps,
        dump_cooldown_s=cfg.observability.dump_cooldown_s,
    )
    RECORDER.install_slow_hook(TRACER)
    # per-tenant cost-attribution ledger (utils/accounting.py): the engine,
    # runtime, and cache tiers feed the process-global LEDGER; the knobs
    # here only tune the noisy-neighbor detector and the master switch
    LEDGER.configure(
        enabled=cfg.observability.tenant_accounting,
        noisy_share=cfg.observability.noisy_neighbor_share,
        noisy_window_s=cfg.observability.noisy_neighbor_window_s,
        noisy_min_step_s=cfg.observability.noisy_neighbor_min_step_s,
    )
    node = CacheNode(cfg)
    if cfg.observability.lab_faults:
        # scenario-lab chaos drill (lab/faults.py): armed ONLY when the
        # operator set observability.lab_faults (or its env override) — the
        # injector hooks are single-bool-read passthroughs otherwise
        from tfservingcache_tpu.lab import faults as lab_faults

        lab_faults.arm_json(cfg.observability.lab_faults, metrics=node.metrics)
    rest_port, grpc_port = await node.start()
    log.info(
        "cache node up: REST :%d, gRPC :%d (provider=%s, cache=%s)",
        rest_port, grpc_port, cfg.model_provider.type, cfg.cache.base_dir,
    )
    router = None
    if cfg.discovery.type:
        from tfservingcache_tpu.cluster.router import Router

        router = Router(cfg, node)
        await router.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix
            pass
    await stop.wait()
    log.info("shutting down")
    if router is not None:
        await router.close()
    await node.close()


def run_server(cfg: Config) -> None:
    if cfg.serving.platform:
        # must happen before backend init: a JAX_PLATFORMS env var alone does
        # not beat an installed PJRT plugin's registration (see conftest.py) —
        # only the config update reliably selects the platform
        import jax

        jax.config.update("jax_platforms", cfg.serving.platform)
    asyncio.run(serve(cfg))

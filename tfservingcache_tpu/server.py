"""Process wiring: build and run a cache node (+ router when discovery is
configured).

Reference equivalent: cmd/taskhandler/main.go:20-113 — serveCache always
runs; serveProxy only when ``discovery.type`` is set (main.go:88-105:
single-node "cache-only" mode otherwise); a 30 s health loop pushes status
into every gRPC health server (main.go:35-42).
"""

from __future__ import annotations

import asyncio
import signal

from tfservingcache_tpu.cache.disk_cache import ModelDiskCache
from tfservingcache_tpu.cache.manager import CacheManager
from tfservingcache_tpu.cache.providers import create_provider
from tfservingcache_tpu.config import Config
from tfservingcache_tpu.protocol.grpc_server import GrpcServingServer
from tfservingcache_tpu.protocol.local_backend import LocalServingBackend
from tfservingcache_tpu.protocol.rest import RestServingServer
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.metrics import Metrics

log = get_logger("server")

HEALTH_LOOP_PERIOD_S = 30.0  # reference main.go:41


class CacheNode:
    """One serving node: provider -> disk cache -> JAX runtime behind the
    REST/gRPC protocol servers."""

    def __init__(self, cfg: Config, runtime=None) -> None:
        self.cfg = cfg
        self.metrics = Metrics(model_labels=cfg.metrics.model_labels)
        provider = create_provider(cfg.model_provider)
        disk_cache = ModelDiskCache(cfg.cache.base_dir, cfg.cache.disk_capacity_bytes)
        if runtime is None:
            from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime

            mesh = None
            if cfg.mesh.chips_per_group > 1:
                import jax

                from tfservingcache_tpu.parallel.mesh import group_mesh

                # this node serves chip group 0 of its local devices; the ring
                # assigns models to nodes = chip groups (SURVEY.md §7 step 8)
                mesh = group_mesh(jax.devices(), cfg.mesh.chips_per_group, 0)
            runtime = TPUModelRuntime(cfg.serving, self.metrics, mesh=mesh)
        self.manager = CacheManager(provider, disk_cache, runtime, self.metrics)
        self.backend = LocalServingBackend(
            self.manager,
            batch_window_ms=cfg.serving.batch_window_ms,
            batch_max_size=cfg.serving.batch_max_size,
        )
        self.rest = RestServingServer(
            self.backend,
            self.metrics,
            require_version=False,
            metrics_path=cfg.metrics.path,
            metrics_scrape_targets=cfg.metrics.scrape_targets,
        )
        self.grpc = GrpcServingServer(
            self.backend, self.metrics, cfg.proxy.grpc_max_message_bytes
        )
        self._health_task: asyncio.Task | None = None

    async def start(self) -> tuple[int, int]:
        rest_port = await self.rest.start(self.cfg.cache_node.rest_port)
        grpc_port = await self.grpc.start(self.cfg.cache_node.grpc_port)
        self._health_task = asyncio.create_task(self._health_loop())
        return rest_port, grpc_port

    def is_healthy(self) -> bool:
        return self.manager.is_healthy()

    async def _health_loop(self) -> None:
        while True:
            healthy = await asyncio.get_running_loop().run_in_executor(None, self.is_healthy)
            self.grpc.set_health(healthy)
            await asyncio.sleep(HEALTH_LOOP_PERIOD_S)

    async def close(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
        self.backend.close()
        await self.rest.close()
        await self.grpc.close()
        self.manager.close()


async def serve(cfg: Config) -> None:
    node = CacheNode(cfg)
    rest_port, grpc_port = await node.start()
    log.info(
        "cache node up: REST :%d, gRPC :%d (provider=%s, cache=%s)",
        rest_port, grpc_port, cfg.model_provider.type, cfg.cache.base_dir,
    )
    router = None
    if cfg.discovery.type:
        from tfservingcache_tpu.cluster.router import Router

        router = Router(cfg, node)
        await router.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix
            pass
    await stop.wait()
    log.info("shutting down")
    if router is not None:
        await router.close()
    await node.close()


def run_server(cfg: Config) -> None:
    asyncio.run(serve(cfg))

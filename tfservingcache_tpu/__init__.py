"""tfservingcache_tpu — a TPU-native multi-tenant model-serving cache.

A ground-up JAX/XLA re-design of the capabilities of mKaloer/TFServingCache
(reference layer map: /root/reference, see SURVEY.md):

  - speaks the TensorFlow Serving predict protocol (REST + gRPC) so existing
    clients work unmodified (reference pkg/tfservingproxy/);
  - routes each (model, version) to TPU chip groups in a pod slice via a
    consistent hash ring with configurable per-model replication
    (reference pkg/taskhandler/cluster.go);
  - on a cache miss JIT-fetches the model artifact from disk/S3/GCS/Azure,
    compiles it with JAX/XLA and pins the executable + params in TPU HBM
    under a byte-budgeted two-tier LRU (reference pkg/cachemanager/);
  - replaces the reference's external TensorFlow Serving process (reference
    pkg/cachemanager/servingcontroller.go) with an in-process JAX runtime —
    the process boundary in the reference's hot path disappears.

Nothing in this package is a translation of the reference's Go: the compute
path is jit/pjit/shard_map over a jax.sharding.Mesh and Pallas kernels; the
runtime around it is asyncio + a small C++ routing core.
"""

__version__ = "0.1.0"

"""Core shared types.

Reference equivalents: ``ModelIdentifier``/``Model`` structs
(pkg/cachemanager/cachemanager.go:45-54) and the routing key format
``name + "##" + version`` (pkg/taskhandler/taskhandler.go:84-92).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, NamedTuple


class ModelId(NamedTuple):
    name: str
    version: int

    @property
    def key(self) -> str:
        """Consistent-hash routing key (reference taskhandler.go:87)."""
        return f"{self.name}##{self.version}"

    def __str__(self) -> str:
        return f"{self.name}@{self.version}"


@dataclass
class Model:
    """A fetched model artifact on local disk."""

    identifier: ModelId
    path: str = ""                 # absolute path of the artifact dir in the disk cache
    size_on_disk: int = 0
    metadata: dict[str, Any] = field(default_factory=dict)


class ModelState(enum.IntEnum):
    """Model lifecycle state machine.

    Mirrors TF Serving's ``ModelVersionStatus_State`` enum values 0/10/20/30/40/50
    that the reference tracks via gRPC (pkg/cachemanager/servingcontroller.go:29-54);
    here the state machine lives in-process in the JAX runtime.
    """

    UNKNOWN = 0
    START = 10
    LOADING = 20
    AVAILABLE = 30
    UNLOADING = 40
    END = 50


@dataclass
class NodeInfo:
    """A serving peer (reference ``ServingService``, pkg/taskhandler/cluster.go:16-20);
    identity string is ``host:restPort:grpcPort`` (cluster.go:142-164)."""

    host: str
    rest_port: int
    grpc_port: int

    @property
    def ident(self) -> str:
        return f"{self.host}:{self.rest_port}:{self.grpc_port}"

    @classmethod
    def from_ident(cls, s: str) -> "NodeInfo":
        host, rest, grpc = s.rsplit(":", 2)
        return cls(host=host, rest_port=int(rest), grpc_port=int(grpc))

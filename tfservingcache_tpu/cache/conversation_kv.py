"""Conversation KV tier: park, spill, and resume decode state (ISSUE 18).

The three-tier residency model the *model* artifacts already enjoy
(HBM -> host DRAM -> disk, cache/host_tier.py + cache/disk_cache.py) applied
to *KV pages*: when a request carrying a ``conversation_id`` retires, the
lane's live pages (int8 + per-row scales when the arena is quantized, so
half the bytes) and its token history are exported into this byte-budgeted
tier instead of being discarded. The next turn re-imports the parked pages
into the arena and prefills only the suffix — O(new tokens) instead of
O(conversation), the way SGLang-lineage stacks scale session reuse past HBM
(PAPERS.md).

Tier discipline mirrors ``HostRamTier``: one shared LRU engine per level
(native/lru.py via ``make_lru_cache``), byte budget, MRU touch on get,
evict callbacks outside the internal lock. The host level's evict callback
IS the spill: the coldest conversation serializes to a flat blob
(``pack_parked``) and moves into a second byte-budgeted LRU over disk
files. A disk hit promotes back to host. The same blob format rides PR 8's
integrity-checked peer wire when the ring rebalances
(protocol/peer_transfer.py ``iter_kv_frames``/``KVStreamReceiver``), so a
conversation survives its node changing.

``get`` PEEKS — the entry survives until the next park of the same
conversation replaces it — so a crashed lane (runtime/batcher.py
generate_recovery) can re-resume from its parked ancestor instead of
re-prefilling the whole history.
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import struct
import threading
from dataclasses import dataclass
from typing import Any

import numpy as np

from tfservingcache_tpu.cache.lru import CapacityError, LRUEntry
from tfservingcache_tpu.native import make_lru_cache
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("conversation_kv")

# blob format tag (disk spill files and the peer KV wire share it)
KV_BLOB_MAGIC = b"TPKV1\n"
_HDR_LEN = struct.Struct("<I")


@dataclass
class ParkedConversation:
    """One parked conversation's resumable decode state.

    ``pages_k``/``pages_v`` are OWNED host copies of the lane's live arena
    pages in block-table order, shape ``(layers, n_pages, n_kv, page_tokens,
    hd)`` in the arena dtype (int8 when the arena is quantized, in which
    case ``k_scale``/``v_scale`` carry the per-row f32 scales). ``history``
    is the exact token prefix those pages cover — resume matches it against
    the new prompt to decide how many tokens skip prefill. Page bytes
    round-trip bit-exact: park copies raw arena rows and resume scatters
    them back verbatim, so a resumed lane's KV is byte-identical to one
    that never retired.
    """

    model_id: str
    history: np.ndarray                 # (tokens,) int32
    pages_k: np.ndarray
    pages_v: np.ndarray
    k_scale: np.ndarray | None
    v_scale: np.ndarray | None
    page_tokens: int
    nbytes: int = 0

    def __post_init__(self) -> None:
        if not self.nbytes:
            self.nbytes = sum(
                a.nbytes
                for a in (self.history, self.pages_k, self.pages_v,
                          self.k_scale, self.v_scale)
                if a is not None
            )


def _raw_bytes(a: np.ndarray) -> memoryview:
    # uint8 view, not tobytes(): extension dtypes (bfloat16) lack the
    # buffer protocol and a view avoids copying the page payload
    return memoryview(np.ascontiguousarray(a).reshape(-1).view(np.uint8))


def pack_parked(parked: ParkedConversation) -> bytes:
    """Serialize to a flat self-describing blob (disk spill + peer wire).

    Layout: magic, u32 header length, JSON header (model id, page_tokens,
    history length, per-array dtype/shape), then the raw array bytes
    concatenated in header order. Byte-exact round-trip by construction —
    arrays are stored as their raw memory, no npz/pickle re-encode.
    """
    arrays: list[tuple[str, np.ndarray]] = [
        ("history", parked.history),
        ("pages_k", parked.pages_k),
        ("pages_v", parked.pages_v),
    ]
    if parked.k_scale is not None:
        arrays.append(("k_scale", parked.k_scale))
    if parked.v_scale is not None:
        arrays.append(("v_scale", parked.v_scale))
    header = {
        "model": str(parked.model_id),
        "page_tokens": int(parked.page_tokens),
        "arrays": [
            {"name": n, "dtype": a.dtype.name, "shape": list(a.shape)}
            for n, a in arrays
        ],
    }
    hb = json.dumps(header).encode()
    parts = [KV_BLOB_MAGIC, _HDR_LEN.pack(len(hb)), hb]
    parts.extend(_raw_bytes(a) for _, a in arrays)
    return b"".join(parts)


def unpack_parked(blob: bytes | memoryview) -> ParkedConversation:
    import ml_dtypes  # registers bfloat16/float8 names with np.dtype

    del ml_dtypes
    mv = memoryview(blob)
    n_magic = len(KV_BLOB_MAGIC)
    if bytes(mv[:n_magic]) != KV_BLOB_MAGIC:
        raise ValueError("bad parked-KV blob: wrong magic")
    (hlen,) = _HDR_LEN.unpack_from(mv, n_magic)
    off = n_magic + _HDR_LEN.size
    header = json.loads(bytes(mv[off:off + hlen]).decode())
    off += hlen
    out: dict[str, np.ndarray] = {}
    for ent in header["arrays"]:
        dt = np.dtype(ent["dtype"])
        shape = tuple(int(s) for s in ent["shape"])
        nb = int(np.prod(shape)) * dt.itemsize if shape else dt.itemsize
        a = np.frombuffer(mv, np.uint8, nb, off).view(dt).reshape(shape)
        out[ent["name"]] = a.copy()  # own the buffer, don't pin the blob
        off += nb
    if off != len(mv):
        raise ValueError(
            f"bad parked-KV blob: {len(mv) - off} trailing bytes"
        )
    return ParkedConversation(
        model_id=header["model"],
        history=out["history"],
        pages_k=out["pages_k"],
        pages_v=out["pages_v"],
        k_scale=out.get("k_scale"),
        v_scale=out.get("v_scale"),
        page_tokens=int(header["page_tokens"]),
    )


ConvKey = tuple[str, str]  # (model_id, conversation_id)


@lockchecked
class ConversationKVTier:
    """Two-level byte-budgeted LRU of ``ParkedConversation``.

    Level 1 (host DRAM) holds live ``ParkedConversation`` payloads; its
    evict callback spills the blob to level 2 (disk files under
    ``disk_dir``) when a disk budget is configured, else the conversation
    is simply dropped (counted as an eviction either way). A zero host
    budget disables the tier entirely — every ``put`` is a no-op and every
    ``get`` a miss, byte-identical behavior to a build without the tier.
    """

    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_hits": "_stats_lock",
        "_spilled_hits": "_stats_lock",
        "_misses": "_stats_lock",
        "_parked_total": "_stats_lock",
        "_spills": "_stats_lock",
        "_migrations_in": "_stats_lock",
    }

    def __init__(
        self,
        capacity_bytes: int,
        disk_capacity_bytes: int = 0,
        disk_dir: str | None = None,
        metrics: Any = None,
    ) -> None:
        self.metrics = metrics
        self.capacity_bytes = max(0, int(capacity_bytes))
        self.disk_capacity_bytes = max(0, int(disk_capacity_bytes))
        self.disk_dir = disk_dir
        self.enabled = self.capacity_bytes > 0
        # host level: payload = ParkedConversation
        self.host = make_lru_cache(max(1, self.capacity_bytes), self._on_evict_host)
        # disk level: payload = blob path; evict callback deletes the file
        self._spill = (
            self.enabled and self.disk_capacity_bytes > 0 and disk_dir is not None
        )
        self.disk = make_lru_cache(max(1, self.disk_capacity_bytes), self._on_evict_disk)
        self._closed = threading.Event()
        self._stats_lock = threading.Lock()
        self._hits = 0
        self._spilled_hits = 0
        self._misses = 0
        self._parked_total = 0
        self._spills = 0
        self._migrations_in = 0
        if self._spill:
            os.makedirs(disk_dir, exist_ok=True)
        self._update_gauges()

    # -- core ---------------------------------------------------------------
    def put(self, conversation_id: str, parked: ParkedConversation) -> None:
        """Park (or re-park, replacing the previous turn's entry)."""
        if not self.enabled or self._closed.is_set():
            return
        key = (str(parked.model_id), str(conversation_id))
        try:
            self.host.put(key, parked.nbytes, parked)
        except CapacityError:
            log.warning(
                "conversation %s (%d KV bytes) exceeds parked-KV budget %d; dropped",
                conversation_id, parked.nbytes, self.capacity_bytes,
            )
            return
        # a re-park supersedes any spilled copy of the same conversation
        self.disk.remove(key, run_callback=True)
        with self._stats_lock:
            self._parked_total += 1
        self._update_gauges()

    def get(
        self, conversation_id: str, model_id: str, touch: bool = True,
    ) -> tuple[ParkedConversation | None, str]:
        """Look up parked state; returns ``(parked, outcome)`` with outcome
        one of ``hit`` (host), ``spilled`` (read back + re-promoted from
        disk), ``miss``. PEEKS — the entry stays parked so a crashed lane
        can resume again; the next park of the same conversation replaces
        it."""
        if not self.enabled:
            return None, "miss"
        key = (str(model_id), str(conversation_id))
        parked = self.host.get(key, touch=touch)
        if parked is not None:
            self._count("hit")
            return parked, "hit"
        path = self.disk.get(key, touch=touch)
        if path is not None:
            try:
                with open(path, "rb") as f:
                    parked = unpack_parked(f.read())
            except (OSError, ValueError) as e:
                log.warning("parked-KV read-back failed for %s: %s", key, e)
                self.disk.remove(key, run_callback=True)
                self._count("miss")
                self._update_gauges()
                return None, "miss"
            # promote host-ward (may itself spill a colder conversation);
            # drop the disk copy so bytes are never double-counted
            self.disk.remove(key, run_callback=True)
            try:
                self.host.put(key, parked.nbytes, parked)
            except CapacityError:
                pass  # serve it anyway; too big to re-park
            self._count("spilled")
            self._update_gauges()
            return parked, "spilled"
        self._count("miss")
        return None, "miss"

    def adopt(self, conversation_id: str, parked: ParkedConversation) -> None:
        """Land a conversation migrated from a peer (ring rebalance)."""
        self.put(conversation_id, parked)
        with self._stats_lock:
            self._migrations_in += 1

    def drop(self, conversation_id: str, model_id: str) -> None:
        key = (str(model_id), str(conversation_id))
        self.host.remove(key, run_callback=False)
        self.disk.remove(key, run_callback=True)
        self._update_gauges()

    def drop_model(self, model_id: str) -> None:
        """Forget every conversation parked for a model (unload path)."""
        mid = str(model_id)
        for key in [k for k in self.host.keys_mru_first() if k[0] == mid]:
            self.host.remove(key, run_callback=False)
        for key in [k for k in self.disk.keys_mru_first() if k[0] == mid]:
            self.disk.remove(key, run_callback=True)
        self._update_gauges()

    # -- eviction / spill ---------------------------------------------------
    def _on_evict_host(self, key: ConvKey, entry: LRUEntry[ParkedConversation]) -> None:
        if self._spill and not self._closed.is_set():
            blob = pack_parked(entry.payload)
            name = hashlib.sha256(
                f"{key[0]}\x00{key[1]}".encode()
            ).hexdigest()[:24]
            path = os.path.join(self.disk_dir, f"{name}.kv")
            try:
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(blob)
                os.replace(tmp, path)
                self.disk.put(key, len(blob), path)
                with self._stats_lock:
                    self._spills += 1
                if self.metrics is not None:
                    self.metrics.evictions.labels("conversation_kv_host").inc()
                self._update_gauges()
                log.info(
                    "parked conversation %s spilled host->disk (%d bytes)",
                    key[1], len(blob),
                )
                return
            except (OSError, CapacityError) as e:
                log.warning("parked-KV spill failed for %s: %s", key, e)
        if self.metrics is not None:
            self.metrics.evictions.labels("conversation_kv_host").inc()
        self._update_gauges()

    def _on_evict_disk(self, key: ConvKey, entry: LRUEntry[str]) -> None:
        try:
            os.unlink(entry.payload)
        except OSError:
            pass
        if self.metrics is not None:
            self.metrics.evictions.labels("conversation_kv_disk").inc()
        self._update_gauges()

    # -- outcome accounting (resume path calls back into metrics) -----------
    def _count(self, outcome: str) -> None:
        with self._stats_lock:
            if outcome == "hit":
                self._hits += 1
            elif outcome == "spilled":
                self._spilled_hits += 1
            else:
                self._misses += 1
        if self.metrics is not None:
            self.metrics.kv_resume.labels(outcome).inc()

    # -- introspection ------------------------------------------------------
    def stats(self) -> dict[str, Any]:
        with self._stats_lock:
            hits, spilled = self._hits, self._spilled_hits
            misses = self._misses
            parked, spills = self._parked_total, self._spills
            migrations = self._migrations_in
        lookups = hits + spilled + misses
        return {
            "enabled": self.enabled,
            "host_conversations": len(self.host),
            "disk_conversations": len(self.disk),
            "host_bytes": self.host.total_bytes,
            "disk_bytes": self.disk.total_bytes,
            "hits": hits,
            "spilled_hits": spilled,
            "misses": misses,
            "hit_rate": round((hits + spilled) / lookups, 4) if lookups else 0.0,
            "parked_total": parked,
            "spills": spills,
            "migrations_in": migrations,
        }

    def parked_page_count(self, model_id: str | None = None) -> int:
        """Pages currently parked (host tier only — disk entries are opaque
        blobs). Feeds the conservation census's parked-page extension."""
        total = 0
        for key, entry in self.host.items_lru_first():
            if model_id is not None and key[0] != str(model_id):
                continue
            total += int(entry.payload.pages_k.shape[1])
        return total

    @property
    def total_bytes(self) -> int:
        return self.host.total_bytes + self.disk.total_bytes

    def __len__(self) -> int:
        return len(self.host) + len(self.disk)

    def _update_gauges(self) -> None:
        host_b = float(self.host.total_bytes)
        disk_b = float(self.disk.total_bytes)
        n = len(self.host) + len(self.disk)
        if self.metrics is not None:
            self.metrics.kv_parked_bytes.labels("host").set(host_b)
            self.metrics.kv_parked_bytes.labels("disk").set(disk_b)
            self.metrics.kv_parked_conversations.set(n)
        RECORDER.note_conversation_kv(self.stats())

    def clear(self) -> None:
        self.host.clear()
        self.disk.clear()
        self._update_gauges()

    def close(self) -> None:
        self._closed.set()
        # plain clear, not spill: the process is going away
        self._spill = False
        self.host.clear()
        self.disk.clear()
        if self.disk_dir is not None:
            shutil.rmtree(self.disk_dir, ignore_errors=True)
        self._update_gauges()

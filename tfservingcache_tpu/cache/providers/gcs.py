"""GCS model provider over the JSON API (storage/v1).

No reference equivalent — the reference covers disk/S3/Azure (SURVEY.md §2
C8-C10); GCS is the natural third cloud on TPU-VMs (SURVEY.md §2 C9
"TPU-equiv" note) and follows the same provider pattern: paginated list under
``<basePath>/<model>/<version>/``, per-object download, size = sum of listed
sizes, health = 1-key list.

Auth: bearer token from (in order) ``GCS_ACCESS_TOKEN`` env, or the GCE/TPU-VM
metadata server's default service account. Anonymous when neither is
available (public buckets, test fakes).
"""

from __future__ import annotations

import json
import os
import time
import threading
import urllib.parse
import urllib.request

from tfservingcache_tpu.cache.providers.base import ProviderError
from tfservingcache_tpu.cache.providers.object_store import (
    ObjectInfo,
    ObjectStoreProvider,
    http_call,
    http_download,
)
from tfservingcache_tpu.utils.lockcheck import lockchecked

_METADATA_TOKEN_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/"
    "service-accounts/default/token"
)
_METADATA_RETRY_S = 60.0


@lockchecked
class GCSModelProvider(ObjectStoreProvider):
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_token": "_token_lock", "_token_expiry": "_token_lock"}

    def __init__(self, bucket: str, base_path: str = "", endpoint: str = "") -> None:
        super().__init__(base_path)
        if not bucket:
            raise ProviderError("gcs provider requires a bucket")
        self.bucket = bucket
        self._base_url = (endpoint or "https://storage.googleapis.com").rstrip("/")
        self._token = ""
        self._token_expiry = 0.0
        # negative-cache with TTL: off-GCP hosts stay anonymous without
        # paying a metadata probe per request, but one transient failure on a
        # real TPU-VM must not downgrade the provider to anonymous forever
        self._no_metadata_until = 0.0
        # load_model's download pool calls _bearer_token from several
        # threads: exactly ONE refreshes an expired token (the rest wait for
        # its result) — unsynchronized, all 8 would race the metadata server
        # and one transient failure could downgrade its siblings' downloads
        # of the same artifact to anonymous mid-flight
        self._token_lock = threading.Lock()

    # -- auth ----------------------------------------------------------------
    def _bearer_token(self) -> str:
        env = os.environ.get("GCS_ACCESS_TOKEN", "")
        if env:
            return env
        with self._token_lock:
            if self._token and time.monotonic() < self._token_expiry - 60:
                return self._token
            if time.monotonic() < self._no_metadata_until:
                return ""
            req = urllib.request.Request(
                _METADATA_TOKEN_URL, headers={"Metadata-Flavor": "Google"}
            )
            try:
                status, _, body = http_call(req, timeout=2.0, retries=1)
            except ProviderError:
                self._no_metadata_until = time.monotonic() + _METADATA_RETRY_S
                return ""  # not on GCP (or transient blip): anonymous for a while
            if status != 200:
                # negative-cache non-200 too (e.g. 404 when the instance has
                # no default service account): without it every list page and
                # object download would serially repeat the metadata
                # round-trip
                self._no_metadata_until = time.monotonic() + _METADATA_RETRY_S
                return ""
            tok = json.loads(body)
            self._token = tok.get("access_token", "")
            self._token_expiry = time.monotonic() + float(tok.get("expires_in", 0))
            return self._token

    def _request(self, url: str) -> urllib.request.Request:
        req = urllib.request.Request(url)
        token = self._bearer_token()
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        return req

    # -- ObjectStoreProvider primitives -------------------------------------
    def _list_page(
        self, prefix: str, delimiter: str, marker: str, max_keys: int = 0,
        timeout: float = 30.0, retries: int = 3,
    ) -> tuple[list[ObjectInfo], list[str], str]:
        params = {
            "prefix": prefix,
            "fields": "items(name,size),prefixes,nextPageToken",
        }
        if delimiter:
            params["delimiter"] = delimiter
        if marker:
            params["pageToken"] = marker
        if max_keys:
            params["maxResults"] = str(max_keys)
        url = (
            f"{self._base_url}/storage/v1/b/{urllib.parse.quote(self.bucket)}/o"
            f"?{urllib.parse.urlencode(sorted(params.items()))}"
        )
        status, _, body = http_call(self._request(url), timeout=timeout, retries=retries)
        if status != 200:
            raise ProviderError(f"gcs list failed: HTTP {status}: {body[:300]!r}")
        data = json.loads(body)
        objects = [
            ObjectInfo(key=item["name"], size=int(item.get("size", 0)))
            for item in data.get("items", [])
        ]
        prefixes = list(data.get("prefixes", []))
        return objects, prefixes, data.get("nextPageToken", "")

    def _download(self, key: str, dest_path: str) -> None:
        url = (
            f"{self._base_url}/storage/v1/b/{urllib.parse.quote(self.bucket)}/o/"
            f"{urllib.parse.quote(key, safe='')}?alt=media"
        )
        http_download(lambda: self._request(url), dest_path)

"""Disk model provider.

Reference equivalent: pkg/cachemanager/modelproviders/diskmodelprovider/
diskmodelprovider.go. Semantics kept: version directories match by numeric
parse so ``000000042`` serves version 42 (diskmodelprovider.go:46-69).
Fixed: ``model_size`` is the recursive tree size, not a dir stat
(SURVEY.md §7 quirk list).
"""

from __future__ import annotations

import os
import shutil

from tfservingcache_tpu.cache.disk_cache import dir_size_bytes
from tfservingcache_tpu.cache.providers.base import (
    STREAM_META_FILES,
    ModelNotFoundError,
    ModelProvider,
    ProviderError,
    _notify_file,
    atomic_dest,
)
from tfservingcache_tpu.types import Model, ModelId


class DiskModelProvider(ModelProvider):
    def __init__(self, base_dir: str) -> None:
        self.base_dir = os.path.abspath(base_dir)

    def _find_src_path(self, name: str, version: int) -> str:
        """Numeric version-dir matching (reference findSrcPathForModel,
        diskmodelprovider.go:46-69)."""
        model_dir = os.path.join(self.base_dir, name)
        if not os.path.isdir(model_dir):
            raise ModelNotFoundError(f"model dir not found: {model_dir}")
        for entry in sorted(os.listdir(model_dir)):
            full = os.path.join(model_dir, entry)
            if not os.path.isdir(full):
                continue
            try:
                if int(entry) == version:
                    return full
            except ValueError:
                continue
        raise ModelNotFoundError(f"version {version} of model {name!r} not found in {model_dir}")

    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        src = self._find_src_path(name, version)
        with atomic_dest(dest_dir) as tmp:
            shutil.copytree(src, tmp)
        return Model(
            identifier=ModelId(name, version),
            path=dest_dir,
            size_on_disk=dir_size_bytes(dest_dir),
        )

    def load_model_streaming(
        self, name: str, version: int, dest_dir: str, on_file=None
    ) -> Model:
        """File-by-file copy, metadata first, announcing each file as it
        lands — model.json reaches the runtime's precompile hook while
        params.bin is still copying. Same atomic-staging discipline as
        ``load_model``; without a callback that simpler path is used."""
        if on_file is None:
            return self.load_model(name, version, dest_dir)
        src = self._find_src_path(name, version)
        with atomic_dest(dest_dir) as tmp:
            rels = []
            for root, _dirs, files in os.walk(src):
                for fn in files:
                    full = os.path.join(root, fn)
                    rels.append(os.path.relpath(full, src))
            rels.sort(key=lambda r: (os.path.basename(r) not in STREAM_META_FILES, r))
            for rel in rels:
                local = os.path.join(tmp, rel)
                os.makedirs(os.path.dirname(local), exist_ok=True)
                shutil.copy2(os.path.join(src, rel), local)
                _notify_file(on_file, rel, local)
        return Model(
            identifier=ModelId(name, version),
            path=dest_dir,
            size_on_disk=dir_size_bytes(dest_dir),
        )

    def list_versions(self, name: str) -> list[int]:
        """All numeric version dirs, ascending (zero-padded names collapse to
        their numeric value, diskmodelprovider.go:46-69 semantics)."""
        model_dir = os.path.join(self.base_dir, name)
        if not os.path.isdir(model_dir):
            raise ModelNotFoundError(f"model dir not found: {model_dir}")
        versions = set()
        for entry in os.listdir(model_dir):
            try:
                if os.path.isdir(os.path.join(model_dir, entry)):
                    versions.add(int(entry))
            except ValueError:
                continue
        if not versions:
            raise ModelNotFoundError(f"no versions of model {name!r} in {model_dir}")
        return sorted(versions)

    def model_size(self, name: str, version: int) -> int:
        return dir_size_bytes(self._find_src_path(name, version))

    def check(self) -> None:
        """The reference's disk provider is always-healthy
        (diskmodelprovider.go:85-88); here at least require the root to exist."""
        if not os.path.isdir(self.base_dir):
            raise ProviderError(f"provider base dir missing: {self.base_dir}")

"""Shared machinery for object-store model providers (S3 / GCS / Azure Blob).

Reference equivalents: pkg/cachemanager/modelproviders/s3modelprovider/
s3modelprovider.go and .../azblobmodelprovider/azblobmodelprovider.go. Both
follow the same pattern (SURVEY.md §2 C9/C10): paginated list of every object
under ``<basePath>/<model>/<version>/`` + per-object download
(s3modelprovider.go:124-159 modelObjectApply), ``model_size`` as the sum of
listed object sizes (s3modelprovider.go:108-122), health = a 1-key list
(s3modelprovider.go:172-181), and an error when the listing comes back empty
(azblobmodelprovider.go:157-159). That pattern is factored here once; the
backends only supply one page of listing and one object download.

The cloud SDKs (boto3 / google-cloud-storage / azure-storage-blob) are not
part of this image, so the backends speak the stores' plain HTTP APIs with
stdlib urllib — which also makes them testable against in-process fake
servers, unlike the reference's SDK-bound providers (SURVEY.md §4: "S3/azBlob
providers ... no fakes").
"""

from __future__ import annotations

import abc
import os
import shutil
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Callable, Iterator

from tfservingcache_tpu.cache.providers.base import (
    STREAM_META_FILES,
    ModelNotFoundError,
    ModelProvider,
    ProviderError,
    _notify_file,
    atomic_dest,
)
from tfservingcache_tpu.types import Model, ModelId
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("providers.objectstore")

_RETRIES = 3
_RETRY_BACKOFF_S = 0.25
# concurrent object downloads per artifact fetch (the reference is
# sequential; see load_model)
_DOWNLOAD_CONCURRENCY = 8


@dataclass(frozen=True)
class ObjectInfo:
    key: str
    size: int


def http_call(
    req: urllib.request.Request, timeout: float = 30.0, retries: int = _RETRIES
) -> tuple[int, dict[str, str], bytes]:
    """One HTTP round-trip with bounded retries on 5xx / connection errors.

    The reference leans on SDK-internal retry policy; a small explicit one
    keeps behavior observable.
    """
    last_err: Exception | None = None
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, dict(resp.headers.items()), resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            if e.code >= 500 and attempt + 1 < retries:
                last_err = e
            else:
                return e.code, dict(e.headers.items()), body
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
            last_err = e
        if attempt + 1 < retries:  # no pointless backoff after the final attempt
            time.sleep(_RETRY_BACKOFF_S * (2**attempt))
    raise ProviderError(f"object store unreachable after {retries} attempts: {last_err}")


def http_download(
    make_req: Callable[[], urllib.request.Request],
    dest_path: str,
    timeout: float = 120.0,
    retries: int = _RETRIES,
) -> None:
    """Stream a GET response straight to ``dest_path`` (multi-GB artifacts
    must not transit host RAM whole). ``make_req`` builds a fresh request per
    attempt so time-sensitive auth headers (SigV4 x-amz-date, Azure
    x-ms-date) stay valid across retries."""
    last_err: Exception | None = None
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(make_req(), timeout=timeout) as resp:
                with open(dest_path, "wb") as fh:
                    shutil.copyfileobj(resp, fh, length=1 << 20)
                return
        except urllib.error.HTTPError as e:
            body = e.read()[:300]
            if e.code >= 500 and attempt + 1 < retries:
                last_err = e
            else:
                raise ProviderError(f"download failed: HTTP {e.code}: {body!r}") from e
        except (urllib.error.URLError, ConnectionError, TimeoutError, OSError) as e:
            last_err = e
        if attempt + 1 < retries:
            time.sleep(_RETRY_BACKOFF_S * (2**attempt))
    raise ProviderError(f"download failed after {retries} attempts: {last_err}")


class ObjectStoreProvider(ModelProvider):
    """Template for providers over a flat key/value object store.

    Key layout mirrors the reference (s3modelprovider.go:161-170):
    ``<base_path>/<model>/<version>/<artifact files...>``. Like the disk
    provider (and diskmodelprovider.go:46-69), the version segment matches by
    numeric value, so a store dir ``000000042`` serves version 42.
    """

    def __init__(self, base_path: str) -> None:
        self.base_path = base_path.strip("/")

    # -- backend primitives -------------------------------------------------
    @abc.abstractmethod
    def _list_page(
        self,
        prefix: str,
        delimiter: str,
        marker: str,
        max_keys: int = 0,
        timeout: float = 30.0,
        retries: int = _RETRIES,
    ) -> tuple[list[ObjectInfo], list[str], str]:
        """One page of listing -> (objects, common-prefixes, next-marker).
        Empty next-marker = last page; ``max_keys`` 0 = backend default."""

    @abc.abstractmethod
    def _download(self, key: str, dest_path: str) -> None:
        """Fetch one object to a local file."""

    # -- shared listing helpers ---------------------------------------------
    def _list_all(self, prefix: str, delimiter: str = "") -> Iterator[tuple[ObjectInfo | None, str | None]]:
        """Iterate every (object, None) and (None, common_prefix) under
        ``prefix`` across pages (reference pagination loops
        s3modelprovider.go:130-158 / azblobmodelprovider.go:125-162)."""
        marker = ""
        while True:
            objects, prefixes, marker = self._list_page(prefix, delimiter, marker)
            for o in objects:
                yield o, None
            for p in prefixes:
                yield None, p
            if not marker:
                return

    def _prefix_for(self, name: str, version: int) -> str:
        parts = [p for p in (self.base_path, name) if p]
        return "/".join(parts) + f"/{self._resolve_version_dir(name, version)}/"

    def _resolve_version_dir(self, name: str, version: int) -> str:
        """Find the stored version-directory segment whose numeric value equals
        ``version`` (zero-padded dirs serve their numeric version, like the
        disk provider). Exact match short-circuits without a list call."""
        base = "/".join(p for p in (self.base_path, name) if p) + "/"
        exact_probe, _, _ = self._list_page(f"{base}{version}/", "", "", max_keys=1)
        if exact_probe:
            return str(version)
        for _, common in self._list_all(base, delimiter="/"):
            if common is None:
                continue
            seg = common[len(base):].strip("/")
            try:
                if int(seg) == version:
                    return seg
            except ValueError:
                continue
        raise ModelNotFoundError(f"version {version} of model {name!r} not found under {base!r}")

    def _list_model_objects(self, name: str, version: int) -> tuple[list[ObjectInfo], str]:
        """-> (objects, resolved prefix). The prefix is resolved exactly once —
        resolution may itself cost a paginated listing for zero-padded version
        dirs, so callers must not re-derive it."""
        prefix = self._prefix_for(name, version)
        objects = [o for o, _ in self._list_all(prefix) if o is not None]
        if not objects:
            # reference azblobmodelprovider.go:157-159: zero blobs is an error
            raise ModelNotFoundError(f"no objects under {prefix!r}")
        return objects, prefix

    # -- ModelProvider interface --------------------------------------------
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        return self._load(name, version, dest_dir, None)

    def load_model_streaming(
        self, name: str, version: int, dest_dir: str, on_file=None
    ) -> Model:
        """Concurrent fetch with metadata objects submitted first and
        ``on_file`` fired per landed object (from this calling thread, in
        completion order) — model.json typically completes while params.bin
        is still streaming, which is the fetch/compile overlap the
        pipelined cold load feeds on."""
        return self._load(name, version, dest_dir, on_file)

    def _load(self, name: str, version: int, dest_dir: str, on_file) -> Model:
        """Fetch every object of the artifact, CONCURRENTLY (the reference
        downloads sequentially, s3modelprovider.go:124-159 — per-object
        round-trip latency then dominates a many-file artifact; a bounded
        pool overlaps them, which is where the cold-miss seconds live for
        object-store deployments). ``_download`` impls are stateless
        (urllib + per-request auth), so calls are thread-safe."""
        objects, prefix = self._list_model_objects(name, version)
        total = 0
        with atomic_dest(dest_dir) as tmp:
            work: list[tuple[ObjectInfo, str, str]] = []
            for obj in objects:
                rel = obj.key[len(prefix):]
                if not rel or rel.endswith("/"):
                    continue  # zero-byte "directory" placeholder objects
                local = os.path.join(tmp, *rel.split("/"))
                os.makedirs(os.path.dirname(local), exist_ok=True)
                work.append((obj, local, rel))
            # metadata first: with a streaming consumer the precompile hint
            # should leave as early as the store allows (harmless otherwise)
            work.sort(key=lambda w: w[2].rsplit("/", 1)[-1] not in STREAM_META_FILES)
            if len(work) <= 1:
                for obj, local, rel in work:
                    self._download(obj.key, local)
                    total += obj.size
                    _notify_file(on_file, rel, local)
            else:
                from concurrent.futures import ThreadPoolExecutor, as_completed

                # NOT a with-block: the context manager's __exit__ joins all
                # in-flight downloads, which would hold the fail-fast raise
                # (and the cold-load deadline) hostage to the slowest
                # transfer's retries. On error the queued futures are
                # cancelled and the raise propagates immediately; abandoned
                # in-flight workers hit ENOENT once atomic_dest removes the
                # staging dir and die into their unread futures (a residual
                # .tmp-* dir from that race is reaped by the disk cache's
                # restart recovery).
                pool = ThreadPoolExecutor(
                    max_workers=min(_DOWNLOAD_CONCURRENCY, len(work)),
                    thread_name_prefix="tpusc-fetch",
                )
                try:
                    futures = {
                        pool.submit(self._download, obj.key, local): (obj, local, rel)
                        for obj, local, rel in work
                    }
                    for f in as_completed(futures):
                        try:
                            f.result()
                            obj, local, rel = futures[f]
                            total += obj.size
                            _notify_file(on_file, rel, local)
                        except Exception as e:  # noqa: BLE001
                            # fail fast: a multi-GB artifact must not keep
                            # streaming its other objects (egress + the cold
                            # deadline) after one of them already failed.
                            # atomic_dest discards the staging dir on raise.
                            raise ProviderError(
                                f"object download failed (remaining "
                                f"downloads cancelled): {e}"
                            ) from e
                finally:
                    pool.shutdown(wait=False, cancel_futures=True)
        log.info("downloaded %s/%d: %d objects, %d bytes", name, version, len(objects), total)
        return Model(
            identifier=ModelId(name, version), path=dest_dir, size_on_disk=total
        )

    def model_size(self, name: str, version: int) -> int:
        """Sum of listed object sizes (reference s3modelprovider.go:108-122)."""
        objects, _ = self._list_model_objects(name, version)
        return sum(o.size for o in objects)

    def list_versions(self, name: str) -> list[int]:
        base = "/".join(p for p in (self.base_path, name) if p) + "/"
        versions = set()
        for _, common in self._list_all(base, delimiter="/"):
            if common is None:
                continue
            seg = common[len(base):].strip("/")
            try:
                versions.add(int(seg))
            except ValueError:
                continue
        if not versions:
            raise ModelNotFoundError(f"no versions of model {name!r} under {base!r}")
        return sorted(versions)

    def check(self) -> None:
        """Health probe = 1-key list, bounded like the reference's
        10s-timeout health list (s3modelprovider.go:172-181 /
        azblobmodelprovider.go:174-186) — a black-holed endpoint must fail
        the probe in ~10s, not stall a liveness loop for minutes of retries."""
        self._list_page(
            self.base_path + "/" if self.base_path else "", "", "",
            max_keys=1, timeout=10.0, retries=1,
        )

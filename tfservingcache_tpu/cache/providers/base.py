"""Storage abstraction.

Reference equivalent: the 3-method ``ModelProvider`` interface
(pkg/cachemanager/modelprovider.go:3-7) — deliberately kept this narrow so
fakes stay trivial (SURVEY.md §4 lesson).
"""

from __future__ import annotations

import abc
import os
import shutil
from contextlib import contextmanager
from typing import Iterator

from tfservingcache_tpu.types import Model


@contextmanager
def atomic_dest(dest_dir: str) -> Iterator[str]:
    """Stage provider writes in a UNIQUE ``<dest>.tmp-<pid>-<rand>`` dir and
    atomically rename on success, so a crash mid-fetch never leaves a
    half-written artifact at the final path (a partial tree would be
    recovered as a complete model after restart). All providers write
    through this.

    The random suffix matters: two fetches of the same model can overlap in
    one process — a cold-load deadline releases the singleflight lock while
    its orphaned worker keeps downloading, and a client retry starts a second
    fetch (cache/manager.py _with_deadline). Per-call staging dirs keep the
    writers fully independent; whoever finishes later wins the final rename
    with a complete tree either way."""
    import uuid

    tmp = f"{dest_dir}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    os.makedirs(os.path.dirname(tmp) or ".", exist_ok=True)
    try:
        yield tmp
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(dest_dir):
        shutil.rmtree(dest_dir, ignore_errors=True)
    try:
        os.replace(tmp, dest_dir)
    except OSError:
        if os.path.isdir(dest_dir):
            # a concurrent fetch of the same artifact won the rename between
            # our rmtree and replace; its tree is complete — discard ours
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise


class ProviderError(Exception):
    pass


class ModelNotFoundError(ProviderError):
    pass


# Files a streaming fetch should land (and announce) FIRST: the artifact
# metadata is enough for the runtime to start compiling the family
# executable while the parameter bytes are still in flight.
STREAM_META_FILES = ("model.json",)


def _notify_file(on_file, rel: str, local_path: str) -> None:
    """Invoke a streaming callback; callbacks are advisory and must never
    break the fetch they ride on."""
    if on_file is None:
        return
    try:
        on_file(rel, local_path)
    except Exception:  # noqa: BLE001 - advisory hook
        import logging

        logging.getLogger("tpusc.providers").debug(
            "streaming on_file callback failed for %s", rel, exc_info=True
        )


class ModelProvider(abc.ABC):
    @abc.abstractmethod
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        """Fetch ``<name>/<version>`` into ``dest_dir`` and return the Model."""

    def load_model_streaming(
        self, name: str, version: int, dest_dir: str, on_file=None
    ) -> Model:
        """Like ``load_model``, additionally invoking
        ``on_file(rel_path, local_path)`` as each artifact file finishes
        landing — metadata files (STREAM_META_FILES) as early as the backend
        allows, so the pipelined cold load can overlap compilation with the
        rest of the fetch. The callback may fire from fetch worker threads
        and must be cheap; exceptions from it are swallowed.

        This default fetches fully and only then fires the callbacks (no
        overlap, but identical semantics) — providers that can genuinely
        stream override it."""
        model = self.load_model(name, version, dest_dir)
        if on_file is not None:
            for root, _dirs, files in os.walk(model.path):
                for fn in sorted(files, key=lambda f: f not in STREAM_META_FILES):
                    full = os.path.join(root, fn)
                    _notify_file(on_file, os.path.relpath(full, model.path), full)
        return model

    @abc.abstractmethod
    def model_size(self, name: str, version: int) -> int:
        """Size in bytes of the stored artifact (used for pre-eviction)."""

    @abc.abstractmethod
    def check(self) -> None:
        """Health probe; raise ProviderError when the backing store is down."""

    def list_versions(self, name: str) -> list[int]:
        """All stored versions of ``name``, ascending (backs the reload-config
        ServableVersionPolicy latest/all shapes — reference forwards the full
        policy to TF Serving, servingcontroller.go:159-187). Providers that
        can list versions must override this."""
        raise ModelNotFoundError(
            f"provider {type(self).__name__} cannot list versions for {name!r}"
        )

    def latest_version(self, name: str) -> int:
        """Highest stored version of ``name`` (serves requests that omit the
        version)."""
        versions = self.list_versions(name)
        if not versions:
            raise ModelNotFoundError(f"no versions of model {name!r}")
        return max(versions)

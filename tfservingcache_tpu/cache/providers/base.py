"""Storage abstraction.

Reference equivalent: the 3-method ``ModelProvider`` interface
(pkg/cachemanager/modelprovider.go:3-7) — deliberately kept this narrow so
fakes stay trivial (SURVEY.md §4 lesson).
"""

from __future__ import annotations

import abc
import os
import shutil
from contextlib import contextmanager
from typing import Iterator

from tfservingcache_tpu.types import Model


@contextmanager
def atomic_dest(dest_dir: str) -> Iterator[str]:
    """Stage provider writes in ``<dest>.tmp-<pid>`` and atomically rename on
    success, so a crash mid-fetch never leaves a half-written artifact at the
    final path (a partial tree would be recovered as a complete model after
    restart). All providers write through this."""
    tmp = f"{dest_dir}.tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(os.path.dirname(tmp) or ".", exist_ok=True)
    try:
        yield tmp
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(dest_dir):
        shutil.rmtree(dest_dir)
    os.replace(tmp, dest_dir)


class ProviderError(Exception):
    pass


class ModelNotFoundError(ProviderError):
    pass


class ModelProvider(abc.ABC):
    @abc.abstractmethod
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        """Fetch ``<name>/<version>`` into ``dest_dir`` and return the Model."""

    @abc.abstractmethod
    def model_size(self, name: str, version: int) -> int:
        """Size in bytes of the stored artifact (used for pre-eviction)."""

    @abc.abstractmethod
    def check(self) -> None:
        """Health probe; raise ProviderError when the backing store is down."""

    def latest_version(self, name: str) -> int:
        """Highest stored version of ``name`` (serves requests that omit the
        version). Providers that can list versions must override this."""
        raise ModelNotFoundError(
            f"provider {type(self).__name__} cannot resolve a latest version for {name!r}"
        )

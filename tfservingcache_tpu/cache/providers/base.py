"""Storage abstraction.

Reference equivalent: the 3-method ``ModelProvider`` interface
(pkg/cachemanager/modelprovider.go:3-7) — deliberately kept this narrow so
fakes stay trivial (SURVEY.md §4 lesson).
"""

from __future__ import annotations

import abc

from tfservingcache_tpu.types import Model


class ProviderError(Exception):
    pass


class ModelNotFoundError(ProviderError):
    pass


class ModelProvider(abc.ABC):
    @abc.abstractmethod
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        """Fetch ``<name>/<version>`` into ``dest_dir`` and return the Model."""

    @abc.abstractmethod
    def model_size(self, name: str, version: int) -> int:
        """Size in bytes of the stored artifact (used for pre-eviction)."""

    @abc.abstractmethod
    def check(self) -> None:
        """Health probe; raise ProviderError when the backing store is down."""

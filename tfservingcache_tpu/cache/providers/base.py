"""Storage abstraction.

Reference equivalent: the 3-method ``ModelProvider`` interface
(pkg/cachemanager/modelprovider.go:3-7) — deliberately kept this narrow so
fakes stay trivial (SURVEY.md §4 lesson).
"""

from __future__ import annotations

import abc
import os
import shutil
from contextlib import contextmanager
from typing import Iterator

from tfservingcache_tpu.types import Model


@contextmanager
def atomic_dest(dest_dir: str) -> Iterator[str]:
    """Stage provider writes in a UNIQUE ``<dest>.tmp-<pid>-<rand>`` dir and
    atomically rename on success, so a crash mid-fetch never leaves a
    half-written artifact at the final path (a partial tree would be
    recovered as a complete model after restart). All providers write
    through this.

    The random suffix matters: two fetches of the same model can overlap in
    one process — a cold-load deadline releases the singleflight lock while
    its orphaned worker keeps downloading, and a client retry starts a second
    fetch (cache/manager.py _with_deadline). Per-call staging dirs keep the
    writers fully independent; whoever finishes later wins the final rename
    with a complete tree either way."""
    import uuid

    tmp = f"{dest_dir}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    os.makedirs(os.path.dirname(tmp) or ".", exist_ok=True)
    try:
        yield tmp
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if os.path.exists(dest_dir):
        shutil.rmtree(dest_dir, ignore_errors=True)
    try:
        os.replace(tmp, dest_dir)
    except OSError:
        if os.path.isdir(dest_dir):
            # a concurrent fetch of the same artifact won the rename between
            # our rmtree and replace; its tree is complete — discard ours
            shutil.rmtree(tmp, ignore_errors=True)
        else:
            raise


class ProviderError(Exception):
    pass


class ModelNotFoundError(ProviderError):
    pass


class ModelProvider(abc.ABC):
    @abc.abstractmethod
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        """Fetch ``<name>/<version>`` into ``dest_dir`` and return the Model."""

    @abc.abstractmethod
    def model_size(self, name: str, version: int) -> int:
        """Size in bytes of the stored artifact (used for pre-eviction)."""

    @abc.abstractmethod
    def check(self) -> None:
        """Health probe; raise ProviderError when the backing store is down."""

    def list_versions(self, name: str) -> list[int]:
        """All stored versions of ``name``, ascending (backs the reload-config
        ServableVersionPolicy latest/all shapes — reference forwards the full
        policy to TF Serving, servingcontroller.go:159-187). Providers that
        can list versions must override this."""
        raise ModelNotFoundError(
            f"provider {type(self).__name__} cannot list versions for {name!r}"
        )

    def latest_version(self, name: str) -> int:
        """Highest stored version of ``name`` (serves requests that omit the
        version)."""
        versions = self.list_versions(name)
        if not versions:
            raise ModelNotFoundError(f"no versions of model {name!r}")
        return max(versions)

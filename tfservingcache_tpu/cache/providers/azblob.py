"""Azure Blob model provider over the Blob service REST API with Shared Key
authentication.

Reference equivalent: pkg/cachemanager/modelproviders/azblobmodelprovider/
azblobmodelprovider.go (C10 in SURVEY.md §2): marker-paginated
ListBlobsFlatSegment under the prefix (:125-162), shared-key credential
(:32-58), error on zero blobs (:157-159), 10s-timeout health list (:174-186).
The azure-storage-blob-go SDK is replaced by stdlib HTTP + the Shared Key
signature scheme (HMAC-SHA256 over canonicalized headers/resource with the
base64-decoded account key).
"""

from __future__ import annotations

import base64
import email.utils
import hashlib
import hmac
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from tfservingcache_tpu.cache.providers.base import ProviderError
from tfservingcache_tpu.cache.providers.object_store import (
    ObjectInfo,
    ObjectStoreProvider,
    http_call,
    http_download,
)

_API_VERSION = "2020-10-02"


def shared_key_auth(
    method: str,
    url: str,
    account_name: str,
    account_key_b64: str,
    headers: dict[str, str],
) -> str:
    """Azure Storage Shared Key signature for a bodyless request."""
    parsed = urllib.parse.urlsplit(url)
    canon_headers = "".join(
        f"{k}:{v}\n"
        for k, v in sorted(headers.items())
        if k.startswith("x-ms-")
    )
    canon_resource = f"/{account_name}{parsed.path or '/'}"
    for k, v in sorted(urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)):
        canon_resource += f"\n{k.lower()}:{v}"
    string_to_sign = "\n".join(
        [
            method,
            "",  # Content-Encoding
            "",  # Content-Language
            "",  # Content-Length (empty for 0)
            "",  # Content-MD5
            "",  # Content-Type
            "",  # Date (x-ms-date used instead)
            "",  # If-Modified-Since
            "",  # If-Match
            "",  # If-None-Match
            "",  # If-Unmodified-Since
            "",  # Range
        ]
    ) + "\n" + canon_headers + canon_resource
    key = base64.b64decode(account_key_b64)
    sig = base64.b64encode(
        hmac.new(key, string_to_sign.encode(), hashlib.sha256).digest()
    ).decode()
    return f"SharedKey {account_name}:{sig}"


class AZBlobModelProvider(ObjectStoreProvider):
    def __init__(
        self,
        account_name: str,
        account_key: str,
        container: str,
        base_path: str = "",
        endpoint: str = "",
    ) -> None:
        super().__init__(base_path)
        if not container:
            raise ProviderError("azblob provider requires a container")
        self.account_name = account_name
        self.account_key = account_key
        self.container = container
        host = (endpoint or f"https://{account_name}.blob.core.windows.net").rstrip("/")
        self._base_url = f"{host}/{container}"

    def _request(self, url: str) -> urllib.request.Request:
        headers = {
            "x-ms-date": email.utils.formatdate(usegmt=True),
            "x-ms-version": _API_VERSION,
        }
        if self.account_name and self.account_key:
            headers["Authorization"] = shared_key_auth(
                "GET", url, self.account_name, self.account_key, headers
            )
        return urllib.request.Request(url, headers=headers)

    # -- ObjectStoreProvider primitives -------------------------------------
    def _list_page(
        self, prefix: str, delimiter: str, marker: str, max_keys: int = 0,
        timeout: float = 10.0, retries: int = 3,
    ) -> tuple[list[ObjectInfo], list[str], str]:
        params = {"restype": "container", "comp": "list", "prefix": prefix}
        if delimiter:
            params["delimiter"] = delimiter
        if marker:
            params["marker"] = marker
        if max_keys:
            params["maxresults"] = str(max_keys)
        url = f"{self._base_url}?{urllib.parse.urlencode(sorted(params.items()))}"
        status, _, body = http_call(self._request(url), timeout=timeout, retries=retries)
        if status != 200:
            raise ProviderError(f"azblob list failed: HTTP {status}: {body[:300]!r}")
        root = ET.fromstring(body)
        objects = []
        prefixes = []
        blobs = root.find("Blobs")
        if blobs is not None:
            for blob in blobs.findall("Blob"):
                name = blob.findtext("Name", "")
                size = int(blob.findtext("Properties/Content-Length", "0"))
                objects.append(ObjectInfo(key=name, size=size))
            for bp in blobs.findall("BlobPrefix"):
                prefixes.append(bp.findtext("Name", ""))
        next_marker = root.findtext("NextMarker", "") or ""
        return objects, prefixes, next_marker

    def _download(self, key: str, dest_path: str) -> None:
        url = f"{self._base_url}/{urllib.parse.quote(key)}"
        http_download(lambda: self._request(url), dest_path)

from tfservingcache_tpu.cache.providers.base import ModelProvider, ProviderError

__all__ = ["ModelProvider", "ProviderError", "create_provider"]


def create_provider(cfg) -> "ModelProvider":
    """Factory by config type (reference CreateModelProvider,
    cmd/taskhandler/main.go:152-187)."""
    from tfservingcache_tpu.config import ModelProviderConfig

    assert isinstance(cfg, ModelProviderConfig)
    t = cfg.type.lower()
    try:
        if t in ("disk", "diskprovider"):
            from tfservingcache_tpu.cache.providers.disk import DiskModelProvider

            return DiskModelProvider(cfg.base_dir)
        if t in ("s3", "s3provider"):
            from tfservingcache_tpu.cache.providers.s3 import S3ModelProvider

            return S3ModelProvider(
                bucket=cfg.bucket, base_path=cfg.base_path, region=cfg.region, endpoint=cfg.endpoint
            )
        if t in ("gcs", "gcsprovider"):
            from tfservingcache_tpu.cache.providers.gcs import GCSModelProvider

            return GCSModelProvider(
                bucket=cfg.bucket, base_path=cfg.base_path, endpoint=cfg.endpoint
            )
        if t in ("azblob", "azblobprovider"):
            from tfservingcache_tpu.cache.providers.azblob import AZBlobModelProvider

            return AZBlobModelProvider(
                account_name=cfg.account_name,
                account_key=cfg.account_key,
                container=cfg.container,
                base_path=cfg.base_path,
                endpoint=cfg.endpoint,
            )
    except ImportError as e:
        raise ProviderError(
            f"model provider {cfg.type!r} is unavailable in this build: {e}"
        ) from e
    raise ValueError(f"unknown model provider type: {cfg.type!r}")

"""S3 model provider over the plain S3 REST API with SigV4 request signing.

Reference equivalent: pkg/cachemanager/modelproviders/s3modelprovider/
s3modelprovider.go (C9 in SURVEY.md §2): paginated ListObjectsV2 under
``<basePath>/<model>/<version>/`` + per-object GET (:51-159), size = sum of
listed sizes (:108-122), health = 1-key list (:172-181). The aws-sdk-go
dependency is replaced by a stdlib HTTP client + hand-rolled AWS Signature
Version 4 (hmac/hashlib), which works against AWS, MinIO, and the in-process
fake used in tests.

Credentials: ``AWS_ACCESS_KEY_ID`` / ``AWS_SECRET_ACCESS_KEY``
[/ ``AWS_SESSION_TOKEN``] env vars; unsigned anonymous requests when unset
(public buckets, test fakes).
"""

from __future__ import annotations

import datetime
import hashlib
import hmac
import os
import urllib.parse
import urllib.request
import xml.etree.ElementTree as ET

from tfservingcache_tpu.cache.providers.base import ProviderError
from tfservingcache_tpu.cache.providers.object_store import (
    ObjectInfo,
    ObjectStoreProvider,
    http_call,
    http_download,
)

_EMPTY_SHA256 = hashlib.sha256(b"").hexdigest()


def _hmac(key: bytes, msg: str) -> bytes:
    return hmac.new(key, msg.encode(), hashlib.sha256).digest()


def sigv4_headers(
    method: str,
    url: str,
    region: str,
    access_key: str,
    secret_key: str,
    session_token: str = "",
    service: str = "s3",
    now: datetime.datetime | None = None,
) -> dict[str, str]:
    """AWS Signature Version 4 for a bodyless request.

    Canonical request -> string-to-sign -> derived signing key, per the S3
    REST authentication spec. Query params are signed in sorted order;
    payload hash is the empty-body constant (all our calls are GETs).
    """
    parsed = urllib.parse.urlsplit(url)
    now = now or datetime.datetime.now(datetime.timezone.utc)
    amz_date = now.strftime("%Y%m%dT%H%M%SZ")
    datestamp = now.strftime("%Y%m%d")

    # callers pass an already-percent-encoded URL; re-quoting here would
    # double-encode ('%20' -> '%2520') and sign a different path than S3
    # canonicalizes, failing every key that needs escaping
    canonical_uri = parsed.path or "/"
    query_pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}={urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(query_pairs)
    )
    headers = {"host": parsed.netloc, "x-amz-content-sha256": _EMPTY_SHA256, "x-amz-date": amz_date}
    if session_token:
        headers["x-amz-security-token"] = session_token
    signed_names = ";".join(sorted(headers))
    canonical_headers = "".join(f"{k}:{headers[k]}\n" for k in sorted(headers))
    canonical_request = "\n".join(
        [method, canonical_uri, canonical_query, canonical_headers, signed_names, _EMPTY_SHA256]
    )
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    string_to_sign = "\n".join(
        [
            "AWS4-HMAC-SHA256",
            amz_date,
            scope,
            hashlib.sha256(canonical_request.encode()).hexdigest(),
        ]
    )
    k_date = _hmac(f"AWS4{secret_key}".encode(), datestamp)
    k_region = _hmac(k_date, region)
    k_service = _hmac(k_region, service)
    k_signing = _hmac(k_service, "aws4_request")
    signature = hmac.new(k_signing, string_to_sign.encode(), hashlib.sha256).hexdigest()
    headers["authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={access_key}/{scope}, "
        f"SignedHeaders={signed_names}, Signature={signature}"
    )
    del headers["host"]  # urllib sets Host itself; signing included it already
    return headers


class S3ModelProvider(ObjectStoreProvider):
    def __init__(
        self,
        bucket: str,
        base_path: str = "",
        region: str = "",
        endpoint: str = "",
    ) -> None:
        super().__init__(base_path)
        if not bucket:
            raise ProviderError("s3 provider requires a bucket")
        self.bucket = bucket
        self.region = region or os.environ.get("AWS_REGION", "us-east-1")
        # Custom endpoint (MinIO / test fake) uses path-style addressing;
        # bare AWS uses virtual-hosted style.
        if endpoint:
            self._base_url = f"{endpoint.rstrip('/')}/{bucket}"
        else:
            self._base_url = f"https://{bucket}.s3.{self.region}.amazonaws.com"
        self.access_key = os.environ.get("AWS_ACCESS_KEY_ID", "")
        self.secret_key = os.environ.get("AWS_SECRET_ACCESS_KEY", "")
        self.session_token = os.environ.get("AWS_SESSION_TOKEN", "")

    def _request(self, url: str) -> urllib.request.Request:
        req = urllib.request.Request(url)
        if self.access_key and self.secret_key:
            for k, v in sigv4_headers(
                "GET", url, self.region, self.access_key, self.secret_key, self.session_token
            ).items():
                req.add_header(k, v)
        return req

    # -- ObjectStoreProvider primitives -------------------------------------
    def _list_page(
        self, prefix: str, delimiter: str, marker: str, max_keys: int = 0,
        timeout: float = 30.0, retries: int = 3,
    ) -> tuple[list[ObjectInfo], list[str], str]:
        params = {"list-type": "2", "prefix": prefix}
        if delimiter:
            params["delimiter"] = delimiter
        if marker:
            params["continuation-token"] = marker
        if max_keys:
            params["max-keys"] = str(max_keys)
        url = f"{self._base_url}?{urllib.parse.urlencode(sorted(params.items()))}"
        status, _, body = http_call(self._request(url), timeout=timeout, retries=retries)
        if status != 200:
            raise ProviderError(f"s3 list failed: HTTP {status}: {body[:300]!r}")
        ns = {"s3": "http://s3.amazonaws.com/doc/2006-03-01/"}
        root = ET.fromstring(body)
        # tolerate fakes that omit the namespace
        def findall(tag: str):
            return root.findall(f"s3:{tag}", ns) or root.findall(tag)

        def text(el, tag: str, default: str = "") -> str:
            child = el.find(f"s3:{tag}", ns)
            if child is None:
                child = el.find(tag)
            return child.text if child is not None and child.text else default

        objects = [
            ObjectInfo(key=text(c, "Key"), size=int(text(c, "Size", "0")))
            for c in findall("Contents")
        ]
        prefixes = [text(c, "Prefix") for c in findall("CommonPrefixes")]
        truncated = (text(root, "IsTruncated", "false")).lower() == "true"
        next_marker = text(root, "NextContinuationToken") if truncated else ""
        return objects, prefixes, next_marker

    def _download(self, key: str, dest_path: str) -> None:
        url = f"{self._base_url}/{urllib.parse.quote(key)}"
        http_download(lambda: self._request(url), dest_path)

"""PeerProvider: source cold misses from warm peers before the store.

Fronts the configured provider (disk/s3/gcs/azblob) with the peer
param-distribution path (ISSUE 8 tentpole): when the fleet status plane
says another node holds the model at ``host``/``hbm`` residency, stream
its ``PackedModelEntry`` over FetchPackedModel (protocol/peer_transfer.py)
at cluster-internal wire speed instead of paying the object store's. Any
peer-path problem — refused stream, mid-stream disconnect, integrity
failure, timeout — logs loudly and falls back to the wrapped provider, so
the worst case is exactly the pre-PR8 cold miss, never a failed request.

Threading: CacheManager fetches run on worker threads, so this provider
uses SYNC grpc channels (one cached per peer target, pruned with
membership). The FleetView it consults lives on the router's event loop;
its dict reads are GIL-safe snapshots and ``note_forward`` is a pure
in-memory EWMA update — acceptable cross-thread by design (the same
relaxation the status plane already makes for piggybacked trailers).
"""

from __future__ import annotations

import os
import threading
import time

from tfservingcache_tpu.cache.providers.base import ModelProvider
from tfservingcache_tpu.types import Model, ModelId, NodeInfo
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.tracing import TRACER
from tfservingcache_tpu.utils.lockcheck import lockchecked

log = get_logger("peer_provider")

# fleet warmth tiers that make a peer a useful param source: host (2) means
# packed chunks are sitting in its DRAM; hbm (3) implies host on nodes with
# the tier enabled (inclusive downward)
_MIN_WARMTH = 2


@lockchecked
class PeerProvider(ModelProvider):
    """Decorator provider; constructed unbound (pass-through) by CacheNode
    and bound to the fleet by the Router once discovery is up."""

    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_channels": "_lock"}

    def __init__(
        self,
        inner: ModelProvider,
        chunk_bytes: int = 2 << 20,
        timeout_s: float = 60.0,
        max_message_bytes: int = 16 << 20,
    ) -> None:
        self.inner = inner
        self.chunk_bytes = int(chunk_bytes)
        self.timeout_s = float(timeout_s)
        self.max_message_bytes = int(max_message_bytes)
        self._fleet = None
        self._cluster = None
        self._self_idents: set[str] = set()
        self._lock = threading.Lock()
        self._channels: dict[str, object] = {}   # grpc target -> sync channel

    # -- binding ------------------------------------------------------------
    def bind_fleet(self, fleet, cluster, self_idents) -> None:
        """Arm the peer path: ``fleet`` is the router's FleetView,
        ``cluster`` the ClusterConnection (for member NodeInfo lookup),
        ``self_idents`` this host's own ring identities (never fetch from
        yourself). Until called, every fetch passes straight through."""
        self._fleet = fleet
        self._cluster = cluster
        self._self_idents = set(self_idents)

    def prune(self, nodes) -> None:
        """Membership-change hook: drop channels to departed peers."""
        live = {f"{n.host}:{n.grpc_port}" for n in nodes}
        with self._lock:
            for target in list(self._channels):
                if target not in live:
                    ch = self._channels.pop(target)
                    try:
                        ch.close()
                    except Exception:  # noqa: BLE001
                        pass

    # -- peer path ----------------------------------------------------------
    def _candidates(self, key: str) -> list[tuple[str, NodeInfo]]:
        fleet, cluster = self._fleet, self._cluster
        if fleet is None or cluster is None:
            return []
        scored: list[tuple[int, float, str, NodeInfo]] = []
        for ident, node in cluster._nodes_by_ident.items():
            if ident in self._self_idents:
                continue
            w = fleet.warmth(ident, key)
            if w < _MIN_WARMTH:
                continue
            scored.append((w, fleet.health(ident), ident, node))
        scored.sort(key=lambda t: (-t[0], -t[1]))
        return [(ident, node) for _, _, ident, node in scored]

    def _channel(self, node: NodeInfo):
        import grpc

        target = f"{node.host}:{node.grpc_port}"
        with self._lock:
            ch = self._channels.get(target)
            if ch is None:
                ch = grpc.insecure_channel(
                    target,
                    options=[
                        ("grpc.max_receive_message_length", self.max_message_bytes),
                        ("grpc.max_send_message_length", self.max_message_bytes),
                        ("grpc.initial_reconnect_backoff_ms", 100),
                        ("grpc.max_reconnect_backoff_ms", 5000),
                    ],
                )
                self._channels[target] = ch
            return ch

    def _try_peers(self, name: str, version: int, dest_dir: str, on_file) -> Model | None:
        """Attempt the peer path; None means fall back to the store."""
        import grpc

        from tfservingcache_tpu.cache.providers.base import atomic_dest
        from tfservingcache_tpu.protocol.peer_transfer import (
            PeerWireError,
            fetch_from_peer,
        )

        mid = ModelId(name, version)
        fleet = self._fleet
        metrics = getattr(fleet, "metrics", None)
        for ident, node in self._candidates(mid.key):
            t0 = time.monotonic()
            got = 0
            entry_box: list = []
            try:
                with TRACER.span("peer_fetch", model=str(mid), peer=ident) as sp:
                    with atomic_dest(dest_dir) as tmp:
                        got = fetch_from_peer(
                            self._channel(node), name, version, tmp,
                            on_file=on_file, timeout_s=self.timeout_s,
                            on_entry=entry_box.append,
                        )
                    sp.attrs["bytes"] = got
                fleet.note_forward(ident, ok=True, latency_s=time.monotonic() - t0)
                if metrics is not None:
                    metrics.peer_fetch_bytes.labels("ok").inc(got)
                log.info(
                    "peer-sourced %s from %s: %d bytes in %.2fs",
                    mid, ident, got, time.monotonic() - t0,
                )
                size = sum(
                    os.path.getsize(os.path.join(r, f))
                    for r, _d, fs in os.walk(dest_dir) for f in fs
                )
                model = Model(identifier=mid, path=dest_dir, size_on_disk=size)
                model.metadata["fetch_source"] = "peer"
                model.metadata["fetch_peer"] = ident
                if entry_box:
                    # transfer-ready packed chunks rebuilt off the wire:
                    # CacheManager hands them to the runtime so the first
                    # load promotes from RAM instead of re-reading the
                    # artifact it just wrote
                    model.metadata["packed_entry"] = entry_box[0]
                return model
            except grpc.RpcError as e:
                got = getattr(e, "partial_bytes", got)
                code = e.code() if hasattr(e, "code") else None
                if code == grpc.StatusCode.NOT_FOUND:
                    # clean miss: the peer's advertisement was stale (it
                    # evicted since). The CONNECTION worked — that proves
                    # liveness, so it counts as a forward success.
                    fleet.note_forward(ident, ok=True,
                                       latency_s=time.monotonic() - t0)
                    if metrics is not None:
                        metrics.peer_fetch_bytes.labels("not_found").inc(got)
                    log.info("peer %s no longer holds %s; trying next source",
                             ident, mid)
                    continue
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    # the peer is alive but at its outbound cap — success
                    # for health, try the next candidate
                    fleet.note_forward(ident, ok=True,
                                       latency_s=time.monotonic() - t0)
                    if metrics is not None:
                        metrics.peer_fetch_bytes.labels("error").inc(got)
                    log.info("peer %s at stream cap for %s; trying next",
                             ident, mid)
                    continue
                fleet.note_forward(ident, ok=False)
                if metrics is not None:
                    metrics.peer_fetch_bytes.labels("error").inc(got)
                log.warning(
                    "peer fetch of %s from %s FAILED mid-stream (%s: %s); "
                    "falling back", mid, ident, code, e,
                )
                continue
            except PeerWireError as e:
                # bytes arrived but failed integrity — the peer is alive
                # (connection-wise) but its stream is suspect; penalize
                got = getattr(e, "partial_bytes", got)
                fleet.note_forward(ident, ok=False)
                if metrics is not None:
                    metrics.peer_fetch_bytes.labels("error").inc(got)
                log.warning(
                    "peer fetch of %s from %s failed integrity (%s); "
                    "falling back", mid, ident, e,
                )
                continue
            except Exception as e:  # noqa: BLE001 - peer path must not be fatal
                got = getattr(e, "partial_bytes", got)
                fleet.note_forward(ident, ok=False)
                if metrics is not None:
                    metrics.peer_fetch_bytes.labels("error").inc(got)
                log.warning(
                    "peer fetch of %s from %s hit %s: %s; falling back",
                    mid, ident, type(e).__name__, e,
                )
                continue
        return None

    # -- ModelProvider interface --------------------------------------------
    def load_model(self, name: str, version: int, dest_dir: str) -> Model:
        model = self._try_peers(name, version, dest_dir, on_file=None)
        if model is not None:
            return model
        return self.inner.load_model(name, version, dest_dir)

    def load_model_streaming(
        self, name: str, version: int, dest_dir: str, on_file=None
    ) -> Model:
        model = self._try_peers(name, version, dest_dir, on_file=on_file)
        if model is not None:
            return model
        return self.inner.load_model_streaming(
            name, version, dest_dir, on_file=on_file
        )

    def model_size(self, name: str, version: int) -> int:
        return self.inner.model_size(name, version)

    def check(self) -> None:
        self.inner.check()

    def list_versions(self, name: str) -> list[int]:
        return self.inner.list_versions(name)

    def latest_version(self, name: str) -> int:
        return self.inner.latest_version(name)

    def close(self) -> None:
        with self._lock:
            for ch in self._channels.values():
                try:
                    ch.close()
                except Exception:  # noqa: BLE001
                    pass
            self._channels.clear()

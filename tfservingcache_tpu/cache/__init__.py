from tfservingcache_tpu.cache.lru import LRUCache, LRUEntry

__all__ = ["LRUCache", "LRUEntry"]

"""Host-RAM warm tier: byte-budgeted LRU of packed parameter chunks.

The middle tier of the three-tier residency model (HBM -> host DRAM ->
disk/store). Where the disk tier retains the *encoded* artifact (bytes the
loader still has to parse, possibly dequantize, and repack), this tier
retains per model exactly what the H2D transfer consumes: the already-
decoded, already-quantized, concatenated chunk buffers in
``_pack_plan`` order, plus the runtime's jitted/AOT executable handles —
so promotion back into HBM is a pure ``device_put`` replay with no
provider fetch and no host decode (runtime/model_runtime.py).

Tier discipline is inclusive downward: a host-tier entry implies the
artifact is still on disk (CacheManager discards the entry when the disk
tier evicts the artifact), so "resident => re-loadable" keeps holding at
every level. Same LRU engine as the other two tiers (native/lru.py via
``make_lru_cache``): byte budget, MRU touch on get, evict callbacks run
outside the internal lock.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from tfservingcache_tpu.cache.lru import LRUEntry
from tfservingcache_tpu.native import make_lru_cache
from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.accounting import LEDGER
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.metrics import Metrics

log = get_logger("host_tier")


@dataclass
class PackedModelEntry:
    """One evicted (or eagerly retained) model's transfer-ready state.

    ``chunks`` are OWNED host buffers (never views into an mmapped artifact
    blob — retaining a view would pin the whole file mapping), one per
    ``_pack_plan`` chunk, in plan order. ``owner``/``shapes``/
    ``quant_dtypes`` describe how the flat buffers re-slice into the outer
    leaf list, mirroring ``packed_device_put_pipelined``'s bookkeeping so
    promotion replays the identical device-op sequence. ``jitted`` keeps
    the family's jax.jit handle alive: jit's dispatch cache lives on the
    function object, so a promoted model's first predict is a cache hit
    even when the last HBM tenant of the family was evicted in between.
    """

    model_def: Any
    chunks: list[tuple[list[int], np.ndarray]]
    owner: list[tuple[int, str]]          # flat idx -> (outer idx, plain|q|scale)
    shapes: list[tuple[int, ...]]         # flat idx -> leaf shape
    quant_dtypes: dict[int, str]          # outer idx -> orig_dtype (quant leaves)
    treedef: Any                          # outer flatten, QuantLeaf as leaf
    jitted: Any
    aot_entries: dict = field(default_factory=dict)
    hbm_bytes: int = 0
    nbytes: int = 0
    # outer idx -> artifact leaf path ("/"-joined key path, registry
    # _leaf_path_str convention). Lets a peer synthesize a complete v2
    # model.json + manifest purely from this entry when streaming it over
    # the wire (protocol/peer_transfer.py) — no access to the original
    # artifact required, so v1-origin entries serve too.
    paths: list[str] = field(default_factory=list)
    # per-chunk wire digests, filled lazily by the first outbound peer
    # stream (build_wire_meta). Chunks are immutable for the entry's
    # lifetime, so a warm node fanning a model out to N peers hashes the
    # bytes once instead of N times.
    wire_hashes: list[str] | None = None


@lockchecked
class HostRamTier:
    """Thread-safe byte-budgeted LRU of ``PackedModelEntry``.

    Thin facade over the shared LRU engine (the tier-interface twin of
    ``ModelDiskCache``: get touches to MRU, put evicts LRU-first to fit,
    callbacks run after the internal lock is released) plus the tier's
    metrics: ``tpusc_host_tier_bytes`` gauge and
    ``tpusc_evictions_total{tier="host"}``.
    """

    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_pins": "_pin_lock",
        "_pinned_evicted": "_pin_lock",
    }

    def __init__(self, capacity_bytes: int, metrics: Metrics | None = None) -> None:
        self.metrics = metrics
        self.lru = make_lru_cache(int(capacity_bytes), self._on_evict)
        self._closed = threading.Event()
        # outbound-stream pins (peer serving, ISSUE 8 satellite 1): the
        # generic LRU engine cannot veto an eviction, so a pinned entry
        # that gets evicted mid-stream is stashed here until the last pin
        # releases — the in-flight sender keeps a consistent snapshot and
        # LRU policy proceeds untouched.
        self._pin_lock = threading.Lock()
        self._pins: dict[ModelId, int] = {}
        self._pinned_evicted: dict[ModelId, PackedModelEntry] = {}

    # -- LRU facade ---------------------------------------------------------
    def get(self, model_id: ModelId, touch: bool = True) -> PackedModelEntry | None:
        return self.lru.get(model_id, touch=touch)

    # -- outbound-stream pinning -------------------------------------------
    def pin(self, model_id: ModelId) -> PackedModelEntry | None:
        """Acquire the entry for an outbound peer stream WITHOUT touching
        LRU order (a remote read must not look like local demand). The
        returned entry stays valid until the matching :meth:`unpin` even if
        the tier evicts it meanwhile. None if absent (clean miss)."""
        with self._pin_lock:
            entry = self.lru.get(model_id, touch=False)
            if entry is None:
                entry = self._pinned_evicted.get(model_id)
            if entry is None:
                return None
            self._pins[model_id] = self._pins.get(model_id, 0) + 1
            return entry

    def unpin(self, model_id: ModelId) -> None:
        with self._pin_lock:
            n = self._pins.get(model_id, 0) - 1
            if n > 0:
                self._pins[model_id] = n
                return
            self._pins.pop(model_id, None)
            self._pinned_evicted.pop(model_id, None)
        self._update_gauge()

    def put(self, model_id: ModelId, entry: PackedModelEntry) -> list[ModelId]:
        if self._closed.is_set():
            return []
        evicted = self.lru.put(model_id, entry.nbytes, entry)
        self._update_gauge()
        return evicted

    def touch(self, model_id: ModelId) -> bool:
        """MRU-promote without materializing the payload; True if present."""
        return self.lru.get(model_id) is not None

    def remove(self, model_id: ModelId) -> None:
        self.lru.remove(model_id, run_callback=False)
        self._update_gauge()

    def __contains__(self, model_id: ModelId) -> bool:
        return model_id in self.lru

    def __len__(self) -> int:
        return len(self.lru)

    def keys_mru_first(self) -> list[ModelId]:
        return self.lru.keys_mru_first()

    def size_of(self, model_id: ModelId) -> int | None:
        entry = self.lru.get(model_id, touch=False)
        return None if entry is None else entry.nbytes

    @property
    def total_bytes(self) -> int:
        return self.lru.total_bytes

    @property
    def capacity_bytes(self) -> int:
        return self.lru.capacity_bytes

    # -- internals ----------------------------------------------------------
    def _on_evict(self, model_id: ModelId, entry: LRUEntry[PackedModelEntry]) -> None:
        # dropping the references IS the free: chunks are plain host arrays.
        # Unless an outbound stream holds a pin — then the payload parks in
        # _pinned_evicted (bytes stay accounted via _update_gauge) and is
        # actually freed by the last unpin.
        with self._pin_lock:
            if self._pins.get(model_id, 0) > 0 and entry.payload is not None:
                self._pinned_evicted[model_id] = entry.payload
        if self.metrics is not None:
            self.metrics.evictions.labels("host").inc()
        self._update_gauge()
        log.info(
            "host tier evicted %s (%d packed bytes)", model_id, entry.size_bytes
        )

    def _update_gauge(self) -> None:
        with self._pin_lock:
            parked = {
                str(mid): float(e.nbytes)
                for mid, e in self._pinned_evicted.items()
            }
            pinned = sum(parked.values())
        # cost ledger: per-tenant host-DRAM levels (owner-scoped zeroing
        # handles the evict side); pin-parked bytes stay on their tenant
        # until the last unpin re-syncs without them
        levels = {
            str(mid): float(e.size_bytes)
            for mid, e in self.lru.items_lru_first()
        }
        for mid, nbytes in parked.items():
            levels[mid] = levels.get(mid, 0.0) + nbytes
        LEDGER.gauge_sync("host_bytes", levels, owner=f"host:{id(self)}")
        total = self.lru.total_bytes + pinned
        peak = RECORDER.observe_watermark("host_tier_bytes", float(total))
        if self.metrics is not None:
            self.metrics.host_tier_bytes.set(total)
            self.metrics.host_tier_bytes_peak.set(peak)

    def clear(self) -> None:
        self.lru.clear()
        self._update_gauge()

    def close(self) -> None:
        self._closed.set()
        self.lru.clear()
        self._update_gauge()

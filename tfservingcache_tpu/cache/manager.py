"""CacheManager — per-node JIT load orchestration.

Reference equivalent: pkg/cachemanager/cachemanager.go (C5 in SURVEY.md §2),
the heart of the system. Differences by design:

  - per-model singleflight instead of one global RW-mutex serializing all
    misses node-wide (the reference flags its big lock as a known todo,
    README.md:75 / cachemanager.go:114-115): concurrent misses on different
    models fetch+compile in parallel; concurrent requests for the same model
    coalesce into one fetch;
  - the "reload serving config and poll every 500 ms" step
    (cachemanager.go:167-195) is a direct in-process runtime.ensure_loaded;
  - hit/stale/miss decision tree kept: HIT = on disk + AVAILABLE in runtime;
    STALE = on disk but not loaded (e.g. HBM-evicted or restart) -> reload
    without re-fetch (cachemanager.go:133-143); MISS = fetch from provider
    (ensure free bytes first), then load.
"""

from __future__ import annotations

import os
import threading
import time

from tfservingcache_tpu.cache.disk_cache import ModelDiskCache
from tfservingcache_tpu.cache.providers.base import ModelProvider
from tfservingcache_tpu.lab import faults as lab_faults
from tfservingcache_tpu.runtime.base import BaseRuntime, LoadTimeoutError
from tfservingcache_tpu.types import Model, ModelId
from tfservingcache_tpu.utils.accounting import LEDGER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.metrics import Metrics
from tfservingcache_tpu.utils.tracing import TRACER

log = get_logger("cachemanager")


class VersionLabelError(LookupError):
    """A ModelSpec.version_label with no mapping in serving.version_labels.

    Surfaced as FAILED_PRECONDITION/412 — TF Serving fails unmapped labels
    the same way; silently serving latest is the one wrong option (VERDICT
    r3 missing #4)."""


def resolve_version_label(version_labels: dict, name: str,
                          label: str) -> int:
    """Shared by CacheManager and Router (which routes by name##version and
    so must resolve labels before consulting the ring)."""
    try:
        return int(version_labels[name][label])
    except (KeyError, TypeError, ValueError):
        raise VersionLabelError(
            f"version label {label!r} is not mapped for model {name!r} "
            "(serving.version_labels)"
        ) from None


@lockchecked
class CacheManager:
    # Guarded-field registry: checked statically by tools/tpusc_check
    # (TPUSC001) and dynamically under TPUSC_LOCKCHECK=1 (utils/lockcheck).
    _tpusc_guarded = {
        "_version_cache": "_version_cache_lock",
        "_negative_cache": "_version_cache_lock",
        "_load_workers": "_load_workers_lock",
    }

    def __init__(
        self,
        provider: ModelProvider,
        disk_cache: ModelDiskCache,
        runtime: BaseRuntime,
        metrics: Metrics | None = None,
        load_timeout_s: float | None = None,
        version_labels: dict | None = None,
    ) -> None:
        self.provider = provider
        self.disk_cache = disk_cache
        self.runtime = runtime
        self.metrics = metrics
        # cold-path deadline over fetch+compile (reference: hardcoded 10 s
        # fetch timeout, cmd/taskhandler/main.go:122). None/0 disables.
        self.load_timeout_s = load_timeout_s or None
        # {model_name: {label: version}} from serving.version_labels
        self.version_labels = version_labels or {}
        # resolve_version memo: an unversioned request for an unknown name
        # otherwise costs a full provider listing PER REQUEST — a hot-path
        # stall at 1000 tenants. Positive entries cache the provider's
        # latest; negative entries cache "name doesn't exist" briefly so a
        # storm of bad names can't hammer the store.
        self._version_cache: dict[str, tuple[int, float]] = {}
        self._negative_cache: dict[str, float] = {}
        self._version_cache_lock = threading.Lock()
        self.version_cache_ttl_s = 10.0
        self.negative_cache_ttl_s = 2.0
        # Deadline workers (see _with_deadline): tracked so close() can join
        # stragglers and a timeout storm can't pile up unbounded threads.
        self._load_workers: set[threading.Thread] = set()
        self._load_workers_lock = threading.Lock()
        self.max_load_workers = 64
        # a model evicted from the disk tier must not keep serving from HBM:
        # its artifact is gone, a restart would break the invariant that
        # resident => re-loadable (subscribe, don't overwrite: several
        # chip-group managers may share one host disk cache)
        disk_cache.add_evict_callback(self._on_disk_evict)

    def _on_disk_evict(self, model_id: ModelId) -> None:
        # unload_and_discard (not plain unload): the host tier is inclusive
        # in the disk tier, so an evicted artifact takes any retained packed
        # chunks down with it (duck-typed for runtimes without the method)
        discard = getattr(self.runtime, "unload_and_discard", None)
        if discard is not None:
            discard(model_id)
        else:
            self.runtime.unload(model_id)
        self._sync_disk_ledger()

    def _sync_disk_ledger(self) -> None:
        """Stamp per-tenant disk-cache levels into the cost ledger
        (owner-scoped: several managers sharing a process never zero each
        other's artifacts)."""
        levels: dict[str, float] = {}
        for mid in self.disk_cache.list_models():
            nbytes = self.disk_cache.size_of(mid)
            if nbytes:
                levels[str(mid)] = float(nbytes)
        LEDGER.gauge_sync("disk_bytes", levels, owner=f"disk:{id(self)}")

    # ------------------------------------------------------------------
    def ensure_servable(self, model_id: ModelId) -> Model:
        """Hit/stale/miss decision + fetch/load; blocks until AVAILABLE.

        Reference: fetchModel (cachemanager.go:91-152).
        """
        label = None
        if self.metrics is not None:
            label = self.metrics.model_label(model_id.name, model_id.version)
            self.metrics.cache_total.labels(label).inc()
        t0 = time.monotonic()

        # fast path outside the lock: fully warm
        model = self.disk_cache.get(model_id)
        if model is not None and self.runtime.is_loaded(model_id):
            if self.metrics is not None:
                self.metrics.cache_hits.labels(label).inc()
                self.metrics.reload_source.labels("hbm").inc()
                self.metrics.cache_duration.labels(label).observe(time.monotonic() - t0)
            LEDGER.note_load(str(model_id), "hbm", time.monotonic() - t0)
            return model

        deadline = t0 + self.load_timeout_s if self.load_timeout_s else None
        with TRACER.span("ensure_servable", model=str(model_id)) as span, \
                self.disk_cache.fetch_lock(model_id):  # per-model singleflight
            model = self.disk_cache.get(model_id)
            if model is not None:
                if self.runtime.is_loaded(model_id):
                    hit = True  # another waiter finished the work
                    source = "hbm"
                else:
                    # STALE: artifact cached, executable not resident — the
                    # runtime reports which tier actually revived it (host
                    # promotion vs full disk load; None = plain runtime)
                    log.info("stale %s: artifact cached, reloading runtime", model_id)
                    src = self._with_deadline(
                        lambda: self.runtime.ensure_loaded(model), deadline,
                        f"reload {model_id}",
                    )
                    hit = True
                    source = src if src in ("hbm", "host") else "disk"
            else:
                hit = False
                model = self._with_deadline(
                    lambda: self._fetch(model_id), deadline, f"fetch {model_id}"
                )
                # a PeerProvider stamps where the bytes actually came from:
                # "peer" = streamed from a warm node's host tier instead of
                # the store (cache/providers/peer.py)
                source = model.metadata.get("fetch_source", "store")
                if source not in ("peer", "store"):
                    source = "store"
                # a peer fetch also hands over the transfer-ready packed
                # chunks it assembled off the wire; the runtime promotes
                # from those directly instead of re-reading the artifact it
                # just wrote. POPPED unconditionally — a Model lives in the
                # disk-cache map, and a retained entry would pin the packed
                # bytes in RAM for as long as the artifact stays cached.
                packed = model.metadata.pop("packed_entry", None)
                if packed is not None:
                    adopt = getattr(self.runtime, "adopt_packed_entry", None)
                    if adopt is not None:
                        adopt(model_id, packed)
                self._with_deadline(
                    lambda: self.runtime.ensure_loaded(model), deadline,
                    f"load {model_id}",
                )
            span.attrs["reload_source"] = source
            if self.metrics is not None:
                (self.metrics.cache_hits if hit else self.metrics.cache_misses).labels(
                    label
                ).inc()
                self.metrics.reload_source.labels(source).inc()
                self.metrics.cache_duration.labels(label).observe(time.monotonic() - t0)
                self.metrics.disk_bytes_in_use.set(self.disk_cache.total_bytes)
            # cost ledger: which tier revived this tenant and what it cost;
            # disk levels re-stamped only on this slow path (a fetch may
            # have put/evicted artifacts), never on the per-request fast path
            LEDGER.note_load(str(model_id), source, time.monotonic() - t0)
            self._sync_disk_ledger()
            return model

    def residency_warmth(self, model_id: ModelId) -> int:
        """How warm is ``model_id`` on THIS node: 3 = HBM-resident,
        2 = host-tier packed (promotable in tens of ms), 1 = disk artifact,
        0 = cold. Advisory snapshot for the router's equal-load tie-break
        (cluster/router.py): a replica that can promote instead of
        refetching should win ties. Never raises — routing must not fail
        on a warmth probe."""
        try:
            if self.runtime.is_loaded(model_id):
                return 3
            contains = getattr(self.runtime, "host_tier_contains", None)
            if contains is not None and contains(model_id):
                return 2
            # size_of, not get: a warmth probe must not perturb LRU recency
            if self.disk_cache.size_of(model_id) is not None:
                return 1
        except Exception:  # noqa: BLE001 - advisory only
            pass
        return 0

    def _with_deadline(self, fn, deadline: float | None, desc: str):
        """Run ``fn`` under the shared cold-load deadline.

        Python can't interrupt a blocking provider download or XLA compile
        in-thread, so with a deadline set the work runs in a daemon worker
        while the request thread waits with a timeout: on expiry the request
        fails fast (LoadTimeoutError -> 504/DEADLINE_EXCEEDED) and its
        singleflight lock is released, while the orphaned worker runs to
        completion in the background. Its result still lands (disk index /
        runtime state machine, which the worker advances to AVAILABLE or END
        itself), so the spent work isn't wasted: the next request finds the
        model warm or STALE. Without a deadline the call runs inline."""
        if deadline is None:
            return fn()
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise LoadTimeoutError(
                f"{desc}: cold-load deadline ({self.load_timeout_s:.1f}s) already spent"
            )
        import contextvars

        ctx = contextvars.copy_context()  # keep TRACER span parentage in the worker
        box: dict = {}
        done = threading.Event()

        def work() -> None:
            try:
                box["value"] = ctx.run(fn)
            except BaseException as e:  # noqa: BLE001 - re-raised in caller
                box["error"] = e
            finally:
                done.set()
                with self._load_workers_lock:
                    self._load_workers.discard(threading.current_thread())

        worker = threading.Thread(target=work, daemon=True, name="tpusc-load-worker")
        with self._load_workers_lock:
            if len(self._load_workers) >= self.max_load_workers:
                raise LoadTimeoutError(
                    f"{desc}: {self.max_load_workers} cold-load workers already "
                    "in flight (deadline storm); failing fast instead of "
                    "spawning an unbounded thread pile"
                )
            self._load_workers.add(worker)
        worker.start()
        if not done.wait(remaining):
            log.warning("%s exceeded cold-load deadline (%.1fs); request fails 504, "
                        "work continues in background", desc, self.load_timeout_s)
            raise LoadTimeoutError(
                f"{desc} exceeded cold-load deadline ({self.load_timeout_s:.1f}s)"
            )
        if "error" in box:
            raise box["error"]
        return box.get("value")

    def prefetch(self, model_id: ModelId) -> Model:
        """Host-side half of a cold miss only: artifact onto local disk, the
        runtime untouched. Cross-host groups use this as a joinable phase 1
        (parallel/multihost.py) so provider/IO failures surface BEFORE any
        process enters a collective it could strand the others in."""
        with self.disk_cache.fetch_lock(model_id):
            model = self.disk_cache.get(model_id)
            if model is not None:
                return model
            return self._fetch(model_id)

    def _fetch(self, model_id: ModelId) -> Model:
        """MISS path: size -> evict-to-fit -> provider fetch -> index.
        Reference cachemanager.go:114-127 (minus its double-eviction quirk).

        With a pipelined runtime the fetch goes through the provider's
        streaming variant: the moment model.json lands on disk its manifest
        is handed to ``runtime.precompile_from_meta``, so the family's XLA
        compile overlaps the rest of the download — the widest overlap the
        cold pipeline gets, since provider fetch is usually its longest
        stage."""
        t0 = time.monotonic()
        # scenario-lab hook (lab/faults.py): stall_store sleeps here — a
        # hung object store, under whatever cold-load deadline the caller
        # wrapped this fetch in. Disarmed it is one bool read.
        lab_faults.fire("store_fetch", model=str(model_id))
        on_file = None
        if getattr(self.runtime, "cold_pipeline_enabled", False):
            runtime = self.runtime

            def on_file(rel: str, local_path: str) -> None:
                if os.path.basename(rel) != "model.json":
                    return
                try:
                    from tfservingcache_tpu.models.registry import (
                        load_artifact_meta,
                    )

                    runtime.precompile_from_meta(load_artifact_meta(local_path))
                except Exception as e:  # noqa: BLE001 - advisory hint only
                    log.debug("early precompile for %s skipped: %s", model_id, e)

        with TRACER.span("provider_fetch", model=str(model_id)):
            size = self.provider.model_size(model_id.name, model_id.version)
            self.disk_cache.ensure_free_bytes(size)
            # duck-typed: fake providers that only implement load_model
            # (tests, external plugins) keep working without the overlap
            stream = getattr(self.provider, "load_model_streaming", None)
            if on_file is not None and stream is not None:
                model = stream(
                    model_id.name, model_id.version,
                    self.disk_cache.model_path(model_id), on_file=on_file,
                )
            else:
                model = self.provider.load_model(
                    model_id.name, model_id.version,
                    self.disk_cache.model_path(model_id),
                )
        self.disk_cache.put(model)
        if self.metrics is not None:
            self.metrics.cache_fetch_duration.labels(
                self.metrics.model_label(model_id.name, model_id.version)
            ).observe(time.monotonic() - t0)
            # the fetch stage of the cold-stage histogram family (its device
            # siblings are recorded by the runtime's load span)
            self.metrics.cold_stage_seconds.labels("provider_fetch").observe(
                time.monotonic() - t0
            )
        log.info(
            "fetched %s (%d bytes) in %.2fs", model_id, model.size_on_disk, time.monotonic() - t0
        )
        return model

    # ------------------------------------------------------------------
    def resolve_version(self, name: str, version: int | None,
                        label: str | None = None) -> int:
        """Map "no version given" (gRPC ModelSpec with unset Int64Value reads
        as 0 — reference taskhandler clientForSpec, tfservingproxy.go:246-250)
        to the newest known version: prefer what's resident, fall back to the
        provider listing. A ``version_label`` resolves through the serving
        config's ``version_labels`` map or fails (never silently latest)."""
        if label:
            return resolve_version_label(self.version_labels, name, label)
        if version:
            return version
        known = [m.version for m in self.disk_cache.list_models() if m.name == name]
        loaded = [m.version for m, s in self.runtime.states_for(name).items() if s == 30]
        if loaded:
            return max(loaded)
        if known:
            return max(known)
        from tfservingcache_tpu.cache.providers.base import ModelNotFoundError

        now = time.monotonic()
        with self._version_cache_lock:
            hit = self._version_cache.get(name)
            if hit is not None and hit[1] > now:
                return hit[0]
            neg = self._negative_cache.get(name)
            if neg is not None and neg > now:
                raise ModelNotFoundError(f"model {name!r} not found (cached)")
        try:
            latest = self.provider.latest_version(name)
        except ModelNotFoundError:
            with self._version_cache_lock:
                if len(self._negative_cache) > 4096:
                    self._negative_cache.clear()
                self._negative_cache[name] = now + self.negative_cache_ttl_s
            raise
        with self._version_cache_lock:
            if len(self._version_cache) > 4096:
                self._version_cache.clear()
            self._version_cache[name] = (latest, now + self.version_cache_ttl_s)
        return latest

    def available_versions(self, name: str) -> list[int]:
        """All versions the node could serve, ascending: the provider's
        listing, falling back to disk-cached versions when the provider can't
        enumerate (backs ReloadConfig's latest/all version policies)."""
        from tfservingcache_tpu.cache.providers.base import ModelNotFoundError

        try:
            return self.provider.list_versions(name)
        except ModelNotFoundError:
            cached = sorted(m.version for m in self.disk_cache.list_models() if m.name == name)
            if cached:
                return cached
            raise

    def is_healthy(self) -> bool:
        """Provider + runtime probes (reference IsHealthy,
        cachemanager.go:76-89, where "TF Serving answers NOT_FOUND for the
        probe model" meant alive; in-process we just probe directly)."""
        try:
            self.provider.check()
            self.runtime.check()
            return True
        except Exception as e:  # noqa: BLE001
            log.warning("health check failed: %s", e)
            return False

    def list_cached(self) -> list[ModelId]:
        return self.disk_cache.list_models()

    def close(self) -> None:
        self.runtime.close()
        # Orphaned deadline workers (request timed out, work still landing):
        # give them a bounded window to finish so shutdown doesn't race their
        # disk-index/runtime writes, then let daemons die with the process.
        with self._load_workers_lock:
            stragglers = list(self._load_workers)
        for t in stragglers:
            t.join(timeout=5.0)

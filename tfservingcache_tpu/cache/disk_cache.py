"""Disk artifact cache: versioned model dirs under a byte-budgeted LRU.

Reference equivalent: the LRUCache + ``BaseDir``/``ModelPath`` pathing in
pkg/cachemanager/lrucache.go:11-38. Layout is the SavedModel convention the
whole protocol assumes: ``<base_dir>/<name>/<version>/...``.

Improvements over the reference (SURVEY.md §5 checkpoint/resume): the index
is rebuilt from disk at startup (the reference loses the LRU index on
restart while files persist, cachemanager.go:154-165), and eviction removes
the actual joined directory tree.
"""

from __future__ import annotations

import os
import queue
import shutil
import threading
from contextlib import contextmanager
from typing import Callable, Iterator

from tfservingcache_tpu.cache.lru import LRUEntry
from tfservingcache_tpu.native import make_lru_cache
from tfservingcache_tpu.types import Model, ModelId
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("disk_cache")


def dir_size_bytes(path: str) -> int:
    """Recursive size (the reference stats the directory inode only —
    diskmodelprovider.go:71-83 — which under-counts; don't replicate)."""
    total = 0
    for root, _dirs, files in os.walk(path):
        for f in files:
            fp = os.path.join(root, f)
            try:
                total += os.path.getsize(fp)
            except OSError:
                pass
    return total


@lockchecked
class ModelDiskCache:
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_key_locks": "_key_locks_guard"}

    def __init__(
        self,
        base_dir: str,
        capacity_bytes: int,
        on_evict: Callable[[ModelId], None] | None = None,
        recover: bool = True,
    ) -> None:
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        # multiple subscribers: with several chip-group runtimes sharing one
        # host disk cache, EVERY group must drop its executable when the
        # artifact goes (resident => re-loadable invariant)
        self._evict_callbacks: list[Callable[[ModelId], None]] = (
            [on_evict] if on_evict is not None else []
        )
        self.lru = make_lru_cache(capacity_bytes, self._evict)
        # Per-model mutexes shared by eviction and (re)load: a deferred evict
        # rmtree must not race a concurrent re-fetch writing the same path.
        self._key_locks: dict[ModelId, threading.Lock] = {}
        self._key_locks_guard = threading.Lock()
        # Evictions run on one dedicated worker so the thread that *caused*
        # an eviction (holding its own model's fetch_lock) never blocks on
        # another model's key lock — two concurrent misses evicting each
        # other's models would otherwise ABBA-deadlock.
        self._evict_queue: queue.Queue = queue.Queue()
        self._evict_worker = threading.Thread(
            target=self._evict_loop, name="tpusc-disk-evict", daemon=True
        )
        self._evict_worker.start()
        if recover:
            self._recover_index()

    @contextmanager
    def fetch_lock(self, model_id: ModelId) -> Iterator[None]:
        """Hold while fetching/writing ``model_id``'s artifact dir. The evict
        callback takes the same lock, so an in-flight eviction of a model that
        is being re-loaded waits, then sees it resident again and skips."""
        with self._key_locks_guard:
            lock = self._key_locks.setdefault(model_id, threading.Lock())
        try:
            with lock:
                yield
        finally:
            # Failure-path pruning: a fetch that never lands (provider error,
            # deadline) leaves a key the evict-side pruning can never reach —
            # never cached means never evicted — so a storm of misses on bad
            # names would grow this dict without bound. Same rule as
            # _evict_impl: drop the entry once it is idle and non-resident.
            with self._key_locks_guard:
                held = self._key_locks.get(model_id)
                if held is lock and not held.locked() and model_id not in self.lru:
                    del self._key_locks[model_id]

    # -- paths --------------------------------------------------------------
    def model_path(self, model_id: ModelId) -> str:
        return os.path.join(self.base_dir, model_id.name, str(model_id.version))

    # -- LRU facade ---------------------------------------------------------
    def get(self, model_id: ModelId) -> Model | None:
        # a read IS a use: touch to MRU so the hot tail of a churned tenant
        # population survives eviction pressure (recency pinned by
        # tests/test_disk_cache.py — a silent touch=False regression here
        # turns the LRU into FIFO)
        model = self.lru.get(model_id, touch=True)
        if model is None:
            return None
        # Tolerate out-of-band deletion: index says cached but files are gone
        # (reference double-check, cachemanager.go:154-165).
        if not os.path.exists(model.path):
            self.lru.remove(model_id)
            return None
        return model

    def put(self, model: Model) -> list[ModelId]:
        # charge what is ACTUALLY on disk, not what the provider claimed:
        # a drifted size_on_disk (manifest lies, partial rewrite, compression
        # difference) would otherwise skew the byte budget until restart
        if os.path.isdir(model.path):
            actual = dir_size_bytes(model.path)
            if actual != model.size_on_disk:
                log.warning(
                    "size drift for %s: claimed %d bytes, %d on disk",
                    model.identifier, model.size_on_disk, actual,
                )
                model.size_on_disk = actual
        return self.lru.put(model.identifier, model.size_on_disk, model)

    def ensure_free_bytes(self, n: int) -> list[ModelId]:
        return self.lru.ensure_free_bytes(n)

    def remove(self, model_id: ModelId) -> None:
        self.lru.remove(model_id, run_callback=True)

    def list_models(self) -> list[ModelId]:
        return self.lru.keys_mru_first()

    def size_of(self, model_id: ModelId) -> int | None:
        """On-disk artifact bytes (None if absent) — the warmer's estimate
        of a model's HBM footprint before paying to load it."""
        model = self.lru.get(model_id, touch=False)
        return None if model is None else model.size_on_disk

    @property
    def total_bytes(self) -> int:
        return self.lru.total_bytes

    @property
    def capacity_bytes(self) -> int:
        return self.lru.capacity_bytes

    # -- internals ----------------------------------------------------------
    def _evict(self, model_id: ModelId, entry: LRUEntry[Model]) -> None:
        self._evict_queue.put((model_id, entry))

    def _evict_loop(self) -> None:
        while True:
            item = self._evict_queue.get()
            try:
                if item is None:
                    return
                self._evict_impl(*item)
            except Exception:  # noqa: BLE001 - worker must survive bad evictions
                log.exception("eviction failed")
            finally:
                self._evict_queue.task_done()

    def drain_evictions(self) -> None:
        """Block until all queued evictions have completed (tests, shutdown)."""
        self._evict_queue.join()

    def _evict_impl(self, model_id: ModelId, entry: LRUEntry[Model]) -> None:
        with self._key_locks_guard:
            lock = self._key_locks.setdefault(model_id, threading.Lock())
        with lock:
            if model_id in self.lru:
                # The key is resident again: either a replacement put() (same
                # path, overwritten in place) or a re-fetch that won the race
                # against this deferred eviction. Nothing to free.
                return
            path = self.model_path(model_id)
            if os.path.isdir(path):
                shutil.rmtree(path, ignore_errors=True)
            # prune now-empty model dir
            parent = os.path.dirname(path)
            try:
                if os.path.isdir(parent) and not os.listdir(parent):
                    os.rmdir(parent)
            except OSError:
                pass
        log.info("evicted %s from disk cache (%d bytes)", model_id, entry.size_bytes)
        # prune this model's key lock (bounded memory under tenant churn); a
        # racer holding the popped lock at worst repeats idempotent work
        with self._key_locks_guard:
            held = self._key_locks.get(model_id)
            if held is not None and not held.locked():
                del self._key_locks[model_id]
        for cb in list(self._evict_callbacks):
            try:
                cb(model_id)
            except Exception:  # noqa: BLE001 - one group's failure can't block others
                log.exception("disk-evict callback failed for %s", model_id)

    def add_evict_callback(self, cb: Callable[[ModelId], None]) -> None:
        self._evict_callbacks.append(cb)

    def _recover_index(self) -> None:
        """Repopulate the LRU from artifacts already on disk (restart path)."""
        found: list[tuple[float, ModelId, str, int]] = []
        try:
            names = os.listdir(self.base_dir)
        except OSError:
            return
        for name in names:
            model_dir = os.path.join(self.base_dir, name)
            try:
                versions = os.listdir(model_dir)
            except (NotADirectoryError, OSError):
                continue
            for ver in versions:
                vdir = os.path.join(model_dir, ver)
                if ".tmp-" in ver:
                    # stray staging dir from a crash mid-fetch (providers write
                    # to <ver>.tmp-<pid> then atomically rename)
                    shutil.rmtree(vdir, ignore_errors=True)
                    continue
                try:
                    version = int(ver)
                except ValueError:
                    continue
                try:
                    if not os.path.isdir(vdir):
                        continue
                    found.append(
                        (os.path.getmtime(vdir), ModelId(name, version), vdir, dir_size_bytes(vdir))
                    )
                except OSError:
                    # vanished out-of-band between listdir and stat — skip it,
                    # don't abort recovery of the remaining artifacts
                    continue
        # oldest first so mtime order becomes LRU order
        for _mtime, mid, vdir, size in sorted(found):
            try:
                self.lru.put(mid, size, Model(identifier=mid, path=vdir, size_on_disk=size))
            except Exception as e:
                log.warning(
                    "dropping recovered artifact %s (%d bytes) that no longer fits: %s",
                    mid, size, e,
                )
                shutil.rmtree(vdir, ignore_errors=True)
        if found:
            log.info("recovered %d cached artifacts (%d bytes)", len(self.lru), self.total_bytes)

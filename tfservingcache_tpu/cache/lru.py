"""Byte-budgeted LRU cache, tier-generic.

Reference equivalent: pkg/cachemanager/lrucache.go (container/list + map over
on-disk models, byte capacity). This version is used for BOTH tiers of the
TPU design (SURVEY.md §2 C6): the disk artifact tier (payload = artifact dir,
evict callback deletes the tree) and the HBM tier (payload = runtime handle,
evict callback unloads the executable and frees device memory).

Deliberate fixes over the reference (SURVEY.md §7 "bugs to NOT replicate"):
  - thread-safe (the reference LRUCache relies on the caller's global mutex,
    lrucache.go:20-26);
  - eviction runs a callback with the full entry instead of os.Remove on a
    relative path that can't delete non-empty dirs (lrucache.go:73-79);
  - oversized items are rejected rather than evicting the world first;
  - single eviction pass per put (the reference evicts in Put and again in
    EnsureFreeBytes, cachemanager.go:121 + lrucache.go:58).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Callable, Generic, Hashable, Iterator, TypeVar

K = TypeVar("K", bound=Hashable)
V = TypeVar("V")

EvictCallback = Callable[[K, "LRUEntry[V]"], None]


@dataclass
class LRUEntry(Generic[V]):
    size_bytes: int
    payload: V


class CapacityError(Exception):
    """Item larger than the whole cache budget."""


class LRUCache(Generic[K, V]):
    def __init__(
        self,
        capacity_bytes: int,
        on_evict: EvictCallback | None = None,
        max_items: int | None = None,
    ) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self.max_items = max_items
        self._on_evict = on_evict
        self._lock = threading.RLock()
        self._entries: OrderedDict[K, LRUEntry[V]] = OrderedDict()  # MRU last
        self._total = 0

    # -- introspection ------------------------------------------------------
    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: K) -> bool:
        with self._lock:
            return key in self._entries

    def keys_mru_first(self) -> list[K]:
        """Reference ``ListModels`` returns MRU-first order (lrucache.go:89-97)."""
        with self._lock:
            return list(reversed(self._entries.keys()))

    def items_lru_first(self) -> Iterator[tuple[K, LRUEntry[V]]]:
        with self._lock:
            return iter(list(self._entries.items()))

    # -- core ---------------------------------------------------------------
    def get(self, key: K, touch: bool = True) -> V | None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            if touch:
                self._entries.move_to_end(key)
            return entry.payload

    def put(self, key: K, size_bytes: int, payload: V) -> list[K]:
        """Insert/replace and evict LRU entries until the budget fits.

        Returns the keys evicted to make room (reference Put, lrucache.go:41-65).
        Replacing an existing key runs the evict callback on the old entry so
        tier resources (HBM executables, artifact trees) are released.
        """
        size_bytes = int(size_bytes)
        if size_bytes > self.capacity_bytes:
            raise CapacityError(
                f"item {key!r} ({size_bytes}B) exceeds cache capacity {self.capacity_bytes}B"
            )
        with self._lock:
            evicted: list[tuple[K, LRUEntry[V]]] = []
            old = self._entries.pop(key, None)
            if old is not None:
                self._total -= old.size_bytes
                evicted.append((key, old))
            evicted += self._evict_to_fit_locked(size_bytes, extra_items=1)
            self._entries[key] = LRUEntry(size_bytes, payload)
            self._total += size_bytes
        self._run_callbacks(evicted)
        return [k for k, _ in evicted if k != key]

    def remove(self, key: K, run_callback: bool = False) -> V | None:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return None
            self._total -= entry.size_bytes
        if run_callback and self._on_evict is not None:
            self._on_evict(key, entry)
        return entry.payload

    def ensure_free_bytes(self, n: int) -> list[K]:
        """Evict LRU entries until at least ``n`` bytes are free
        (reference EnsureFreeBytes, lrucache.go:68-87). Raises CapacityError
        up front when ``n`` can never fit — draining the whole warm cache for
        a load that must fail anyway would be strictly worse."""
        n = int(n)
        if n > self.capacity_bytes:
            raise CapacityError(
                f"requested {n}B free exceeds cache capacity {self.capacity_bytes}B"
            )
        with self._lock:
            evicted = self._evict_to_fit_locked(n, extra_items=0)
        self._run_callbacks(evicted)
        return [k for k, _ in evicted]

    def _evict_to_fit_locked(self, n: int, extra_items: int) -> list[tuple[K, LRUEntry[V]]]:
        """Pop LRU entries until ``n`` extra bytes fit. Callbacks are NOT run
        here — callers run them after releasing the lock so slow eviction work
        (rmtree of a multi-GB artifact, device unload) never blocks readers."""
        evicted: list[tuple[K, LRUEntry[V]]] = []
        while self._entries and (
            self._total + n > self.capacity_bytes
            or (self.max_items is not None and len(self._entries) + extra_items > self.max_items)
        ):
            key, entry = self._entries.popitem(last=False)  # LRU first
            self._total -= entry.size_bytes
            evicted.append((key, entry))
        return evicted

    def _run_callbacks(self, evicted: list[tuple[K, LRUEntry[V]]]) -> None:
        if self._on_evict is None:
            return
        for key, entry in evicted:
            self._on_evict(key, entry)

    def clear(self) -> None:
        with self._lock:
            evicted = [(k, e) for k, e in self._entries.items()]
            self._entries.clear()
            self._total = 0
        self._run_callbacks(evicted)

// tpusc_native — native runtime components for the TPU serving cache.
//
// The reference implements its runtime (routing ring, LRU index) in Go
// (pkg/taskhandler/cluster.go, pkg/cachemanager/lrucache.go); here the same
// roles are played by C++ behind a plain-C ABI loaded via ctypes, with a
// pure-Python fallback (tfservingcache_tpu/native/__init__.py).
//
// Placement parity is a hard requirement: a mixed fleet where some nodes run
// the native ring and some the Python fallback must route every key to the
// same owners.  The ring therefore uses the exact hash the Python side uses —
// BLAKE2b with an 8-byte digest (RFC 7693), value read big-endian — and the
// exact tie-breaking sort (point, then member string).
//
// Components:
//   - blake2b64: unkeyed BLAKE2b-64 (written from RFC 7693, not copied)
//   - Ring:      consistent-hash ring, vnodes per member, get_n distinct
//   - Lru:       byte-budgeted LRU index with max-item cap and atomic
//                two-phase eviction reporting
//
// Thread-safety: each object carries its own shared_mutex; lookups take the
// shared side so concurrent request routing never serializes.

#include <algorithm>
#include <charconv>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <list>
#include <mutex>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <utility>
#include <vector>

// ---------------------------------------------------------------------------
// BLAKE2b (RFC 7693), unkeyed, 8-byte digest
// ---------------------------------------------------------------------------

namespace {

constexpr uint64_t kIv[8] = {
    0x6A09E667F3BCC908ULL, 0xBB67AE8584CAA73BULL,
    0x3C6EF372FE94F82BULL, 0xA54FF53A5F1D36F1ULL,
    0x510E527FADE682D1ULL, 0x9B05688C2B3E6C1FULL,
    0x1F83D9ABFB41BD6BULL, 0x5BE0CD19137E2179ULL,
};

constexpr uint8_t kSigma[10][16] = {
    {0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15},
    {14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3},
    {11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4},
    {7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8},
    {9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13},
    {2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9},
    {12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11},
    {13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10},
    {6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5},
    {10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0},
};

inline uint64_t rotr64(uint64_t x, int n) { return (x >> n) | (x << (64 - n)); }

inline uint64_t load_le64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);  // little-endian hosts only (x86/arm) — fine here
  return v;
}

inline void g_mix(uint64_t v[16], int a, int b, int c, int d, uint64_t x,
                  uint64_t y) {
  v[a] = v[a] + v[b] + x;
  v[d] = rotr64(v[d] ^ v[a], 32);
  v[c] = v[c] + v[d];
  v[b] = rotr64(v[b] ^ v[c], 24);
  v[a] = v[a] + v[b] + y;
  v[d] = rotr64(v[d] ^ v[a], 16);
  v[c] = v[c] + v[d];
  v[b] = rotr64(v[b] ^ v[c], 63);
}

void compress(uint64_t h[8], const uint8_t block[128], uint64_t t,
              bool last) {
  uint64_t m[16];
  for (int i = 0; i < 16; i++) m[i] = load_le64(block + 8 * i);
  uint64_t v[16];
  for (int i = 0; i < 8; i++) v[i] = h[i];
  for (int i = 0; i < 8; i++) v[8 + i] = kIv[i];
  v[12] ^= t;  // low word of the 128-bit offset; inputs here are < 2^64
  if (last) v[14] = ~v[14];
  for (int r = 0; r < 12; r++) {
    const uint8_t* s = kSigma[r % 10];
    g_mix(v, 0, 4, 8, 12, m[s[0]], m[s[1]]);
    g_mix(v, 1, 5, 9, 13, m[s[2]], m[s[3]]);
    g_mix(v, 2, 6, 10, 14, m[s[4]], m[s[5]]);
    g_mix(v, 3, 7, 11, 15, m[s[6]], m[s[7]]);
    g_mix(v, 0, 5, 10, 15, m[s[8]], m[s[9]]);
    g_mix(v, 1, 6, 11, 12, m[s[10]], m[s[11]]);
    g_mix(v, 2, 7, 8, 13, m[s[12]], m[s[13]]);
    g_mix(v, 3, 4, 9, 14, m[s[14]], m[s[15]]);
  }
  for (int i = 0; i < 8; i++) h[i] ^= v[i] ^ v[8 + i];
}

// 8-byte-digest BLAKE2b of `data`, returned as the big-endian integer the
// Python side computes via int.from_bytes(blake2b(digest_size=8), "big").
uint64_t blake2b64(const uint8_t* data, size_t len) {
  uint64_t h[8];
  for (int i = 0; i < 8; i++) h[i] = kIv[i];
  h[0] ^= 0x01010000ULL ^ 8ULL;  // depth 1, fanout 1, key 0, digest 8

  uint8_t block[128];
  size_t off = 0;
  // all blocks before the last are full; the final (possibly empty) chunk is
  // zero-padded and compressed with the final flag
  while (len - off > 128) {
    compress(h, data + off, static_cast<uint64_t>(off) + 128, false);
    off += 128;
  }
  size_t rem = len - off;
  std::memset(block, 0, sizeof(block));
  if (rem) std::memcpy(block, data + off, rem);
  compress(h, block, static_cast<uint64_t>(len), true);

  // digest bytes = little-endian h[0]; value = those 8 bytes read big-endian
  uint64_t out = 0;
  for (int i = 0; i < 8; i++) {
    out = (out << 8) | ((h[0] >> (8 * i)) & 0xFF);
  }
  return out;
}

uint64_t point_of(const std::string& s) {
  return blake2b64(reinterpret_cast<const uint8_t*>(s.data()), s.size());
}

// ---------------------------------------------------------------------------
// Consistent-hash ring
// ---------------------------------------------------------------------------

struct Ring {
  explicit Ring(int vnodes) : vnodes(vnodes) {}

  int vnodes;
  mutable std::shared_mutex mu;
  std::vector<uint64_t> points;   // sorted
  std::vector<uint32_t> owner_ix; // parallel: index into members
  std::vector<std::string> members;

  void set_members(std::vector<std::string> new_members) {
    // dedupe, keep deterministic (point, owner-string) sort like the Python
    // side's tuple sort
    std::sort(new_members.begin(), new_members.end());
    new_members.erase(std::unique(new_members.begin(), new_members.end()),
                      new_members.end());
    std::vector<std::pair<uint64_t, uint32_t>> pairs;
    pairs.reserve(new_members.size() * vnodes);
    for (uint32_t mi = 0; mi < new_members.size(); mi++) {
      for (int i = 0; i < vnodes; i++) {
        pairs.emplace_back(
            point_of(new_members[mi] + "#" + std::to_string(i)), mi);
      }
    }
    std::sort(pairs.begin(), pairs.end(),
              [&](const auto& a, const auto& b) {
                if (a.first != b.first) return a.first < b.first;
                return new_members[a.second] < new_members[b.second];
              });
    std::unique_lock lk(mu);
    members = std::move(new_members);
    points.resize(pairs.size());
    owner_ix.resize(pairs.size());
    for (size_t i = 0; i < pairs.size(); i++) {
      points[i] = pairs[i].first;
      owner_ix[i] = pairs[i].second;
    }
  }

  // N distinct members clockwise from the key's point, '\n'-joined into out.
  // Returns bytes needed (incl. NUL); caller retries with a bigger buffer if
  // the return exceeds cap.
  int get_n(const std::string& key, int n, char* out, int cap) const {
    if (n < 1) n = 1;
    std::shared_lock lk(mu);
    if (points.empty()) {
      if (cap > 0) out[0] = '\0';
      return 1;
    }
    n = std::min<int>(n, static_cast<int>(members.size()));
    uint64_t p = point_of(key);
    size_t idx = std::lower_bound(points.begin(), points.end(), p) -
                 points.begin();
    if (idx == points.size()) idx = 0;
    std::vector<bool> seen(members.size(), false);
    std::string joined;
    int found = 0;
    for (size_t step = 0; step < points.size() && found < n; step++) {
      uint32_t mi = owner_ix[(idx + step) % points.size()];
      if (!seen[mi]) {
        seen[mi] = true;
        if (found) joined += '\n';
        joined += members[mi];
        found++;
      }
    }
    int needed = static_cast<int>(joined.size()) + 1;
    if (needed <= cap) std::memcpy(out, joined.c_str(), needed);
    return needed;
  }
};

// ---------------------------------------------------------------------------
// Byte-budgeted LRU index
// ---------------------------------------------------------------------------

struct Lru {
  Lru(long long capacity, long long max_items)
      : capacity(capacity), max_items(max_items) {}

  long long capacity;
  long long max_items;  // -1 = unbounded
  long long total = 0;
  mutable std::shared_mutex mu;
  std::list<std::pair<std::string, long long>> order;  // LRU front, MRU back
  std::unordered_map<std::string,
                     std::list<std::pair<std::string, long long>>::iterator>
      index;

  bool contains(const std::string& k) const {
    std::shared_lock lk(mu);
    return index.count(k) != 0;
  }

  // returns size if present (touching unless touch=0), -1 if absent
  long long get(const std::string& k, int touch) {
    if (!touch) {  // pure read: shared side, so resident checks never serialize
      std::shared_lock lk(mu);
      auto it = index.find(k);
      return it == index.end() ? -1 : it->second->second;
    }
    std::unique_lock lk(mu);
    auto it = index.find(k);
    if (it == index.end()) return -1;
    order.splice(order.end(), order, it->second);
    return it->second->second;
  }

  // Evictions needed to fit `extra` bytes / `extra_items` items, LRU-first,
  // optionally pretending key `skip` (a being-replaced entry) is already
  // gone.  Pure planning — no mutation.  Shared by put and ensure_free so
  // the two paths can never diverge.
  std::vector<std::string> plan_evictions(long long extra, int extra_items,
                                          const std::string* skip) const {
    std::vector<std::string> out;
    long long t = total;
    long long items = static_cast<long long>(order.size());
    if (skip) {
      auto it = index.find(*skip);
      if (it != index.end()) {
        t -= it->second->second;
        items--;
      }
    }
    for (auto it = order.begin(); it != order.end(); ++it) {
      if (skip && it->first == *skip) continue;
      if (t + extra <= capacity &&
          (max_items < 0 || items + extra_items <= max_items)) {
        break;
      }
      out.push_back(it->first);
      t -= it->second;
      items--;
    }
    return out;
  }

  void drop(const std::string& k) {
    auto it = index.find(k);
    if (it == index.end()) return;
    total -= it->second->second;
    order.erase(it->second);
    index.erase(it);
  }
};

std::string join_lines(const std::vector<std::string>& v) {
  std::string out;
  for (size_t i = 0; i < v.size(); i++) {
    if (i) out += '\n';
    out += v[i];
  }
  return out;
}

// write a string into (out, cap); returns needed bytes incl. NUL
int write_out(const std::string& s, char* out, int cap) {
  int needed = static_cast<int>(s.size()) + 1;
  if (needed <= cap && out) std::memcpy(out, s.c_str(), needed);
  return needed;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

unsigned long long tpusc_blake2b64(const char* data, long long len) {
  return blake2b64(reinterpret_cast<const uint8_t*>(data),
                   static_cast<size_t>(len));
}

void* tpusc_ring_new(int vnodes) { return new Ring(vnodes); }

void tpusc_ring_free(void* r) { delete static_cast<Ring*>(r); }

void tpusc_ring_set_members(void* r, const char** members, int n) {
  std::vector<std::string> v;
  v.reserve(n);
  for (int i = 0; i < n; i++) v.emplace_back(members[i]);
  static_cast<Ring*>(r)->set_members(std::move(v));
}

int tpusc_ring_len(void* r) {
  Ring* ring = static_cast<Ring*>(r);
  std::shared_lock lk(ring->mu);
  return static_cast<int>(ring->members.size());
}

int tpusc_ring_members(void* r, char* out, int cap) {
  Ring* ring = static_cast<Ring*>(r);
  std::shared_lock lk(ring->mu);
  return write_out(join_lines(ring->members), out, cap);
}

int tpusc_ring_get_n(void* r, const char* key, int n, char* out, int cap) {
  return static_cast<Ring*>(r)->get_n(key, n, out, cap);
}

void* tpusc_lru_new(long long capacity, long long max_items) {
  return new Lru(capacity, max_items);
}

void tpusc_lru_free(void* l) { delete static_cast<Lru*>(l); }

long long tpusc_lru_total(void* l) {
  Lru* lru = static_cast<Lru*>(l);
  std::shared_lock lk(lru->mu);
  return lru->total;
}

int tpusc_lru_len(void* l) {
  Lru* lru = static_cast<Lru*>(l);
  std::shared_lock lk(lru->mu);
  return static_cast<int>(lru->index.size());
}

int tpusc_lru_contains(void* l, const char* key) {
  return static_cast<Lru*>(l)->contains(key) ? 1 : 0;
}

long long tpusc_lru_get(void* l, const char* key, int touch) {
  return static_cast<Lru*>(l)->get(key, touch);
}

// Insert/replace `key` with `size`, evicting LRU entries to fit.
// On success writes '\n'-joined evicted keys (replaced old entry NOT
// included) and returns bytes needed; if the buffer is too small returns the
// needed size WITHOUT mutating (caller retries).  Returns -1 on capacity
// error (item larger than the whole budget).
int tpusc_lru_put(void* l, const char* key, long long size, char* out,
                  int cap) {
  Lru* lru = static_cast<Lru*>(l);
  std::string k(key);
  std::unique_lock lk(lru->mu);
  if (size > lru->capacity) return -1;

  // plan as if the old entry were already gone; like the Python tier,
  // max_items == 0 still admits the new item after draining everything
  std::vector<std::string> plan = lru->plan_evictions(size, 1, &k);
  std::string joined = join_lines(plan);
  int needed = static_cast<int>(joined.size()) + 1;
  if (needed > cap) {
    return needed;  // caller retries with a bigger buffer; nothing mutated
  }

  lru->drop(k);
  for (const auto& ek : plan) lru->drop(ek);
  lru->order.emplace_back(k, size);
  lru->index[k] = std::prev(lru->order.end());
  lru->total += size;
  std::memcpy(out, joined.c_str(), needed);
  return needed;
}

// returns old size if removed, -1 if absent
long long tpusc_lru_remove(void* l, const char* key) {
  Lru* lru = static_cast<Lru*>(l);
  std::unique_lock lk(lru->mu);
  auto it = lru->index.find(key);
  if (it == lru->index.end()) return -1;
  long long size = it->second->second;
  lru->total -= size;
  lru->order.erase(it->second);
  lru->index.erase(it);
  return size;
}

// Evict until `n` bytes are free.  Same buffer protocol as put; -1 when n
// exceeds the whole capacity.
int tpusc_lru_ensure_free(void* l, long long n, char* out, int cap) {
  Lru* lru = static_cast<Lru*>(l);
  std::unique_lock lk(lru->mu);
  if (n > lru->capacity) return -1;
  std::vector<std::string> plan = lru->plan_evictions(n, 0, nullptr);
  std::string joined = join_lines(plan);
  int needed = static_cast<int>(joined.size()) + 1;
  if (needed > cap) return needed;
  for (const auto& ek : plan) lru->drop(ek);
  std::memcpy(out, joined.c_str(), needed);
  return needed;
}

// '\n'-joined keys; mru_first mirrors the reference ListModels order
int tpusc_lru_keys(void* l, int mru_first, char* out, int cap) {
  Lru* lru = static_cast<Lru*>(l);
  std::shared_lock lk(lru->mu);
  std::vector<std::string> keys;
  keys.reserve(lru->order.size());
  for (const auto& kv : lru->order) keys.push_back(kv.first);  // LRU first
  if (mru_first) std::reverse(keys.begin(), keys.end());
  return write_out(join_lines(keys), out, cap);
}

void tpusc_lru_clear(void* l) {
  Lru* lru = static_cast<Lru*>(l);
  std::unique_lock lk(lru->mu);
  lru->order.clear();
  lru->index.clear();
  lru->total = 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// JSON tensor encoder
//
// The REST ":predict" response path serializes output tensors as JSON number
// lists; CPython's json.dumps walks a Python list tree at ~1 M floats/s,
// which caps an LM's (B, vocab) last-token response at <100 qps per host
// core.  This encoder writes the nested-list JSON straight from the numpy
// buffer with std::to_chars (shortest round-trip representation for the
// SOURCE dtype, so float32 prints "0.1", not the 17-digit double repr of
// the nearest double — parse-equal after the client's float32 cast, and
// ~40% smaller).  Non-finite values print the tokens Python's json module
// emits (NaN / Infinity / -Infinity) so existing clients see no change.
// ---------------------------------------------------------------------------

namespace {

template <typename T>
inline char* write_num(char* p, T v) {
  auto r = std::to_chars(p, p + 32, v);
  return r.ptr;
}

template <typename T>
inline char* write_float(char* p, T v) {
  if (std::isfinite(v)) {
    auto r = std::to_chars(p, p + 30, v);
    // json.dumps prints integral floats as "3.0", and json.loads turns a
    // bare "3" into an int — keep the float-typedness on the wire
    bool has_mark = false;
    for (char* q = p; q != r.ptr; ++q) {
      if (*q == '.' || *q == 'e' || *q == 'E') { has_mark = true; break; }
    }
    if (!has_mark) { *r.ptr++ = '.'; *r.ptr++ = '0'; }
    return r.ptr;
  }
  const char* s = std::isnan(v) ? "NaN" : (v > 0 ? "Infinity" : "-Infinity");
  size_t n = std::strlen(s);
  std::memcpy(p, s, n);
  return p + n;
}

inline char* write_bool(char* p, uint8_t v) {
  const char* s = v ? "true" : "false";
  size_t n = std::strlen(s);
  std::memcpy(p, s, n);
  return p + n;
}

template <typename T, typename Writer>
char* enc_dim(const T*& d, const int64_t* shape, int ndim, int dim, char* p,
              Writer w) {
  if (dim == ndim) {
    return w(p, *d++);
  }
  *p++ = '[';
  for (int64_t i = 0; i < shape[dim]; ++i) {
    if (i) { *p++ = ','; *p++ = ' '; }  // ", " = json.dumps's default
    p = enc_dim(d, shape, ndim, dim + 1, p, w);
  }
  *p++ = ']';
  return p;
}

template <typename T, typename Writer>
long long enc_typed(const void* data, const int64_t* shape, int ndim,
                    char* out, long long cap, int per_elem, Writer w) {
  long long n = 1, brackets = 1;
  for (int k = 0; k < ndim; ++k) {
    n *= shape[k];
    if (k + 1 < ndim) brackets += n;
  }
  if (ndim == 0) brackets = 0;
  // worst case: every element + ", " separator + ".0", every bracket pair
  long long bound = n * (per_elem + 4) + brackets * 2 + 16;
  if (bound > cap) return -bound;  // caller retries with the returned size
  const T* d = static_cast<const T*>(data);
  char* p = enc_dim(d, shape, ndim, 0, out, w);
  return p - out;
}

}  // namespace

extern "C" {

// kind: 1=float32 2=float64 3=int32 4=int64 5=bool8 6=uint8
// Returns bytes written; -1 = unsupported kind; any other negative value is
// -(required capacity) — retry with that size.
long long tpusc_json_encode(const void* data, int kind, const int64_t* shape,
                            int ndim, char* out, long long cap) {
  switch (kind) {
    case 1:
      return enc_typed<float>(data, shape, ndim, out, cap, 24,
                              [](char* p, float v) { return write_float(p, v); });
    case 2:
      return enc_typed<double>(data, shape, ndim, out, cap, 26,
                               [](char* p, double v) { return write_float(p, v); });
    case 3:
      return enc_typed<int32_t>(data, shape, ndim, out, cap, 12,
                                [](char* p, int32_t v) { return write_num(p, v); });
    case 4:
      return enc_typed<int64_t>(data, shape, ndim, out, cap, 21,
                                [](char* p, int64_t v) { return write_num(p, v); });
    case 5:
      return enc_typed<uint8_t>(data, shape, ndim, out, cap, 6,
                                [](char* p, uint8_t v) { return write_bool(p, v); });
    case 6:
      return enc_typed<uint8_t>(data, shape, ndim, out, cap, 4,
                                [](char* p, uint8_t v) { return write_num(p, v); });
    default:
      return -1;
  }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// JSON request parser with dense-tensor extraction
//
// The decode side of the REST hot path: json.loads of a ":predict" body
// builds one Python object per number (~1 M numbers/s).  This parser walks
// the body once; every maximal dense numeric array of >= kTensorMinElems
// elements becomes a flat typed buffer + shape, and the remaining skeleton
// (envelope keys, small lists, strings) is re-emitted as JSON with each
// extracted tensor replaced by a placeholder string the Python side swaps
// for a numpy array.  Anything unusual — ragged shapes, mixed types, depth
// bombs, out-of-range ints — declines extraction (span-copied verbatim) or
// fails the whole parse, and the caller falls back to Python json.loads.
//
// Number/int semantics match Python's json: a token is integral iff it has
// no '.', 'e', 'E'; NaN/Infinity/-Infinity are accepted as doubles.
// ---------------------------------------------------------------------------

namespace jsonp {

constexpr int kMaxDepth = 64;
constexpr int kTensorMaxDims = 32;  // = the ctypes bridge's shape buffer
constexpr long long kTensorMinElems = 64;

struct Tensor {
  bool is_int = true;
  std::vector<int64_t> shape;
  std::vector<double> vals;
  std::vector<int64_t> ivals;
};

struct Parser {
  const char* s;
  long long n;
  long long i = 0;
  bool ok = true;
  bool declined = false;  // structurally fine for json.loads, beyond us
  std::string err;
  std::string out;          // skeleton JSON
  std::string nonce;
  std::vector<Tensor> tensors;

  explicit Parser(const char* text, long long len, const char* nonce_)
      : s(text), n(len), nonce(nonce_) {
    out.reserve(256);
  }

  void fail(const std::string& m) {
    if (ok) {
      ok = false;
      err = m + " at offset " + std::to_string(i);
    }
  }

  void skip_ws() {
    while (i < n && (s[i] == ' ' || s[i] == '\t' || s[i] == '\n' || s[i] == '\r'))
      i++;
  }

  bool lit(const char* w) {
    long long len = static_cast<long long>(std::strlen(w));
    if (i + len <= n && std::memcmp(s + i, w, len) == 0) {
      i += len;
      return true;
    }
    return false;
  }

  // scan a string token (assumes s[i] == '"'); returns false on error
  bool scan_string() {
    i++;  // opening quote
    while (i < n) {
      unsigned char c = s[i];
      if (c == '"') {
        i++;
        return true;
      }
      if (c == '\\') {
        i += 2;
        if (i > n) break;
        continue;
      }
      if (c < 0x20) {
        fail("control character in string");
        return false;
      }
      i++;
    }
    fail("unterminated string");
    return false;
  }

  // scan a number token; *integral = no '.', 'e', 'E'
  bool scan_number(bool* integral) {
    long long start = i;
    *integral = true;
    if (i < n && s[i] == '-') i++;
    if (i >= n || !(s[i] >= '0' && s[i] <= '9')) {
      fail("bad number");
      return false;
    }
    if (s[i] == '0') {
      i++;  // JSON: a leading zero cannot be followed by more digits
      if (i < n && s[i] >= '0' && s[i] <= '9') {
        fail("leading zero");
        return false;
      }
    } else {
      while (i < n && s[i] >= '0' && s[i] <= '9') i++;
    }
    if (i < n && s[i] == '.') {
      *integral = false;
      i++;
      if (i >= n || !(s[i] >= '0' && s[i] <= '9')) {
        fail("bad number fraction");
        return false;
      }
      while (i < n && s[i] >= '0' && s[i] <= '9') i++;
    }
    if (i < n && (s[i] == 'e' || s[i] == 'E')) {
      *integral = false;
      i++;
      if (i < n && (s[i] == '+' || s[i] == '-')) i++;
      if (i >= n || !(s[i] >= '0' && s[i] <= '9')) {
        fail("bad number exponent");
        return false;
      }
      while (i < n && s[i] >= '0' && s[i] <= '9') i++;
    }
    (void)start;
    return true;
  }

  // Try to parse the array starting at s[i] (s[i]=='[') as a dense numeric
  // nd-array into t.  On structural mismatch (non-number leaf, ragged),
  // returns false with i restored — caller re-parses generically.  Hard
  // syntax errors set ok=false.
  bool try_tensor(Tensor* t) {
    long long save = i;
    t->shape.clear();
    t->vals.clear();
    t->ivals.clear();
    t->is_int = true;
    std::vector<int64_t> shape;      // discovered on the first spine
    std::vector<int64_t> counts;     // current index per depth
    if (!tensor_dim(t, &shape, 0)) {
      i = save;
      return false;
    }
    t->shape = shape;
    long long total = 1;
    for (int64_t d : shape) total *= d;
    if (total != static_cast<long long>(t->vals.size())) {
      i = save;  // decline cleanly: the generic path re-parses from the start
      return false;
    }
    (void)counts;
    return ok;
  }

  bool tensor_dim(Tensor* t, std::vector<int64_t>* shape, int depth) {
    if (depth >= kTensorMaxDims) return false;  // rank-capped: decline, not fail
    if (i >= n || s[i] != '[') return false;
    i++;
    skip_ws();
    bool first_spine = static_cast<int>(shape->size()) <= depth;
    if (first_spine) shape->push_back(0);
    int64_t count = 0;
    bool saw_leaf = false, saw_arr = false;
    if (i < n && s[i] == ']') {
      i++;
      // empty dim: record 0; deeper shape unknown — only accept if this is
      // the innermost level seen so far (shape stays [..., 0])
      if (!first_spine && (*shape)[depth] != 0) return false;
      (*shape)[depth] = 0;
      return true;
    }
    while (i < n) {
      skip_ws();
      if (i < n && s[i] == '[') {
        if (saw_leaf) return false;  // mixed leaf/array siblings: not dense
        saw_arr = true;
        if (!tensor_dim(t, shape, depth + 1)) return false;
      } else {
        if (saw_arr) return false;
        saw_leaf = true;
        // numeric leaf required; leaves only allowed at the deepest level
        if (static_cast<int>(shape->size()) != depth + 1) return false;
        long long tok_start = i;
        if (i < n && (s[i] == 'N' || s[i] == 'I' ||
                      (s[i] == '-' && i + 1 < n && s[i + 1] == 'I'))) {
          // NaN / Infinity / -Infinity
          double v;
          if (lit("NaN")) v = std::nan("");
          else if (lit("Infinity")) v = std::numeric_limits<double>::infinity();
          else if (lit("-Infinity")) v = -std::numeric_limits<double>::infinity();
          else return false;
          t->is_int = false;
          t->vals.push_back(v);
        } else if (i < n && (s[i] == '-' || (s[i] >= '0' && s[i] <= '9'))) {
          bool integral;
          if (!scan_number(&integral)) return false;  // hard error recorded
          double d;
          auto r = std::from_chars(s + tok_start, s + i, d);
          if (r.ec != std::errc()) return false;
          t->vals.push_back(d);
          if (t->is_int && integral) {
            int64_t iv;
            auto ri = std::from_chars(s + tok_start, s + i, iv);
            if (ri.ec != std::errc() || ri.ptr != s + i) {
              // integral but outside int64: a float64 demotion would lose
              // precision AND diverge from the json.loads fallback, which
              // yields exact Python ints (np.asarray gives an exact uint64
              // array for [2^63, 2^64)) — decline extraction so the whole
              // array takes the verbatim/fallback path (ADVICE r3)
              return false;
            } else {
              t->ivals.push_back(iv);
            }
          } else {
            t->is_int = false;
          }
        } else {
          return false;  // string/object/bool/null leaf: not a tensor
        }
      }
      count++;
      skip_ws();
      if (i < n && s[i] == ',') {
        i++;
        continue;
      }
      if (i < n && s[i] == ']') {
        i++;
        break;
      }
      fail("expected ',' or ']'");
      return false;
    }
    if (first_spine) {
      (*shape)[depth] = count;
    } else if ((*shape)[depth] != count) {
      return false;  // ragged
    }
    return true;
  }

  void emit_placeholder(size_t k) {
    out += "\"\\u0007";
    out += nonce;
    out += ':';
    out += std::to_string(k);
    out += '"';
  }

  void value(int depth) {
    if (!ok) return;
    if (depth >= kMaxDepth) {
      declined = true;  // valid JSON may continue deeper: let json.loads try
      fail("nesting too deep");
      return;
    }
    skip_ws();
    if (i >= n) {
      fail("unexpected end");
      return;
    }
    char c = s[i];
    if (c == '{') {
      out += '{';
      i++;
      skip_ws();
      if (i < n && s[i] == '}') {
        i++;
        out += '}';
        return;
      }
      while (ok) {
        skip_ws();
        if (i >= n || s[i] != '"') {
          fail("expected object key");
          return;
        }
        long long key_start = i;
        if (!scan_string()) return;
        out.append(s + key_start, i - key_start);
        skip_ws();
        if (i >= n || s[i] != ':') {
          fail("expected ':'");
          return;
        }
        i++;
        out += ':';
        value(depth + 1);
        if (!ok) return;
        skip_ws();
        if (i < n && s[i] == ',') {
          i++;
          out += ',';
          continue;
        }
        if (i < n && s[i] == '}') {
          i++;
          out += '}';
          return;
        }
        fail("expected ',' or '}'");
        return;
      }
      return;
    }
    if (c == '[') {
      Tensor t;
      long long before = i;
      if (try_tensor(&t) && ok) {
        long long total = static_cast<long long>(t.vals.size());
        if (total >= kTensorMinElems) {
          tensors.push_back(std::move(t));
          emit_placeholder(tensors.size() - 1);
          return;
        }
        // parsed fine but small: keep the original text span verbatim
        out.append(s + before, i - before);
        return;
      }
      if (!ok) return;
      // generic array
      out += '[';
      i++;
      skip_ws();
      if (i < n && s[i] == ']') {
        i++;
        out += ']';
        return;
      }
      while (ok) {
        value(depth + 1);
        if (!ok) return;
        skip_ws();
        if (i < n && s[i] == ',') {
          i++;
          out += ',';
          continue;
        }
        if (i < n && s[i] == ']') {
          i++;
          out += ']';
          return;
        }
        fail("expected ',' or ']'");
        return;
      }
      return;
    }
    if (c == '"') {
      long long start = i;
      if (!scan_string()) return;
      out.append(s + start, i - start);
      return;
    }
    if (lit("true")) { out += "true"; return; }
    if (lit("false")) { out += "false"; return; }
    if (lit("null")) { out += "null"; return; }
    if (lit("NaN")) { out += "NaN"; return; }
    if (lit("Infinity")) { out += "Infinity"; return; }
    if (c == '-' && lit("-Infinity")) { out += "-Infinity"; return; }
    if (c == '-' || (c >= '0' && c <= '9')) {
      long long start = i;
      bool integral;
      if (!scan_number(&integral)) return;
      out.append(s + start, i - start);
      return;
    }
    fail("unexpected character");
  }

  void parse() {
    value(0);
    if (!ok) return;
    skip_ws();
    if (i != n) fail("trailing data");
  }
};

}  // namespace jsonp

extern "C" {

void* tpusc_json_parse(const char* text, long long len, const char* nonce) {
  auto* p = new jsonp::Parser(text, len, nonce);
  p->parse();
  return p;
}

int tpusc_jp_ok(void* h) { return static_cast<jsonp::Parser*>(h)->ok ? 1 : 0; }

int tpusc_jp_declined(void* h) {
  return static_cast<jsonp::Parser*>(h)->declined ? 1 : 0;
}

const char* tpusc_jp_error(void* h) {
  return static_cast<jsonp::Parser*>(h)->err.c_str();
}

const char* tpusc_jp_skeleton(void* h, long long* len) {
  auto* p = static_cast<jsonp::Parser*>(h);
  *len = static_cast<long long>(p->out.size());
  return p->out.data();
}

int tpusc_jp_ntensors(void* h) {
  return static_cast<int>(static_cast<jsonp::Parser*>(h)->tensors.size());
}

// -> ndim; shape copied into shape_out (cap entries); is_int + nelems set
int tpusc_jp_tensor_info(void* h, int k, int* is_int, int64_t* shape_out,
                         int cap, long long* nelems) {
  auto& t = static_cast<jsonp::Parser*>(h)->tensors[k];
  *is_int = t.is_int ? 1 : 0;
  int ndim = static_cast<int>(t.shape.size());
  for (int d = 0; d < ndim && d < cap; d++) shape_out[d] = t.shape[d];
  *nelems = static_cast<long long>(t.vals.size());
  return ndim;
}

const void* tpusc_jp_tensor_data(void* h, int k) {
  auto& t = static_cast<jsonp::Parser*>(h)->tensors[k];
  return t.is_int ? static_cast<const void*>(t.ivals.data())
                  : static_cast<const void*>(t.vals.data());
}

void tpusc_jp_free(void* h) { delete static_cast<jsonp::Parser*>(h); }

}  // extern "C"

"""Native (C++) runtime components, loaded via ctypes with Python fallback.

The reference's runtime is native code (Go — pkg/taskhandler/cluster.go,
pkg/cachemanager/lrucache.go); here the equivalent hot-path pieces are C++
(src/tpusc_native.cc) behind a plain-C ABI:

  - BLAKE2b-64 hashing (placement hash, RFC 7693)
  - consistent-hash ring (``NativeHashRing`` — same placement as the Python
    ``HashRing``, verified bit-exact by tests/test_native.py)
  - byte-budgeted LRU index (``NativeLRUCache`` — same semantics as
    ``cache.lru.LRUCache``)

Loading order: prebuilt ``libtpusc_native.so`` next to this file, else a
one-shot ``make`` build if a toolchain exists, else ``load()`` returns None
and callers fall back to the pure-Python implementations.  Set
``TPUSC_NO_NATIVE=1`` to force the fallback.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Any, Callable, Generic, Hashable, Iterator, TypeVar

from tfservingcache_tpu.cache.lru import CapacityError, LRUEntry

from tfservingcache_tpu.utils.lockcheck import lockchecked

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libtpusc_native.so")

_lib: ctypes.CDLL | None = None
_load_attempted = False
_load_lock = threading.Lock()


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.tpusc_blake2b64.argtypes = [ctypes.c_char_p, ctypes.c_longlong]
    lib.tpusc_blake2b64.restype = ctypes.c_ulonglong
    lib.tpusc_ring_new.argtypes = [ctypes.c_int]
    lib.tpusc_ring_new.restype = ctypes.c_void_p
    lib.tpusc_ring_free.argtypes = [ctypes.c_void_p]
    lib.tpusc_ring_set_members.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
    ]
    lib.tpusc_ring_len.argtypes = [ctypes.c_void_p]
    lib.tpusc_ring_len.restype = ctypes.c_int
    lib.tpusc_ring_members.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpusc_ring_members.restype = ctypes.c_int
    lib.tpusc_ring_get_n.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpusc_ring_get_n.restype = ctypes.c_int
    lib.tpusc_lru_new.argtypes = [ctypes.c_longlong, ctypes.c_longlong]
    lib.tpusc_lru_new.restype = ctypes.c_void_p
    lib.tpusc_lru_free.argtypes = [ctypes.c_void_p]
    lib.tpusc_lru_total.argtypes = [ctypes.c_void_p]
    lib.tpusc_lru_total.restype = ctypes.c_longlong
    lib.tpusc_lru_len.argtypes = [ctypes.c_void_p]
    lib.tpusc_lru_len.restype = ctypes.c_int
    lib.tpusc_lru_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpusc_lru_contains.restype = ctypes.c_int
    lib.tpusc_lru_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int]
    lib.tpusc_lru_get.restype = ctypes.c_longlong
    lib.tpusc_lru_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_longlong,
        ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpusc_lru_put.restype = ctypes.c_int
    lib.tpusc_lru_remove.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.tpusc_lru_remove.restype = ctypes.c_longlong
    lib.tpusc_lru_ensure_free.argtypes = [
        ctypes.c_void_p, ctypes.c_longlong, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpusc_lru_ensure_free.restype = ctypes.c_int
    lib.tpusc_lru_keys.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    ]
    lib.tpusc_lru_keys.restype = ctypes.c_int
    lib.tpusc_lru_clear.argtypes = [ctypes.c_void_p]
    lib.tpusc_json_encode.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int, ctypes.c_char_p, ctypes.c_longlong,
    ]
    lib.tpusc_json_encode.restype = ctypes.c_longlong
    lib.tpusc_json_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_longlong, ctypes.c_char_p,
    ]
    lib.tpusc_json_parse.restype = ctypes.c_void_p
    lib.tpusc_jp_ok.argtypes = [ctypes.c_void_p]
    lib.tpusc_jp_ok.restype = ctypes.c_int
    lib.tpusc_jp_declined.argtypes = [ctypes.c_void_p]
    lib.tpusc_jp_declined.restype = ctypes.c_int
    lib.tpusc_jp_error.argtypes = [ctypes.c_void_p]
    lib.tpusc_jp_error.restype = ctypes.c_char_p
    lib.tpusc_jp_skeleton.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.tpusc_jp_skeleton.restype = ctypes.c_void_p
    lib.tpusc_jp_ntensors.argtypes = [ctypes.c_void_p]
    lib.tpusc_jp_ntensors.restype = ctypes.c_int
    lib.tpusc_jp_tensor_info.argtypes = [
        ctypes.c_void_p, ctypes.c_int, ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_longlong),
    ]
    lib.tpusc_jp_tensor_info.restype = ctypes.c_int
    lib.tpusc_jp_tensor_data.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.tpusc_jp_tensor_data.restype = ctypes.c_void_p
    lib.tpusc_jp_free.argtypes = [ctypes.c_void_p]
    return lib


def load() -> ctypes.CDLL | None:
    """The native library, building it on first use if needed; None if
    unavailable (no toolchain / disabled)."""
    global _lib, _load_attempted
    if _lib is not None:
        return _lib
    with _load_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if os.environ.get("TPUSC_NO_NATIVE"):
            return None
        # Always (re)run make when a toolchain exists — it no-ops when the .so
        # is current and rebuilds after source edits, so a stale library can't
        # silently diverge from src/ (placement parity depends on this).  An
        # existing .so is still used if the toolchain is gone.
        try:
            subprocess.run(
                ["make", "-C", _DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except (OSError, subprocess.SubprocessError):
            if not os.path.exists(_LIB_PATH):
                return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except (OSError, AttributeError):
            # AttributeError: a stale prebuilt .so predating a newer symbol
            # (no toolchain to rebuild) must not take down the whole tier
            return None
        return _lib


def native_available() -> bool:
    return load() is not None


def blake2b64(data: bytes) -> int:
    lib = load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return int(lib.tpusc_blake2b64(data, len(data)))


def _call_buffered(fn: Callable[[ctypes.Array, int], int], initial: int = 4096) -> list[str]:
    """Run a needed-size-returning C call, growing the buffer on demand;
    decode the '\\n'-joined result."""
    cap = initial
    while True:
        buf = ctypes.create_string_buffer(cap)
        needed = fn(buf, cap)
        if needed < 0:
            raise CapacityError("native tier reported a capacity violation")
        if needed <= cap:
            raw = buf.value.decode()
            return raw.split("\n") if raw else []
        cap = needed


class NativeHashRing:
    """Drop-in for ``cluster.hashring.HashRing`` backed by the C++ ring.

    Placement-identical to the Python ring (same BLAKE2b-64 points, same
    vnode naming ``member#i``, same tie-break) so mixed native/fallback
    fleets agree on every key's owners.
    """

    def __init__(self, vnodes: int = 160) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.vnodes = vnodes
        self._ptr = lib.tpusc_ring_new(vnodes)

    def __del__(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.tpusc_ring_free(ptr)

    def set_members(self, members: list[str]) -> None:
        for m in members:
            if not m or "\n" in m or "\x00" in m:
                raise ValueError(f"member {m!r} not representable in the native ring")
        arr = (ctypes.c_char_p * len(members))(
            *[m.encode() for m in members]
        )
        self._lib.tpusc_ring_set_members(self._ptr, arr, len(members))

    @property
    def members(self) -> set[str]:
        return set(
            _call_buffered(lambda b, c: self._lib.tpusc_ring_members(self._ptr, b, c))
        )

    def __len__(self) -> int:
        return int(self._lib.tpusc_ring_len(self._ptr))

    def get_n(self, key: str, n: int) -> list[str]:
        kb = key.encode()
        return _call_buffered(
            lambda b, c: self._lib.tpusc_ring_get_n(self._ptr, kb, n, b, c)
        )

    def get(self, key: str) -> str | None:
        nodes = self.get_n(key, 1)
        return nodes[0] if nodes else None


def make_ring(vnodes: int = 160):
    """Native ring when available, Python fallback otherwise."""
    if native_available():
        return NativeHashRing(vnodes)
    from tfservingcache_tpu.cluster.hashring import HashRing

    return HashRing(vnodes)


K = TypeVar("K", bound=Hashable)
V = TypeVar("V")


def _key_str(key: Any) -> str:
    # ModelId carries its canonical routing key; anything else must have a
    # stable, unique str().  Keys travel across the C ABI as NUL-terminated,
    # '\n'-joined strings, so those bytes (and the empty string) are rejected
    # loudly instead of silently corrupting eviction reporting.
    s = key.key if hasattr(key, "key") else str(key)
    if not s or "\n" in s or "\x00" in s:
        raise ValueError(f"key {key!r} not representable in the native tier")
    return s


@lockchecked
class NativeLRUCache(Generic[K, V]):
    """Drop-in for ``cache.lru.LRUCache``: the (key, size, order, budget)
    index lives in C++; payloads and evict callbacks stay on the Python side.

    Same contract as the Python tier: thread-safe, single eviction pass per
    put, oversized items rejected, callbacks run outside the native lock.
    """

    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_payloads": "_lock"}

    def __init__(
        self,
        capacity_bytes: int,
        on_evict: Callable[[K, LRUEntry[V]], None] | None = None,
        max_items: int | None = None,
    ) -> None:
        lib = load()
        if lib is None:
            raise RuntimeError("native library unavailable")
        self._lib = lib
        self.capacity_bytes = int(capacity_bytes)
        self.max_items = max_items
        self._on_evict = on_evict
        self._lock = threading.RLock()  # guards the Python-side payload map
        self._payloads: dict[str, tuple[K, LRUEntry[V]]] = {}
        self._ptr = lib.tpusc_lru_new(
            self.capacity_bytes, -1 if max_items is None else int(max_items)
        )

    def __del__(self) -> None:
        ptr, self._ptr = getattr(self, "_ptr", None), None
        if ptr and getattr(self, "_lib", None) is not None:
            self._lib.tpusc_lru_free(ptr)

    # -- introspection ------------------------------------------------------
    @property
    def total_bytes(self) -> int:
        return int(self._lib.tpusc_lru_total(self._ptr))

    def __len__(self) -> int:
        return int(self._lib.tpusc_lru_len(self._ptr))

    def __contains__(self, key: K) -> bool:
        return bool(self._lib.tpusc_lru_contains(self._ptr, _key_str(key).encode()))

    def _keys(self, mru_first: bool) -> list[str]:
        return _call_buffered(
            lambda b, c: self._lib.tpusc_lru_keys(self._ptr, int(mru_first), b, c)
        )

    def keys_mru_first(self) -> list[K]:
        with self._lock:
            return [self._payloads[s][0] for s in self._keys(True) if s in self._payloads]

    def items_lru_first(self) -> Iterator[tuple[K, LRUEntry[V]]]:
        with self._lock:
            return iter(
                [self._payloads[s] for s in self._keys(False) if s in self._payloads]
            )

    # -- core ---------------------------------------------------------------
    def get(self, key: K, touch: bool = True) -> V | None:
        s = _key_str(key)
        # lock spans the native call so a concurrent put/remove of the same
        # key can't desync the native index from the payload map
        with self._lock:
            size = self._lib.tpusc_lru_get(self._ptr, s.encode(), int(touch))
            if size < 0:
                return None
            held = self._payloads.get(s)
        return held[1].payload if held is not None else None

    def put(self, key: K, size_bytes: int, payload: V) -> list[K]:
        s = _key_str(key)
        size_bytes = int(size_bytes)
        if size_bytes > self.capacity_bytes:
            raise CapacityError(
                f"item {key!r} ({size_bytes}B) exceeds cache capacity {self.capacity_bytes}B"
            )
        sb = s.encode()
        with self._lock:
            old = self._payloads.get(s)
            evicted_keys = _call_buffered(
                lambda b, c: self._lib.tpusc_lru_put(self._ptr, sb, size_bytes, b, c)
            )
            evicted: list[tuple[K, LRUEntry[V]]] = []
            if old is not None:
                evicted.append(old)
            for ek in evicted_keys:
                held = self._payloads.pop(ek, None)
                if held is not None:
                    evicted.append(held)
            self._payloads[s] = (key, LRUEntry(size_bytes, payload))
        self._run_callbacks(evicted)
        return [k for k, _ in evicted if _key_str(k) != s]

    def remove(self, key: K, run_callback: bool = False) -> V | None:
        s = _key_str(key)
        with self._lock:
            if self._lib.tpusc_lru_remove(self._ptr, s.encode()) < 0:
                return None
            held = self._payloads.pop(s, None)
        if held is None:
            return None
        if run_callback and self._on_evict is not None:
            self._on_evict(held[0], held[1])
        return held[1].payload

    def ensure_free_bytes(self, n: int) -> list[K]:
        n = int(n)
        if n > self.capacity_bytes:
            raise CapacityError(
                f"requested {n}B free exceeds cache capacity {self.capacity_bytes}B"
            )
        with self._lock:
            keys = _call_buffered(
                lambda b, c: self._lib.tpusc_lru_ensure_free(self._ptr, n, b, c)
            )
            evicted = [self._payloads.pop(s) for s in keys if s in self._payloads]
        self._run_callbacks(evicted)
        return [k for k, _ in evicted]

    def clear(self) -> None:
        with self._lock:
            evicted = list(self._payloads.values())
            self._payloads.clear()
            self._lib.tpusc_lru_clear(self._ptr)
        self._run_callbacks(evicted)

    def _run_callbacks(self, evicted: list[tuple[K, LRUEntry[V]]]) -> None:
        if self._on_evict is None:
            return
        for key, entry in evicted:
            self._on_evict(key, entry)


def make_lru_cache(
    capacity_bytes: int,
    on_evict: Callable[[Any, LRUEntry[Any]], None] | None = None,
    max_items: int | None = None,
):
    """Native LRU tier when available, Python fallback otherwise."""
    if native_available():
        return NativeLRUCache(capacity_bytes, on_evict, max_items)
    from tfservingcache_tpu.cache.lru import LRUCache

    return LRUCache(capacity_bytes, on_evict, max_items)


# -- JSON tensor encoder ------------------------------------------------------

# numpy dtype name -> tpusc_json_encode kind (src/tpusc_native.cc)
_JSON_KINDS = {
    "float32": 1, "float64": 2, "int32": 3, "int64": 4, "bool": 5, "uint8": 6,
}


def json_encode_array(arr) -> bytes | None:
    """JSON nested-list text for a numeric ndarray, written straight from the
    buffer by the native encoder — ~10x json.dumps(arr.tolist()) on the REST
    response hot path. Returns None when the library or dtype is unavailable
    (caller falls back to the Python path). Float text is the shortest
    round-trip repr for the SOURCE dtype; non-finite values use Python's
    json tokens (NaN/Infinity/-Infinity)."""
    import numpy as np

    lib = load()
    if lib is None:
        return None
    a = np.asarray(arr)
    if not a.dtype.isnative:
        return None  # C++ reads host byte order; '>f4' etc. take the Python path
    if not a.flags["C_CONTIGUOUS"]:
        # NOT ascontiguousarray unconditionally: it promotes 0-d to 1-d,
        # which would wrap a scalar response in brackets
        a = np.ascontiguousarray(a)
    kind = _JSON_KINDS.get(a.dtype.name)
    if kind is None:
        return None
    ndim = a.ndim
    shape = (ctypes.c_int64 * max(ndim, 1))(*(a.shape or (0,)))
    # first-try guess; the C side owns the real bound and returns -(needed)
    # when this is short, so the width tables can't drift apart
    cap = int(a.size) * 14 + 64
    for _ in range(2):
        buf = ctypes.create_string_buffer(cap)
        wrote = lib.tpusc_json_encode(
            a.ctypes.data_as(ctypes.c_void_p), kind, shape, ndim, buf, cap
        )
        if wrote >= 0:
            return buf.raw[:wrote]
        if wrote == -1:
            return None
        cap = -wrote
    return None


# -- JSON request parser ------------------------------------------------------

_PARSE_NONCE = None


def json_parse_request(body: bytes):
    """Parse a JSON request body with dense numeric subtrees extracted as
    numpy arrays (int64 when every token is integral, else float64).

    Returns the parsed structure, or None when the native tier is
    unavailable or declines (caller falls back to ``json.loads``). Raises
    ``ValueError`` for bodies the parser proves malformed — message parity
    with json.loads is NOT guaranteed, so callers should re-raise through
    their existing error mapping.

    Extraction marks subtrees with a per-process nonce'd placeholder string,
    so payload strings cannot collide with placeholders across processes;
    a literal placeholder string inside the SAME request could only remap
    that request's own tensors, never another request's."""
    import secrets

    import numpy as np

    global _PARSE_NONCE
    lib = load()
    if lib is None:
        return None
    if _PARSE_NONCE is None:
        _PARSE_NONCE = secrets.token_hex(8)
    nonce = _PARSE_NONCE
    h = lib.tpusc_json_parse(body, len(body), nonce.encode())
    if not h:
        return None
    try:
        if not lib.tpusc_jp_ok(h):
            if lib.tpusc_jp_declined(h):
                return None  # beyond this parser (e.g. depth), not malformed
            raise ValueError(
                (lib.tpusc_jp_error(h) or b"invalid JSON").decode()
            )
        slen = ctypes.c_longlong()
        sptr = lib.tpusc_jp_skeleton(h, ctypes.byref(slen))
        skeleton = ctypes.string_at(sptr, slen.value)
        import json

        tree = json.loads(skeleton)
        nt = lib.tpusc_jp_ntensors(h)
        if nt == 0:
            return tree
        arrays = []
        for k in range(nt):
            is_int = ctypes.c_int()
            nelems = ctypes.c_longlong()
            shape = (ctypes.c_int64 * 32)()
            ndim = lib.tpusc_jp_tensor_info(
                h, k, ctypes.byref(is_int), shape, 32, ctypes.byref(nelems)
            )
            dt = np.int64 if is_int.value else np.float64
            data = lib.tpusc_jp_tensor_data(h, k)
            flat = np.ctypeslib.as_array(
                ctypes.cast(data, ctypes.POINTER(ctypes.c_int64 if is_int.value
                                                 else ctypes.c_double)),
                shape=(max(nelems.value, 0),),
            )
            arrays.append(
                flat.astype(dt, copy=True).reshape(tuple(shape[:ndim]))
            )
        prefix = "\x07" + nonce + ":"

        def swap(v):
            if isinstance(v, dict):
                return {k2: swap(x) for k2, x in v.items()}
            if isinstance(v, list):
                return [swap(x) for x in v]
            if isinstance(v, str) and v.startswith(prefix):
                idx = int(v[len(prefix):])
                return arrays[idx]
            return v

        return swap(tree)
    finally:
        lib.tpusc_jp_free(h)

"""Config system.

Reference equivalent: cmd/taskhandler/cfg.go:10-62 (viper: ./config.yaml +
``TFSC_``-prefixed env vars with ``.`` -> ``_`` mapping). Key design change
noted in SURVEY.md §2 C2: the reference reads viper keys ad-hoc deep inside
libraries; here the whole config is parsed once into typed dataclasses and
injected, so every component is constructible in tests without global state.

Env override: ``TPUSC_<KEY>`` where dots become underscores, e.g.
``TPUSC_CACHE_DISK_CAPACITY_BYTES=1000`` overrides ``cache.disk_capacity_bytes``
(mirrors reference cfg.go:15-17 semantics with the new prefix).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field
from typing import Any

import yaml

ENV_PREFIX = "TPUSC_"


@dataclass
class ServingConfig:
    """In-process JAX serving runtime (replaces reference's external TF Serving
    block, config.yaml:29-37)."""

    max_concurrent_models: int = 16        # models resident in HBM at once
    hbm_capacity_bytes: int = 8 << 30      # HBM byte budget for pinned params
    warmup: bool = True                    # run one predict to pin+compile on load
    compile_cache_dir: str = ""            # persistent XLA compile cache ("" = off)
    # cold-load (fetch+compile) deadline; 0 disables. The reference hardcodes
    # a 10 s fetch timeout (main.go:122); XLA first-compiles can take longer,
    # so the default is looser. Enforced by CacheManager.ensure_servable.
    load_timeout_s: float = 30.0
    platform: str = ""                     # "" = default jax backend; "cpu" forces CPU
    # adaptive micro-batching (TF Serving --enable_batching equivalent,
    # in-process now): 0 disables; concurrent same-shape requests within the
    # window coalesce into one device call. Default 0 (OFF) per measured
    # evidence: on the chip (r5, tpu_runs/) LM REST loses consistently with
    # batching (36-66 vs 100-105 QPS) as does r2's mnist REST (-31%); the
    # wins are protocol/family-specific and window-noisy (r5 full run:
    # mnist REST batch 202 vs 161, mnist gRPC batch 199 vs 241 — the
    # OPPOSITE split of the same day's batcher_qps window). A default must
    # hold across families; off does. Enable per-deployment (set 1-2 ms)
    # only when profiling shows concurrent same-shape warm traffic whose
    # batched device call beats the window latency — e.g. many-client
    # fan-in on one cheap-decode model (bench.py `batcher_qps` section
    # measures exactly this pair).
    batch_window_ms: float = 0.0
    batch_max_size: int = 64
    # Prefix KV cache for :generate (runtime/prefix_cache.py): byte budget
    # of device memory for reusable prompt-prefix K/V. 0 = off (default —
    # entries hold real HBM). Single-group runtimes only; B=1 requests.
    prefix_cache_bytes: int = 0
    # Pipelined cold load (runtime/model_runtime.py): AOT-compile the family
    # executable concurrently with the params transfer, double-buffer the
    # packed H2D chunks, and dequantize leaves as they land, so cold
    # wall-clock ≈ max(stage) instead of Σ(stages). False restores the
    # strictly serialized stage-after-stage path (identical results, one
    # flag away). Multi-PROCESS mesh runtimes always run serialized — the
    # cross-host lockstep device-op stream must not depend on host thread
    # timing; single-process meshes pipeline when mesh_fast_path is on.
    cold_load_pipeline: bool = True
    # Mesh parity for the fast path (ISSUE 20): single-process mesh
    # runtimes run the same pipelined cold load, host warm tier, packed
    # adoption, and continuous/paged :generate engine as single-chip
    # runtimes, with params and KV arenas sharded per the family's
    # partition rules. False restores the pre-parity behavior (serialized
    # loads, coalesce generate) — the A/B lever the mesh_generate bench
    # section flips. Multi-process (cross-host) groups ignore the knob and
    # stay serialized/coalesced: their device-op stream is lockstep.
    mesh_fast_path: bool = True
    # Host buffers the chunk assembler may run ahead of the H2D stream
    # (bounded queue depth; each slot holds up to one ~256 MB packed chunk).
    cold_pipeline_buffer_depth: int = 2
    # :generate engine for the transformer_lm family. "coalesce" (default)
    # keeps batch-formation-time coalescing (GenerateCoalescer): safe,
    # proven, but a request arriving just after a batch launches waits for
    # the whole fixed-length scan, and early-EOS rows burn padded steps
    # until the batch drains. "continuous" enables the slotted
    # iteration-level engine (runtime/batcher.py ContinuousGenerateEngine):
    # a fixed slot array advanced by one compiled decode-chunk program,
    # with admission at chunk boundaries and per-row retirement at EOS /
    # max_new_tokens. Mesh/multi-process runtimes ignore "continuous" and
    # take the coalesce path unconditionally (same rule as
    # cold_load_pipeline: lockstep device-op streams must not depend on a
    # host scheduler thread).
    generate_engine: str = "coalesce"
    # Slot count S of the continuous engine's decode array: one compiled
    # program serves all S lanes; S bounds concurrent decodes per model.
    generate_slots: int = 8
    # Decode steps per device dispatch (chunk size k). k=1 retires rows
    # with zero wasted steps; larger k amortizes host dispatch overhead at
    # the cost of up to k-1 overshoot steps per finishing row (PERF.md
    # "Continuous batching" discusses the tradeoff).
    generate_chunk_tokens: int = 8
    # Chunked prefill interleaving for generate_engine=continuous over a
    # paged arena (ISSUE 19): 0 (default) prefills each admitted prompt in
    # one dispatch — a 2k-token prompt monopolizes the engine for the whole
    # prefill, inflating every other lane's inter-token latency and TTFT.
    # > 0 splits cold-miss prefills into fixed chunks of this many tokens
    # (clamped up to a pow2 so one compiled program serves every chunk):
    # the lane sits in a PREFILLING state and advances one chunk per
    # scheduler boundary while the other lanes keep decoding between
    # chunks. Prompts that fit one chunk, shared-prefix/resume hits, and
    # spec-draft engines take the single-dispatch path unchanged.
    prefill_chunk_tokens: int = 0
    # Paged KV for the continuous engine. 0 (default) keeps the dense
    # per-lane slot array (slots x max_seq rows reserved per lane). > 0
    # replaces it with a shared page arena: fixed pages of kv_page_tokens
    # tokens each, handed out by a free-list at admission (the row's full
    # prompt + max_new budget is pre-reserved) and recycled at retirement,
    # so HBM is sized by tokens in flight instead of worst case.
    kv_page_tokens: int = 0
    # Usable arena pages (one extra trash page is always added). 0 = auto:
    # generate_slots x ceil(max_seq / kv_page_tokens) — the dense-equivalent
    # byte budget; shrink it to cap KV HBM, grow it (with generate_slots) to
    # admit more concurrent rows at the same budget.
    kv_arena_pages: int = 0
    # Cross-request shared-prefix KV over the paged arena
    # (runtime/prefix_cache.py PagePrefixIndex): byte budget of arena pages
    # the radix prefix index may pin for reuse. 0 = off (default). > 0 (and
    # kv_page_tokens > 0) makes admission map the longest page-aligned
    # shared prompt prefix read-only into a new row's block table (refcount
    # bump, no prefill compute over the shared part, no page copy) and
    # reserve only the private suffix + max_new pages — N concurrent
    # same-system-prompt rows pay O(1) arena memory for the prefix. Pages a
    # lane would write into are copy-on-write; index-held pages are
    # reclaimed under admission pressure before a request is ever blocked.
    kv_share_prefix_bytes: int = 0
    # Fused Pallas paged-attention decode kernel (ops/attention.py
    # paged_attention): walk each lane's block table inside the kernel and
    # compute online-softmax attention straight from the page arena — one
    # pass over the KV bytes instead of paged_gather_kv's materialized
    # pages[tables] round-trip. true (default) uses the kernel on TPU
    # backends when shapes qualify (head_dim % 64 == 0, heads divisible by
    # kv heads) and falls back to the gather+einsum reference everywhere
    # else; false forces the reference path unconditionally — byte-for-byte
    # the pre-kernel behavior, the A/B lever for parity tests and bench.
    kv_paged_kernel: bool = True
    # KV page arena element type. "" (default) stores pages in the model's
    # own dtype. "int8" quantizes pages symmetrically per (page, kv_head,
    # token) row with f32 scales riding beside the arena — rows dequantize
    # inside the decode kernel (or before the reference einsum), and the
    # auto-sized arena (kv_arena_pages == 0) grows to fill the SAME byte
    # budget the dense arena would have used (~1.9x pages for bf16 models),
    # which is the capacity win. Page bookkeeping (reserve/CoW/census) is
    # count-based and identical under quantization.
    kv_arena_dtype: str = ""
    # In-engine speculative decoding for generate_engine=continuous
    # (runtime/batcher.py): name of the DRAFT model — "name" (highest
    # resident version) or "name@version". "" = off (default). When set,
    # each continuous scheduler attaches the draft to its paged slot state
    # (runtime.slot_attach_draft) and replaces plain decode chunks with
    # draft/verify rounds: the draft proposes spec_tokens greedy tokens per
    # lane, ONE multi-position verify pass scores them, and each lane
    # accepts a variable-length prefix — greedy streams stay byte-identical
    # to spec-off. Admission reserves spec_tokens of extra page headroom
    # per row in BOTH arenas, so requests sized to the exact arena edge may
    # need one more page than without spec. Mesh runtimes and dense
    # (non-paged) states ignore the knob; lanes with temperature > 0 fall
    # back to single-token emission inside the round.
    spec_draft_model: str = ""
    # Draft tokens proposed per verify round when spec_draft_model is set
    # (clamped to the pow2 bucket ladder {1, 2, 4, 8} at attach — bounds
    # the verify program count). Also the per-row page headroom reserved at
    # admission. Higher values win only when acceptance is high; the
    # runtime's acceptance health gate (_spec_admit) auto-disables a pair
    # that sustains low acceptance and re-auditions it periodically.
    spec_tokens: int = 4
    # Transparent crash recovery for generate_engine=continuous
    # (runtime/batcher.py): on an engine-thread death (device failure,
    # mid-decode eviction, injected kill) the crashed scheduler's in-flight
    # and queued rows requeue into a fresh scheduler thread instead of
    # failing — admission re-prefills each interrupted row's prompt plus
    # the tokens it already emitted (the prefix cache makes the replay
    # cheap; greedy streams stay token-identical), and every requeued row
    # counts in tpusc_requests_recovered_total{reason}. false restores the
    # fail-all-rows behavior.
    generate_recovery: bool = True
    # Per-row recovery budget: a row that survives this many engine crashes
    # fails on the next one (a poison prompt that deterministically crashes
    # the engine must not respawn scheduler threads forever).
    generate_max_recoveries: int = 2
    # Conversation KV tier for generate_engine=continuous
    # (cache/conversation_kv.py): host-RAM byte budget for PARKED decode
    # state. A `:generate` request carrying a conversation_id parks its
    # lane's live KV pages (raw arena dtype + int8 scales — half the dense
    # bytes under kv_arena_dtype=int8) at retirement; the conversation's
    # next turn resumes with a suffix-only prefill over the re-imported
    # pages — O(new tokens) TTFT instead of a full-history re-prefill,
    # token-identical under the exact-hit sampling discipline. 0 = off
    # (default — requests with conversation ids behave exactly as today).
    conversation_kv_bytes: int = 0
    # Disk spill level under the host budget: the coldest parked
    # conversations spill (LRU) to conversation_kv_dir instead of dropping
    # when conversation_kv_bytes overflows; a resume that finds its turn on
    # disk re-promotes it to host. 0 = no spill (cold conversations drop).
    conversation_kv_disk_bytes: int = 0
    # Directory for spilled conversation KV blobs (one file per parked
    # conversation, atomic tmp+rename writes). Cleared on tier close.
    conversation_kv_dir: str = "/tmp/tpusc_conv_kv"
    # ModelSpec.version_label resolution map: {model_name: {label: version}}.
    # TF Serving owns labels in its serving config (version_labels); the
    # reference forwards labeled specs verbatim for it to resolve
    # (tfservingproxy.go:246-250). Here the map lives in THIS config; a
    # labeled request for an unmapped label fails FAILED_PRECONDITION/412
    # instead of silently serving latest (VERDICT r3 missing #4).
    version_labels: dict = field(default_factory=dict)


@dataclass
class CacheConfig:
    """Disk artifact cache (reference config.yaml:25-27) plus the host-RAM
    warm tier that sits between it and the HBM slots."""

    base_dir: str = "/tmp/tpusc_models"
    disk_capacity_bytes: int = 10 << 30
    # Host-RAM warm tier (cache/host_tier.py): byte budget of host DRAM for
    # retaining evicted models' already-decoded, pre-packed transfer chunks
    # plus their executable handles, so re-admission skips provider fetch
    # and host decode entirely and pays only the H2D stream. 0 = off
    # (default — identical to the two-tier behavior). Mesh/multi-process
    # runtimes ignore it and always take the full load path.
    host_tier_bytes: int = 0


@dataclass
class ModelProviderConfig:
    """Reference config.yaml:1-23."""

    type: str = "disk"                 # disk | s3 | gcs | azblob
    base_dir: str = "./models"         # disk provider root
    # s3/gcs/azblob:
    bucket: str = ""
    base_path: str = ""
    region: str = ""
    endpoint: str = ""                 # custom endpoint (minio etc.)
    account_name: str = ""             # azblob
    account_key: str = ""
    container: str = ""


@dataclass
class ProxyConfig:
    """Router/front layer (reference config.yaml:38-43)."""

    rest_port: int = 8093
    grpc_port: int = 8100
    replicas_per_model: int = 1
    grpc_max_message_bytes: int = 16 << 20   # reference cachemanager.go:230-233
    # on membership change, pre-load owned models already in the local disk
    # cache (cluster/warmer.py; no reference counterpart — SURVEY §7 (a))
    warm_on_assignment: bool = True


@dataclass
class CacheNodePorts:
    rest_port: int = 8094
    grpc_port: int = 8095


@dataclass
class DiscoveryConfig:
    """Reference config.yaml:44-58 (serviceDiscovery.*)."""

    type: str = ""                     # "" = single-node cache-only mode | static | file | consul | etcd | kubernetes
    heartbeat_ttl_s: float = 5.0
    service_name: str = "tpuserve-cache"
    # static backend:
    nodes: list[str] = field(default_factory=list)   # "host:restPort:grpcPort"
    # file backend:
    path: str = ""
    poll_interval_s: float = 2.0
    # consul/etcd/k8s endpoints:
    address: str = ""                  # consul http addr or etcd grpc addr
    namespace: str = ""                # k8s namespace ("" = from serviceaccount)
    field_selector: str = ""           # k8s endpoints selector
    prefer_localhost: bool = False     # reference etcd.go:162-166 outbound-IP fallback


@dataclass
class ClusterConfig:
    """Fleet status plane (cluster/status.py): the cross-node residency/
    health exchange the router's p2c tie-breaks and soft route-around
    consume, surfaced at ``GET /monitoring/cluster``. No reference
    counterpart — the reference cluster exchanges membership only."""

    # master switch for the exchange (piggyback + poll). Off: the router
    # falls back to local-only warmth and load-only p2c (pre-PR7 behavior).
    status_exchange: bool = True
    # low-rate poll fallback for peers no routed traffic reaches; also the
    # freshness bar below which a peer is NOT re-polled (piggyback wins)
    status_poll_interval_s: float = 5.0
    # a status older than this is stale: its warmth advertisements stop
    # counting and the peer's health score starts decaying
    status_stale_after_s: float = 15.0
    # hard bound on the encoded piggyback payload; encode drops the
    # coldest models first to fit and stamps how many were cut
    status_byte_cap: int = 4096
    # most models a single NodeStatus advertises (warmest win)
    status_max_models: int = 64
    # most tenant accounting rows a single NodeStatus piggybacks (ordered
    # by dominant share; the byte cap trims these before models). 0 turns
    # the per-tenant fleet view off.
    status_max_tenants: int = 8
    # collection cache: piggybacking on every response re-collects at most
    # this often (a fresh collect is <1 ms, but per-response would still
    # be wasteful at high QPS)
    status_min_interval_s: float = 0.25
    # peers scoring below this are deprioritized in p2c replica ordering
    # (soft route-around; they stay in the ring and keep their keys)
    health_threshold: float = 0.5
    # EWMA weight for forward outcomes (higher = reacts faster, forgets
    # faster): at 0.3, three straight failures drop health to ~0.34 and
    # three straight successes recover it past 0.5
    health_error_alpha: float = 0.3
    # latency normalization: score factor = ref / (ref + latency_ewma)
    health_latency_ref_s: float = 1.0
    # -- peer param distribution (cache/providers/peer.py) ------------------
    # On a cold miss, stream another node's host-tier packed chunks over
    # gRPC instead of refetching from the provider (requires
    # status_exchange for the warmth map). Off: every miss goes to store.
    peer_fetch: bool = True
    # target size of one streamed chunk message (the sender re-frames the
    # ~256 MB pack-plan chunks into messages of at most this many bytes)
    peer_fetch_chunk_bytes: int = 2 << 20
    # outbound streams a single node serves per requesting peer at once;
    # excess fetches are refused (the asker falls back to the store)
    peer_fetch_max_inflight_per_peer: int = 2
    # end-to-end deadline for one peer fetch; on expiry the asker falls
    # back to the store (loud, never request-fatal)
    peer_fetch_timeout_s: float = 60.0
    # -- load-adaptive replication (cluster/replication.py) -----------------
    # ceiling for the per-model replica count the controller may grow to;
    # proxy.replicas_per_model stays the floor/default. 0 disables the
    # controller (static N, pre-PR8 behavior).
    max_replicas_per_model: int = 4
    # in-flight requests per replica (EWMA) that justify one more replica:
    # desired N = clamp(ceil(ewma / target), base, max)
    replica_load_target: float = 2.0
    # controller evaluation cadence
    replica_eval_interval_s: float = 2.0
    # shrink hysteresis: N decays only after this many CONSECUTIVE evals
    # wanting a lower N (growth applies immediately; ring assignment is
    # prefix-stable under N changes so only N itself needs damping)
    replica_decay_ticks: int = 3


@dataclass
class MeshConfig:
    """TPU chip-group topology — new territory (SURVEY.md §2 parallelism
    inventory: the reference has none). Models larger than one chip are
    sharded over a chip group; the ring assigns models to groups.

    Cross-host groups (chips_per_group > chips per host): set ``coordinator``
    (jax.distributed rendezvous, e.g. host0:8476), ``num_processes``,
    ``process_id``, and one ``worker_addrs`` "host:port" entry PER PROCESS —
    the group-work endpoint its leader broadcasts collective ops to
    (parallel/multihost.py). The group's leader process is its ring member."""

    chips_per_group: int = 1           # chip-group size for sharded models
    axis_names: tuple[str, ...] = ("data", "model")
    data_parallel: int = 1
    coordinator: str = ""              # jax.distributed coordinator address
    num_processes: int = 1
    process_id: int = 0
    worker_addrs: list[str] = field(default_factory=list)  # per-process host:port


@dataclass
class MetricsConfig:
    model_labels: bool = False         # per-model:version labels (reference cachemanager.go:251-258)
    path: str = "/monitoring/prometheus/metrics"
    # extra text-format exporters merged into this node's /metrics (reference
    # MetricsHandler scraping TF Serving live, pkg/taskhandler/metrics.go:16-53)
    scrape_targets: list[str] = field(default_factory=list)
    # cardinality guard for model_labels: after this many distinct
    # name:version values, NEW tenants fold into the "__other__" bucket so
    # a 1000-tenant churn can't explode every {model=...} family
    max_model_labels: int = 512
    # scrape_targets merge mode: sum counter series with identical label
    # sets across sources (per-tenant fleet aggregation) instead of the
    # default family-level dedup where the first exporter wins
    scrape_sum_counters: bool = False


@dataclass
class TracingConfig:
    """Always-on request tracing (utils/tracing.py; no reference
    counterpart — SURVEY.md §5 "no OpenTelemetry/pprof anywhere")."""

    capacity: int = 256                # completed traces kept in the ring
    # tail sampling: traces slower than this survive in a separate bounded
    # buffer even after fast traffic wraps the main ring; 0 disables
    slow_threshold_ms: float = 1000.0
    slow_capacity: int = 64


@dataclass
class ObservabilityConfig:
    """Engine flight recorder (utils/flight_recorder.py): per-step
    telemetry rings are always on (they're preallocated host lists — cost
    is bytes, not time); these knobs govern the anomaly-dump spool."""

    # Spool dir for anomaly dumps (SLO breach / page-exhaustion blocking /
    # engine-thread crash). "" disables dumps; the rings keep recording.
    flight_dir: str = "/tmp/tpusc_flight"
    # Per-model step-ring capacity: at a 10 ms chunk cadence 4096 entries
    # is the last ~40 s of engine history.
    ring_entries: int = 4096
    # Spool bound: oldest dump files beyond this count are deleted.
    max_dumps: int = 16
    # Rate limit for recurring triggers (page exhaustion); SLO-breach dumps
    # dedup per trace id instead.
    dump_cooldown_s: float = 60.0
    # -- per-tenant resource accounting (utils/accounting.py) ---------------
    # master switch for the cost-attribution ledger (step seconds, token
    # counts, byte-second / page-second gauge integrals, load latencies)
    tenant_accounting: bool = True
    # noisy-neighbor detector: a tenant holding at least this share of the
    # engine step-time window while OTHER tenants sit queued triggers one
    # "noisy_neighbor" flight dump (deduped by the recorder cooldown)
    noisy_neighbor_share: float = 0.8
    # sliding window the share is computed over
    noisy_neighbor_window_s: float = 5.0
    # windows with less than this much total step time never fire (an idle
    # node's only tenant trivially holds 100% of nothing)
    noisy_neighbor_min_step_s: float = 0.25
    # -- scenario-lab fault injector (lab/faults.py) ------------------------
    # "" (default) keeps the injector disarmed: every hook site in the
    # engine/manager/peer-receiver/fleet plane is a single-bool-read
    # passthrough. Set to a JSON list of fault specs to arm a chaos drill
    # at startup, e.g. '[{"kind": "freeze_scheduler", "after": 10,
    # "duration_s": 0.25}]' — kinds: kill_engine, freeze_scheduler,
    # stall_store, corrupt_peer_chunk, drop_peer. Reachable as the
    # TPUSC_OBSERVABILITY_LAB_FAULTS env override; a malformed spec fails
    # startup rather than silently running a no-op drill.
    lab_faults: str = ""


@dataclass
class LoggingConfig:
    level: str = "info"
    fmt: str = "text"                  # text | json (reference cfg.go:28-61)


@dataclass
class Config:
    serving: ServingConfig = field(default_factory=ServingConfig)
    cache: CacheConfig = field(default_factory=CacheConfig)
    model_provider: ModelProviderConfig = field(default_factory=ModelProviderConfig)
    proxy: ProxyConfig = field(default_factory=ProxyConfig)
    cache_node: CacheNodePorts = field(default_factory=CacheNodePorts)
    discovery: DiscoveryConfig = field(default_factory=DiscoveryConfig)
    cluster: ClusterConfig = field(default_factory=ClusterConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    metrics: MetricsConfig = field(default_factory=MetricsConfig)
    tracing: TracingConfig = field(default_factory=TracingConfig)
    observability: ObservabilityConfig = field(default_factory=ObservabilityConfig)
    logging: LoggingConfig = field(default_factory=LoggingConfig)
    # health probe model name (reference cfg.go:64-66 default)
    health_probe_model: str = "__TPUSC_PROBE_CHECK__"


def _coerce(value: str, target: Any) -> Any:
    """Coerce an env-var string to the type of the dataclass default."""
    if isinstance(target, bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(target, int):
        return int(value)
    if isinstance(target, float):
        return float(value)
    if isinstance(target, (list, tuple)):
        parts = [p for p in value.split(",") if p]
        return type(target)(parts)
    return value


def _apply_mapping(cfg: Any, data: dict[str, Any], path: str = "") -> None:
    known = {f.name for f in dataclasses.fields(cfg)}
    unknown = set(data) - known
    if unknown:
        # loud but permissive: a typo'd or reference-style camelCase key must
        # not silently degrade to defaults
        import logging

        logging.getLogger("tpusc.config").warning(
            "ignoring unknown config key(s) %s under %r (known: %s)",
            sorted(unknown), path or ".", sorted(known),
        )
    for f in dataclasses.fields(cfg):
        if f.name not in data:
            continue
        val = data[f.name]
        cur = getattr(cfg, f.name)
        if dataclasses.is_dataclass(cur):
            if val is None:
                continue  # empty YAML section ("discovery:" with children commented out)
            if not isinstance(val, dict):
                raise ValueError(
                    f"config section {path}{f.name!s} must be a mapping, got {type(val).__name__}"
                )
            _apply_mapping(cur, val, f"{path}{f.name}.")
        elif isinstance(val, str) and not isinstance(cur, str):
            setattr(cfg, f.name, _coerce(val, cur))
        elif isinstance(cur, tuple) and isinstance(val, list):
            setattr(cfg, f.name, tuple(val))
        else:
            setattr(cfg, f.name, val)


def _apply_env(cfg: Any, prefix: str) -> None:
    for f in dataclasses.fields(cfg):
        cur = getattr(cfg, f.name)
        key = f"{prefix}{f.name.upper()}"
        if dataclasses.is_dataclass(cur):
            _apply_env(cur, f"{key}_")
        elif key in os.environ:
            try:
                setattr(cfg, f.name, _coerce(os.environ[key], cur))
            except ValueError as e:
                raise ValueError(f"invalid value for env {key}: {e}") from e


def load_config(path: str | None = None, env: bool = True) -> Config:
    """Load ``config.yaml`` (if present) and apply ``TPUSC_*`` env overrides.

    Mirrors reference cfg.go:10-27: missing file is fine (env/defaults only).
    """
    cfg = Config()
    if path is None and os.path.exists("config.yaml"):
        path = "config.yaml"
    if path and os.path.exists(path):
        with open(path) as fh:
            data = yaml.safe_load(fh) or {}
        _apply_mapping(cfg, data)
    if env:
        _apply_env(cfg, ENV_PREFIX)
    return cfg

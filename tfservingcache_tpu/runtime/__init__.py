from tfservingcache_tpu.runtime.base import BaseRuntime, RuntimeError_
from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime

__all__ = ["BaseRuntime", "RuntimeError_", "TPUModelRuntime"]

"""The TPU model runtime: artifact -> JAX fn -> XLA executable pinned in HBM.

This is the component that dissolves the reference's L1 process boundary
(SURVEY.md §7 design stance): where the reference POSTs a desired-state
ReloadConfigRequest to tensorflow_model_server and polls GetModelStatus every
500 ms until AVAILABLE (cachemanager.go:167-195), this runtime loads the
artifact, ``jit``-compiles the family's apply fn, runs a warmup call to
materialize the executable + params in HBM, and flips the state machine to
AVAILABLE — all in-process, nothing to poll.

HBM is the scarce resource (the reference only budgets disk bytes —
SURVEY.md §7 hard part (b)); resident models live in a byte-budgeted LRU
whose eviction drops executable + param references so XLA frees device
memory.

Variable request batch sizes are padded up to power-of-two buckets so each
model compiles O(log max_batch) executables instead of one per batch size —
dynamic shapes would otherwise force an XLA recompile per novel batch.
"""

from __future__ import annotations

import functools
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Mapping

import numpy as np

from tfservingcache_tpu.cache.lru import LRUEntry
from tfservingcache_tpu.native import make_lru_cache
from tfservingcache_tpu.config import ServingConfig
from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, load_artifact
from tfservingcache_tpu.runtime.base import BaseRuntime, ModelNotLoadedError, RuntimeError_
from tfservingcache_tpu.types import Model, ModelId, ModelState
from tfservingcache_tpu.utils.accounting import LEDGER
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.metrics import Metrics
from tfservingcache_tpu.utils.tracing import TRACER

log = get_logger("runtime")


def next_bucket(n: int) -> int:
    """Smallest power of two >= n (batch padding bucket)."""
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


# Speculative-decoding worst case (VERDICT r5 #6): at acceptance ~0 every
# verify round still pays spec_tokens draft forwards + one chunked target
# forward to emit ONE token — strictly more target work per token than plain
# decode. Below this tokens-per-round the draft is pure overhead for any
# spec_tokens >= 2, so a sustained run of such generates auto-disables the
# (target, draft) pair; disabled pairs re-audition periodically in case the
# workload (or draft version) changed.
SPEC_MIN_TOKENS_PER_ROUND = 1.5
SPEC_DISABLE_AFTER = 8      # consecutive low-acceptance generates
SPEC_REPROBE_EVERY = 64     # every Nth gated request runs the draft again


def tree_nbytes(tree: Any) -> int:
    import jax

    return sum(x.nbytes for x in jax.tree_util.tree_leaves(tree) if hasattr(x, "nbytes"))


@functools.lru_cache(maxsize=256)
def _split_fn(dtype_str: str, shapes: tuple[tuple[int, ...], ...]):
    """Jitted on-device re-slice of one packed parameter buffer. Cached per
    (dtype, shape list) — one compile per model family, shared by every
    tenant's load."""
    import jax

    def split(buf):
        parts = []
        off = 0
        for shape in shapes:
            n = 1
            for d in shape:
                n *= d
            parts.append(buf[off:off + n].reshape(shape))
            off += n
        return parts

    return jax.jit(split)


_PACK_CHUNK_BYTES = 256 << 20


def _pack_plan(arrs: list[np.ndarray]) -> list[list[int]]:
    """Deterministic transfer plan: flat indices grouped per dtype, each
    group sliced into <=~256 MB chunks. Shared by the serialized and the
    pipelined packed transfer so both issue the IDENTICAL device-op
    sequence — only who assembles the host buffers differs."""
    groups: dict[str, list[int]] = {}
    for i, a in enumerate(arrs):
        groups.setdefault(a.dtype.str, []).append(i)
    chunks: list[list[int]] = []
    for idxs in groups.values():
        chunk: list[int] = []
        chunk_bytes = 0
        for i in idxs:
            chunk.append(i)
            chunk_bytes += arrs[i].nbytes
            if chunk_bytes >= _PACK_CHUNK_BYTES:
                chunks.append(chunk)
                chunk, chunk_bytes = [], 0
        if chunk:
            chunks.append(chunk)
    return chunks


def packed_device_put(host_params: Any, device: Any) -> Any:
    """Single-stream host->device transfer of a parameter pytree.

    The cold-miss path is bandwidth-bound on the host<->HBM link (round-2
    profile: ~80% of the LM 3.14 s cold p50 was device_put of 38 separate
    leaves). Leaves are concatenated per dtype into contiguous host buffers,
    shipped in one transfer each, and re-sliced on device by a cached jitted
    split — per-leaf transfer round trips collapse to one per ~256 MB chunk.
    Chunking bounds the transient device overshoot (packed buffer + its
    re-sliced copies coexist until the split returns) to params + one chunk,
    so a model near the HBM budget still loads.
    """
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(host_params)
    arrs = [np.asarray(x) for x in leaves]
    if len(arrs) <= 2:
        return jax.device_put(host_params, device)
    out: list[Any] = [None] * len(arrs)
    for chunk in _pack_plan(arrs):
        flat = (
            np.concatenate([arrs[i].ravel() for i in chunk])
            if len(chunk) > 1
            else arrs[chunk[0]].ravel()
        )
        buf = jax.device_put(flat, device)
        parts = _split_fn(flat.dtype.str, tuple(arrs[i].shape for i in chunk))(buf)
        del buf  # the split's output is the only live device copy
        for i, p in zip(chunk, parts):
            out[i] = p
    return jax.tree_util.tree_unflatten(treedef, out)


def _flatten_for_pack(host_params: Any):
    """-> (outer leaves, outer treedef, flat np arrays, owner map). The one
    flatten bookkeeping shared by the pipelined transfer and the host-tier
    entry builder: QuantLeaf stays a single OUTER leaf contributing its q
    and scale as two FLAT arrays, so both consumers agree on what a flat
    index means."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    is_quant = lambda x: isinstance(x, QuantLeaf)  # noqa: E731
    outer, treedef = jax.tree_util.tree_flatten(host_params, is_leaf=is_quant)
    arrs: list[np.ndarray] = []
    owner: list[tuple[int, str]] = []  # flat idx -> (outer idx, plain|q|scale)
    for oi, leaf in enumerate(outer):
        if is_quant(leaf):
            arrs.append(np.asarray(leaf.q))
            owner.append((oi, "q"))
            arrs.append(np.asarray(leaf.scale))
            owner.append((oi, "scale"))
        else:
            arrs.append(np.asarray(leaf))
            owner.append((oi, "plain"))
    return outer, treedef, arrs, owner


def _shard_chunk_plan(
    arrs: list[np.ndarray], shard_list: list[Any]
) -> list[tuple[Any, list[tuple[int, tuple]]]]:
    """Chunk→shard segment map for a sharded packed transfer (ISSUE 20):
    for every addressable device, the host-index slices of each flat leaf
    that land on it (``NamedSharding.addressable_devices_indices_map``),
    grouped per dtype into <=~256 MB chunks like ``_pack_plan``. Devices
    iterate in id order and leaves in flat order, so the device-op stream
    stays a pure function of (params, shardings) — the same determinism
    contract as the unsharded plan. A replicated leaf contributes its full
    slice to EVERY device (that is what replication costs on any path);
    a partitioned leaf ships each device only its own shard — the
    per-host/per-device shard filter."""
    seg_by_dev: dict[Any, list[tuple[int, tuple]]] = {}
    for i, (arr, sharding) in enumerate(zip(arrs, shard_list)):
        for dev, idx in sharding.addressable_devices_indices_map(
            arr.shape
        ).items():
            seg_by_dev.setdefault(dev, []).append((i, idx))
    plan: list[tuple[Any, list[tuple[int, tuple]]]] = []
    for dev in sorted(seg_by_dev, key=lambda d: d.id):
        by_dtype: dict[str, list[tuple[int, tuple]]] = {}
        for i, idx in seg_by_dev[dev]:
            by_dtype.setdefault(arrs[i].dtype.str, []).append((i, idx))
        for group in by_dtype.values():
            chunk: list[tuple[int, tuple]] = []
            chunk_bytes = 0
            for i, idx in group:
                chunk.append((i, idx))
                chunk_bytes += arrs[i][idx].nbytes  # view: shape math only
                if chunk_bytes >= _PACK_CHUNK_BYTES:
                    plan.append((dev, chunk))
                    chunk, chunk_bytes = [], 0
            if chunk:
                plan.append((dev, chunk))
    return plan


def packed_device_put_sharded(
    host_params: Any,
    shardings: Any,
    buffer_depth: int = 2,
) -> Any:
    """Pipelined packed transfer of a pytree onto a (single-process) mesh:
    ``shardings`` is a pytree of ``NamedSharding`` matching ``host_params``
    (parallel/sharding.param_shardings). Each device receives only its own
    shard bytes, packed per dtype into ~256 MB chunks assembled on a side
    thread while the previous chunk's ``device_put`` streams — the same
    double-buffering as the unsharded pipelined path, minus the on-device
    dequant interleave (the mesh branch dequantizes on host first, because
    partition rules name float leaves). The global arrays are assembled
    from the landed per-device shards via
    ``jax.make_array_from_single_device_arrays`` — committed shardings,
    identical to what ``shard_params`` would have produced."""
    import queue as queue_mod

    import jax

    outer, treedef, arrs, owner = _flatten_for_pack(host_params)
    if any(role != "plain" for _, role in owner):
        raise ValueError(
            "sharded packed transfer requires host-dequantized leaves"
        )
    shard_list = jax.tree_util.tree_leaves(shardings)
    if len(shard_list) != len(arrs):
        raise ValueError("shardings tree does not match params tree")
    if len(arrs) <= 2:
        return jax.device_put(host_params, shardings)

    plan = _shard_chunk_plan(arrs, shard_list)
    done = object()
    q: Any = queue_mod.Queue(maxsize=max(1, buffer_depth))
    stop = threading.Event()

    def put(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def assemble() -> None:
        try:
            for dev, chunk in plan:
                parts = [
                    np.ascontiguousarray(arrs[i][idx]).ravel()
                    for i, idx in chunk
                ]
                flat = parts[0] if len(parts) == 1 else np.concatenate(parts)
                if not put((dev, chunk, flat)):
                    return
                del parts, flat
            put(done)
        except BaseException as e:  # noqa: BLE001 - re-raised by the consumer
            put(e)

    # flat idx -> {device: landed single-device shard}
    shard_parts: dict[int, dict[Any, Any]] = {i: {} for i in range(len(arrs))}
    worker = threading.Thread(
        target=assemble, name="tpusc-shard-assembler", daemon=True
    )
    worker.start()
    try:
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            dev, chunk, flat = item
            buf = jax.device_put(flat, dev)
            parts = _split_fn(
                flat.dtype.str, tuple(arrs[i][idx].shape for i, idx in chunk)
            )(buf)
            del buf, flat
            for (i, _idx), p in zip(chunk, parts):
                shard_parts[i][dev] = p
    finally:
        stop.set()
        worker.join(timeout=5.0)

    out: list[Any] = [None] * len(arrs)
    for i, (arr, sharding) in enumerate(zip(arrs, shard_list)):
        devs = sharding.addressable_devices_indices_map(arr.shape)
        out[i] = jax.make_array_from_single_device_arrays(
            arr.shape, sharding, [shard_parts[i][d] for d in devs]
        )
        shard_parts[i] = {}
    return jax.tree_util.tree_unflatten(treedef, out)


def packed_device_put_pipelined(
    host_params: Any,
    device: Any,
    buffer_depth: int = 2,
    capture: list | None = None,
    shardings: Any | None = None,
) -> tuple[Any, float]:
    """Double-buffered packed transfer with interleaved on-device dequant.

    -> (device params with every QuantLeaf already expanded, seconds spent
    dispatching dequants). Two overlaps over ``packed_device_put``:

      * chunk N+1's host-side ``concatenate`` runs on an assembler thread
        (feeding a queue bounded at ``buffer_depth`` chunks) while chunk N's
        async ``device_put`` streams — today that concat blocks the link;
      * a quantized leaf whose q and scale have both landed dequantizes
        immediately, overlapping the remaining chunks' transfer, instead of
        waiting for the whole tree (via ``_dequantize_on_device`` per leaf,
        so the q/scale references drop with the same per-leaf discipline).

    Every DEVICE op (device_put, split, dequant) still issues from the
    calling thread, in the same ``_pack_plan`` order as the serialized path
    — the device-op stream is a pure function of the artifact, never of
    host thread timing.

    ``capture``, when given, collects ``(chunk, flat)`` pairs as each chunk
    ships — the host-tier retention hook. Captured buffers are always OWNED
    (a single-element chunk's ``ravel`` is a view into the artifact's blob;
    retaining it would pin the whole file mapping, so views are copied).

    ``shardings`` (ISSUE 20) is the shard filter: a pytree of
    ``NamedSharding`` matching ``host_params`` routes the transfer through
    ``packed_device_put_sharded`` — per-device shard chunks instead of
    whole-leaf chunks, ``device`` ignored, dequant seconds 0.0 (the mesh
    branch dequantizes on host before calling).
    """
    import queue as queue_mod

    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    if shardings is not None:
        return (
            packed_device_put_sharded(
                host_params, shardings, buffer_depth=buffer_depth
            ),
            0.0,
        )
    outer, treedef, arrs, owner = _flatten_for_pack(host_params)
    if len(arrs) <= 2:
        params = jax.device_put(host_params, device)
        t0 = time.monotonic()
        return _dequantize_on_device(params), time.monotonic() - t0

    chunks = _pack_plan(arrs)
    done = object()
    q: Any = queue_mod.Queue(maxsize=max(1, buffer_depth))
    stop = threading.Event()

    def put(item) -> bool:
        # bounded put that can always be abandoned: a consumer-side failure
        # sets ``stop`` and the assembler must not block on a full queue
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def assemble() -> None:
        try:
            for chunk in chunks:
                flat = (
                    np.concatenate([arrs[i].ravel() for i in chunk])
                    if len(chunk) > 1
                    else arrs[chunk[0]].ravel()
                )
                if not put((chunk, flat)):
                    return
                del flat
            put(done)
        except BaseException as e:  # noqa: BLE001 - re-raised by the consumer
            put(e)

    out_outer: list[Any] = [None] * len(outer)
    landed: dict[int, dict[str, Any]] = {}  # quant leaves awaiting both halves
    dequant_s = 0.0
    worker = threading.Thread(
        target=assemble, name="tpusc-chunk-assembler", daemon=True
    )
    worker.start()
    try:
        while True:
            item = q.get()
            if item is done:
                break
            if isinstance(item, BaseException):
                raise item
            chunk, flat = item
            buf = jax.device_put(flat, device)
            parts = _split_fn(
                flat.dtype.str, tuple(arrs[i].shape for i in chunk)
            )(buf)
            if capture is not None:
                capture.append(
                    (chunk, flat if flat.base is None else flat.copy())
                )
            del buf, flat  # the split's output is the only live device copy
            for i, p in zip(chunk, parts):
                oi, role = owner[i]
                if role == "plain":
                    out_outer[oi] = p
                    continue
                got = landed.setdefault(oi, {})
                got[role] = p
                if len(got) == 2:
                    ql = QuantLeaf(got["q"], got["scale"], outer[oi].orig_dtype)
                    del landed[oi]
                    t0 = time.monotonic()
                    out_outer[oi] = _dequantize_on_device(ql)
                    dequant_s += time.monotonic() - t0
    finally:
        stop.set()
        worker.join(timeout=5.0)
    return jax.tree_util.tree_unflatten(treedef, out_outer), dequant_s


def _abstract_post_dequant(host_params: Any) -> Any:
    """``jax.ShapeDtypeStruct`` pytree of ``host_params`` AFTER device
    dequant — the signature the family executable is traced against."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    def leaf(x):
        if isinstance(x, QuantLeaf):
            return jax.ShapeDtypeStruct(
                np.asarray(x.q).shape, np.dtype(x.orig_dtype)
            )
        a = np.asarray(x)
        return jax.ShapeDtypeStruct(a.shape, a.dtype)

    return jax.tree_util.tree_map(
        leaf, host_params, is_leaf=lambda x: isinstance(x, QuantLeaf)
    )


@functools.lru_cache(maxsize=None)
def _dequant_fn(orig_dtype: str):
    """Cached jitted per-leaf dequant (q int8, scale f32) -> orig_dtype.
    The jit cache further keys on shapes, so every tenant of a family
    reuses one executable per leaf shape."""
    import jax
    import jax.numpy as jnp

    return jax.jit(
        lambda q, s: (q.astype(jnp.float32) * s).astype(jnp.dtype(orig_dtype))
    )


def _dequantize_on_device(params: Any) -> Any:
    """Expand QuantLeaf nodes (int8 q + f32 scale, already device-resident)
    into their original float dtype on device — the compute side of the
    int8 artifact transport. The q/scale references are dropped leaf by
    leaf as outputs materialize, so the transient HBM overshoot stays
    ~one leaf, not int8-tree + float-tree at once (the same bounded-
    overshoot discipline as packed_device_put's chunking)."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    def leaf(x):
        if isinstance(x, QuantLeaf):
            out = _dequant_fn(x.orig_dtype)(x.q, x.scale)
            x.q = x.scale = None  # free the int8 buffer once XLA is done
            return out
        return x

    return jax.tree_util.tree_map(
        leaf, params, is_leaf=lambda x: isinstance(x, QuantLeaf)
    )


def _dequantize_on_host(params: Any) -> Any:
    """Host-side expansion for the sharded branch (partition rules name
    float leaves)."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    return jax.tree_util.tree_map(
        lambda x: x.dequant_host() if isinstance(x, QuantLeaf) else x,
        params, is_leaf=lambda x: isinstance(x, QuantLeaf),
    )


def build_packed_entry(
    model_def: ModelDef,
    host_params: Any,
    jitted: Any,
    hbm_bytes: int,
    captured: list | None = None,
) -> Any:
    """Build a host-tier ``PackedModelEntry`` from a model's host pytree.

    ``captured`` — the chunk buffers the pipelined transfer just shipped —
    is reused verbatim when present (the load already paid the
    concatenates); otherwise the chunks are re-assembled here from the
    same ``_pack_plan``, which is the demotion path (params pulled back
    from the device) and the non-pipelined load paths (small trees,
    serialized fallback). Either way every retained buffer is OWNED:
    views into an artifact's decoded blob are copied rather than pinned.
    """
    from tfservingcache_tpu.cache.host_tier import PackedModelEntry
    from tfservingcache_tpu.models.registry import QuantLeaf, _leaf_path_str

    outer, treedef, arrs, owner = _flatten_for_pack(host_params)
    quant_dtypes = {
        oi: leaf.orig_dtype
        for oi, leaf in enumerate(outer)
        if isinstance(leaf, QuantLeaf)
    }
    # outer idx -> artifact leaf path, so a peer can synthesize a complete
    # v2 manifest from this entry alone (protocol/peer_transfer.py). Same
    # path convention as save_artifact; flatten order matches outer (both
    # flatten the same tree with the same is_leaf).
    import jax

    paths_with_leaves = jax.tree_util.tree_flatten_with_path(
        host_params, is_leaf=lambda x: isinstance(x, QuantLeaf)
    )[0]
    paths = [_leaf_path_str(kp) for kp, _ in paths_with_leaves]
    if captured:
        chunks = [(list(chunk), flat) for chunk, flat in captured]
    else:
        chunks = []
        for chunk in _pack_plan(arrs):
            flat = (
                np.concatenate([arrs[i].ravel() for i in chunk])
                if len(chunk) > 1
                else np.array(arrs[chunk[0]].ravel())
            )
            chunks.append((chunk, flat))
    return PackedModelEntry(
        model_def=model_def,
        chunks=chunks,
        owner=owner,
        shapes=[a.shape for a in arrs],
        quant_dtypes=quant_dtypes,
        treedef=treedef,
        jitted=jitted,
        hbm_bytes=int(hbm_bytes),
        nbytes=sum(f.nbytes for _, f in chunks),
        paths=paths,
    )


def promote_packed_entry(entry: Any, device: Any) -> tuple[Any, float]:
    """Replay a ``PackedModelEntry``'s chunks into HBM -> (device params,
    dequant dispatch seconds). This is ``packed_device_put_pipelined``'s
    consumer loop minus everything promotion gets to skip: no provider
    fetch, no artifact decode, no host-side concatenate (the buffers are
    retained pre-packed) — the identical device-op sequence the original
    load issued, fed straight from host RAM."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    out_outer: list[Any] = [None] * entry.treedef.num_leaves
    landed: dict[int, dict[str, Any]] = {}
    dequant_s = 0.0
    for chunk, flat in entry.chunks:
        buf = jax.device_put(flat, device)
        parts = _split_fn(
            flat.dtype.str, tuple(entry.shapes[i] for i in chunk)
        )(buf)
        del buf
        for i, p in zip(chunk, parts):
            oi, role = entry.owner[i]
            if role == "plain":
                out_outer[oi] = p
                continue
            got = landed.setdefault(oi, {})
            got[role] = p
            if len(got) == 2:
                ql = QuantLeaf(got["q"], got["scale"], entry.quant_dtypes[oi])
                del landed[oi]
                t0 = time.monotonic()
                out_outer[oi] = _dequantize_on_device(ql)
                dequant_s += time.monotonic() - t0
    return jax.tree_util.tree_unflatten(entry.treedef, out_outer), dequant_s


def unpack_entry_host(entry: Any) -> Any:
    """Rebuild the HOST pytree from a ``PackedModelEntry``'s retained
    chunks, expanding quant leaves on host (``dequant_host``). The sharded
    promotion path (ISSUE 20) consumes this: its transfer re-slices
    per-device segments out of whole leaves, so the whole-leaf chunk replay
    that ``promote_packed_entry`` runs doesn't apply — and partition rules
    name float leaves, so quant pairs must expand before sharding."""
    import jax

    from tfservingcache_tpu.models.registry import QuantLeaf

    flat: list[Any] = [None] * len(entry.shapes)
    for chunk, buf in entry.chunks:
        off = 0
        for i in chunk:
            shape = entry.shapes[i]
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            flat[i] = buf[off:off + n].reshape(shape)
            off += n
    outer: list[Any] = [None] * entry.treedef.num_leaves
    pending: dict[int, dict[str, Any]] = {}
    for i, (oi, role) in enumerate(entry.owner):
        if role == "plain":
            outer[oi] = flat[i]
        else:
            pending.setdefault(oi, {})[role] = flat[i]
    for oi, got in pending.items():
        ql = QuantLeaf(got["q"], got["scale"], entry.quant_dtypes[oi])
        outer[oi] = ql.dequant_host()
    return jax.tree_util.tree_unflatten(entry.treedef, outer)


@dataclass
class LoadedModel:
    model_def: ModelDef
    params: Any                      # device-resident pytree
    jitted: Any                      # jax.jit-wrapped apply
    hbm_bytes: int
    load_lock: threading.Lock = field(default_factory=threading.Lock)


@dataclass
class SlotDecodeState:
    """Device + host state of one model's continuous-decode slot array
    (runtime/batcher.py ContinuousGenerateEngine). Dense mode
    (``page_tokens == 0``): the K/V arrays are (layers, S, n_kv, max_seq,
    head_dim) — one lane per slot, advanced by ``_decode_chunk_jit`` and
    surgically written by admission inserts. Paged mode: ``k``/``v`` hold
    the shared page arena (layers, arena_pages + 1, n_kv, page_tokens, hd)
    — page 0 is the trash page — and each lane reads/writes through its
    ``block_tables`` row; the free-list hands pages out at admission and
    recycles them at retirement. The host mirrors (tok/pos/active/temps/
    topks, block tables, free-list) are owned by the engine's scheduler
    thread; the runtime only reads them to build chunk inputs."""

    model_id: ModelId
    cfg_key: tuple
    family: str
    slots: int
    max_seq: int
    k: Any                           # device slot array OR paged arena
    v: Any
    tok: np.ndarray                  # (S,) i32 — last sampled token per lane
    pos: np.ndarray                  # (S,) i32 — next write position
    active: np.ndarray               # (S,) bool
    temps: np.ndarray                # (S,) f32 per-lane temperature
    topks: np.ndarray                # (S,) i32 per-lane top_k
    chunk_counter: int = 0           # host-side PRNG stream for chunk keys
    # -- paged-arena bookkeeping (scheduler-thread-owned; page_tokens == 0
    # means dense mode and none of these are consulted) --
    page_tokens: int = 0
    arena_pages: int = 0             # usable pages (excludes trash page 0)
    pages_per_slot: int = 0          # ceil(max_seq / page_tokens)
    # int8 arena (serving.kv_arena_dtype): per-row f32 scale buffers riding
    # with the page payload ({"k","v"} device arrays, None for dense dtype).
    # All page bookkeeping above is PAGE-COUNT based, so quantization never
    # touches reserve/release/CoW/census semantics — scales just travel with
    # every page write/copy.
    scales: Any = None
    arena_dtype: str = ""            # "" = model dtype; "int8" = quantized
    # serving.kv_paged_kernel: fused Pallas paged-attention decode kernel
    # (ops/attention.paged_attention gate) vs the gather+einsum reference
    kernel: bool = True
    block_tables: np.ndarray | None = None   # (S, pages_per_slot) i32
    free_pages: list = field(default_factory=list)
    lane_pages: dict = field(default_factory=dict)  # lane -> [page ids]
    # -- cross-request shared-prefix KV (ISSUE 9): page_refs[pg] counts every
    # owner of a page — referencing lanes plus the prefix index's own holds.
    # A page is writable by a lane iff its refcount is exactly 1 (the lane is
    # the sole owner); a first write into a refs>1 page goes through CoW
    # (cow_page + generation._page_copy_jit). Invariant, checked by
    # check_page_conservation: every arena page is exactly one of free,
    # trash (page 0), or refs > 0.
    page_refs: np.ndarray | None = None      # (arena_pages + 1,) i32
    prefix_index: Any = None                 # PagePrefixIndex | None
    # -- in-engine speculative decoding (ISSUE 16): the draft model's own
    # SlotDecodeState rides on the target's — same slot count and
    # page_tokens, its own arena/tables/free-list/census, no prefix index —
    # so every scheduler reserve/release call mirrors 1:1 onto the draft
    # arena and both censuses stay exact. The draft state's tok/pos/active
    # host mirrors alias the target's (identical by construction: both
    # caches advance through the same accepted positions). None = spec off.
    spec_draft_id: Any = None        # ModelId of the attached draft
    spec_draft: Any = None           # the draft's SlotDecodeState
    spec_tokens: int = 0             # draft proposals per verify round

    @property
    def paged(self) -> bool:
        return self.page_tokens > 0

    def pages_needed(self, tokens: int) -> int:
        return -(-int(tokens) // self.page_tokens)

    def lane_capacity(self, lane: int) -> int:
        """Token capacity currently reserved for ``lane`` (page-granular)."""
        return len(self.lane_pages.get(lane, ())) * self.page_tokens

    def reserve_pages(self, lane: int, tokens: int,
                      shared_pages: list | tuple = (),
                      cow_headroom: int = 0) -> bool:
        """Reserve enough pages for ``tokens`` (the row's full prompt +
        max_new budget, so a mid-decode row can never starve) and point the
        lane's block table at them. ``shared_pages`` are already-resident
        prefix pages mapped READ-ONLY at the front of the row (refcount
        bump, no allocation — this is what multiplies admitted slots); only
        the private remainder is popped from the free-list, plus
        ``cow_headroom`` pages that must EXIST free but are left unpopped
        for an immediately-following slot_cow. False when the free-list
        can't cover it — the caller blocks admission and retries after
        retirements."""
        need = self.pages_needed(tokens)
        n_map = len(shared_pages)
        priv = max(0, need - n_map)
        if priv + cow_headroom > len(self.free_pages):
            return False
        pages = [int(pg) for pg in shared_pages]
        pages += [self.free_pages.pop() for _ in range(priv)]
        if self.page_refs is not None:
            for pg in pages:
                self.page_refs[pg] += 1
        self.lane_pages[lane] = pages
        self.block_tables[lane, :] = 0
        self.block_tables[lane, :len(pages)] = pages
        return True

    def release_pages(self, lane: int) -> None:
        """Drop a retired/failed lane's page references — a page returns to
        the free-list only when its LAST owner lets go (shared prefix pages
        survive for their other referencing lanes / the prefix index) — and
        park the lane on the trash page (zeroed table row) so its frozen
        in-chunk rewrites can never touch a recycled page's next occupant."""
        pages = self.lane_pages.pop(lane, None)
        if pages:
            if self.page_refs is None:
                self.free_pages.extend(pages)
            else:
                for pg in pages:
                    n = int(self.page_refs[pg]) - 1
                    self.page_refs[pg] = max(n, 0)
                    if n <= 0:
                        self.free_pages.append(pg)
        if self.block_tables is not None:
            self.block_tables[lane, :] = 0

    def cow_page(self, lane: int, slot: int) -> tuple[int, int] | None:
        """Host half of copy-on-write: swap ``lane``'s block-table entry at
        ``slot`` to a fresh free page and move the lane's reference onto it.
        Returns (src, dst) for the device page copy, or None when the
        free-list is empty (callers reserve cow_headroom so the admission
        protocol can't hit that)."""
        if not self.free_pages:
            return None
        src = int(self.block_tables[lane, slot])
        dst = self.free_pages.pop()
        self.page_refs[dst] = 1
        self.block_tables[lane, slot] = dst
        self.lane_pages[lane][slot] = dst
        n = int(self.page_refs[src]) - 1
        self.page_refs[src] = max(n, 0)
        if n <= 0:
            # the "shared" page was sole-owned after all (caller raced its
            # own check) — recycle rather than leak it
            self.free_pages.append(src)
        return src, dst

    def page_stats(self) -> dict:
        """Distinct-page split of the arena (trash page 0 excluded):
        ``free`` on the free-list, ``cached`` held only by the prefix index
        (reclaimable under admission pressure), ``shared`` referenced by a
        lane AND at least one other owner, ``private`` sole-owned by one
        lane. Used by the flight recorder / gauges; a shared page counts
        ONCE no matter how many lanes read it, so pages_used reflects true
        admission headroom."""
        lane_refs: dict[int, int] = {}
        for pages in self.lane_pages.values():
            for pg in pages:
                lane_refs[pg] = lane_refs.get(pg, 0) + 1
        held = (self.prefix_index.held_pages()
                if self.prefix_index is not None else {})
        shared = sum(1 for pg, n in lane_refs.items()
                     if n > 1 or pg in held)
        return {
            "free": len(self.free_pages),
            "cached": sum(1 for pg in held if pg not in lane_refs),
            "shared": shared,
            "private": len(lane_refs) - shared,
        }

    def check_page_conservation(self) -> None:
        """Assert the refcount invariant over the whole arena: every usable
        page is exactly one of free, or referenced, with ``page_refs``
        agreeing with the actual lane + index reference census — i.e. no
        page is leaked and none is double-booked. Test/bench hook (cheap:
        O(arena), host-only)."""
        if not self.paged:
            return
        census = np.zeros(self.arena_pages + 1, np.int64)
        for pages in self.lane_pages.values():
            for pg in pages:
                census[pg] += 1
        if self.prefix_index is not None:
            for pg, n in self.prefix_index.held_pages().items():
                census[pg] += n
        free = set(self.free_pages)
        assert len(free) == len(self.free_pages), "duplicate free-list pages"
        assert 0 not in free, "trash page on the free list"
        assert census[0] == 0, "trash page is referenced"
        for pg in range(1, self.arena_pages + 1):
            refs = int(census[pg])
            if pg in free:
                assert refs == 0, f"page {pg} free but referenced {refs}x"
            else:
                assert refs > 0, f"page {pg} leaked (not free, unreferenced)"
            if self.page_refs is not None:
                got = int(self.page_refs[pg])
                assert got == refs, (
                    f"page {pg}: page_refs says {got}, census says {refs}"
                )


# TPUSC_PAGECHECK=1 (same opt-in idiom as utils/lockcheck.py's
# TPUSC_LOCKCHECK): assert before every paged decode chunk that no LIVE
# lane's block table maps the trash page below its visible position.
# `paged_gather_kv` / the Pallas kernel read whatever the table points at —
# a trash-page entry behind `pos` would silently attend over junk KV (no
# crash, just wrong tokens), which is exactly the failure mode this guard
# exists to catch in tests and soaks.
_PAGECHECK = os.environ.get("TPUSC_PAGECHECK", "") == "1"


def _check_trash_unreachable(state: SlotDecodeState) -> None:
    """Raise if any active lane's block-table row maps page 0 (trash) in a
    slot the lane's attention window can reach (pages covering tokens
    0..pos inclusive). Host-only, O(slots x pages_per_slot)."""
    for lane in range(state.slots):
        if not bool(state.active[lane]):
            continue
        # pos is the NEXT write position; the chunk's first step writes at
        # pos and attends over 0..pos inclusive
        live = state.pages_needed(int(state.pos[lane]) + 1)
        row = state.block_tables[lane, :live]
        if (row == 0).any():
            bad = int(np.argmax(row == 0))
            raise AssertionError(
                f"TPUSC_PAGECHECK: lane {lane} maps trash page 0 at "
                f"block-table slot {bad} below pos={int(state.pos[lane])} "
                f"(live pages={live}) — attention would read junk KV"
            )


def _mesh_serialized(fn):
    """Serialize device-program launches on mesh runtimes (ISSUE 20). A
    partitioned program's launch enqueues a collective participant on every
    mesh device; two threads interleaving launches can enqueue them in
    DIFFERENT per-device orders — the CPU backend deadlocks its rendezvous
    outright, and real device queues would cross-schedule the collectives.
    Every dispatch entry point that an arbitrary thread may call (solo
    generate/predict, the engine scheduler's slot_* ops) holds the
    runtime-wide RLock for the duration of the call, so launches hit all
    devices in one consistent order. Single-device runtimes skip the lock:
    concurrent dispatch overlap there is free and safe."""

    @functools.wraps(fn)
    def wrapped(self, *args, **kwargs):
        if self.mesh is None:
            return fn(self, *args, **kwargs)
        with self._mesh_dispatch_lock:
            return fn(self, *args, **kwargs)

    return wrapped


@lockchecked
class TPUModelRuntime(BaseRuntime):
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_load_locks": "_load_locks_guard",
        "_adopted": "_adopted_lock",
        "_spec_health": "_spec_lock",
        "_jitted_by_key": "_jit_lock",
        "_aot_cache": "_aot_lock",
        "_aot_futures": "_aot_lock",
        "_slot_states": "_slot_lock",
        "_slot_init_guards": "_slot_lock",
    }

    def __init__(
        self,
        cfg: ServingConfig | None = None,
        metrics: Metrics | None = None,
        mesh: Any | None = None,
        group: int = 0,
        host_tier_bytes: int = 0,
    ) -> None:
        super().__init__()
        import jax

        self.cfg = cfg or ServingConfig()
        self.metrics = metrics
        self.mesh = mesh  # jax.sharding.Mesh for multi-chip models (parallel/)
        self.group = group  # chip-group index on this host (metrics label)
        if self.cfg.compile_cache_dir:
            # persistent XLA compile cache: restart != recompile-the-world
            # (SURVEY.md §5 checkpoint/resume note)
            jax.config.update("jax_compilation_cache_dir", self.cfg.compile_cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        # LOCAL devices: in a multi-controller (cross-host) deployment
        # jax.devices() includes peers' non-addressable chips — the
        # single-device path and health probe must stay on this process's own
        self._devices = jax.local_devices(backend=self.cfg.platform or None)
        if mesh is not None:
            from tfservingcache_tpu.parallel.sharding import is_single_process

            # does this runtime's chip-group mesh span processes?
            self._mp_mesh = not is_single_process(mesh)
        else:
            self._mp_mesh = False
        self._replicate_out = None  # lazily-built cached reshard-identity jit
        self._resident = make_lru_cache(
            self.cfg.hbm_capacity_bytes,
            on_evict=self._on_evict,
            max_items=self.cfg.max_concurrent_models,
        )
        self._load_locks: dict[ModelId, threading.Lock] = {}
        self._load_locks_guard = threading.Lock()
        # one-shot transfer-ready entries handed over by a peer fetch
        # (CacheManager adopt, cache/providers/peer.py): the next _load of
        # that model promotes straight from these chunks — no artifact
        # read-back of bytes that just crossed the wire. Independent of the
        # host tier on purpose: the fast first load must not depend on the
        # warm-tier budget being enabled.
        self._adopted: dict[ModelId, Any] = {}
        self._adopted_lock = threading.Lock()
        # Host-RAM warm tier (cache/host_tier.py): packed transfer chunks +
        # executable handles of evicted models, so re-admission skips fetch
        # and decode and pays only the H2D stream. Single-process only
        # (ISSUE 20 lifted the single-process-mesh gate, mesh_fast_path
        # restores it): a CROSS-HOST group's device-op stream must not
        # depend on which models happen to sit in one process's host tier,
        # but a mesh owned entirely by this process has no peer to diverge
        # from. Demotions that must re-pack from the device copy run on the
        # worker thread below — never in the evicting thread, which
        # typically holds load or slot-map locks (see _on_evict).
        self._host_tier = None
        self._demote_queue: queue.Queue | None = None
        if host_tier_bytes > 0 and (
            mesh is None or (not self._mp_mesh and self.cfg.mesh_fast_path)
        ):
            from tfservingcache_tpu.cache.host_tier import HostRamTier

            self._host_tier = HostRamTier(host_tier_bytes, metrics)
            self._demote_queue = queue.Queue()
            self._demote_thread = threading.Thread(
                target=self._demote_loop, name="tpusc-demote", daemon=True
            )
            self._demote_thread.start()
        # prefix KV cache (OFF unless budgeted). Mesh/group runtimes get it
        # too (VERDICT r5 #7): on a cross-host group every process's cache
        # evolves identically under the lockstep op stream, the LEADER's hit
        # decision rides the work envelope (prefix_rows below) so followers
        # provably run the same program, and a reform (multihost.py) resets
        # every cache to empty together
        self._prefix_cache = None
        if self.cfg.prefix_cache_bytes > 0:
            from tfservingcache_tpu.runtime.prefix_cache import PrefixCache

            self._prefix_cache = PrefixCache(self.cfg.prefix_cache_bytes)
        # speculative acceptance gate (_spec_admit/_spec_observe): per
        # (target, draft) low-acceptance streaks and disabled flags.
        # Active on single-process runtimes; a multi-process FOLLOWER keeps
        # it off (it obeys the envelope), and the group LEADER re-activates
        # it (multihost.py) to decide for the whole group.
        self._spec_health: dict[tuple[ModelId, ModelId], dict] = {}
        self._spec_lock = threading.Lock()
        self._spec_gate_active = not self._mp_mesh
        # One jitted apply per (family, config) build key: all tenants of a
        # family share one XLA executable — tenant N's cold load is
        # params-transfer only. Entries are refcounted by resident models and
        # dropped when the last tenant is evicted, so executables don't pin
        # device memory after every user of them is gone.
        self._jitted_by_key: dict[str, tuple[Any, int]] = {}
        # RLock: _resident.put below runs eviction callbacks (_on_evict takes
        # this lock to decrement) in the inserting thread
        self._jit_lock = threading.RLock()
        # Pipelined cold load: AOT executables compiled on a side executor
        # concurrently with the params transfer, keyed by
        # (family cache_key, input signature). jax.jit's dispatch cache never
        # sees AOT-compiled programs, so warmup and predict must route
        # matching calls through these directly; entries share the lifetime
        # of the family's refcounted jit entry (_on_evict / close).
        self._aot_cache: dict[tuple[str, tuple], tuple[Any, float, float]] = {}
        self._aot_futures: dict[tuple[str, tuple], Any] = {}
        self._aot_lock = threading.Lock()
        self._compile_pool: Any = None  # lazy 1-thread executor
        # continuous-decode slot arrays (ContinuousGenerateEngine), one per
        # model with in-flight continuous generates. Their K/V HBM is
        # engine-owned working memory (like the prefix cache's budget, it is
        # NOT charged to the resident-model LRU) and dies with the model:
        # _on_evict / reset_group_state / close all drop it.
        self._slot_states: dict[ModelId, SlotDecodeState] = {}
        self._slot_lock = threading.Lock()
        # _mesh_serialized: one consistent per-device launch order for
        # partitioned programs (held only when self.mesh is not None)
        self._mesh_dispatch_lock = threading.RLock()
        # per-model once-guards for slot-state allocation (the array is big;
        # see slot_decode_state) — entries are popped once the state lands
        self._slot_init_guards: dict[ModelId, threading.Lock] = {}

    # -- load ---------------------------------------------------------------
    def ensure_loaded(self, model: Model) -> str:
        """-> which residency tier actually served this call: ``"hbm"``
        (already resident), ``"host"`` (warm-tier promotion), ``"disk"``
        (full load from the artifact). Feeds the ``tpusc_reload_source``
        counter in CacheManager."""
        mid = model.identifier
        if self.is_loaded(mid):
            return "hbm"
        with self._load_locks_guard:
            lock = self._load_locks.setdefault(mid, threading.Lock())
        try:
            with lock:
                if self.is_loaded(mid):  # singleflight: someone else finished it
                    return "hbm"
                return self._load(model)
        finally:
            # Failure-path pruning (mirror of _on_evict): a model whose load
            # keeps failing never becomes resident, so the evict-side prune
            # never runs for it and a storm of failing tenants would grow
            # this dict without bound. Drop the idle lock when nothing landed.
            if not self.is_loaded(mid):
                with self._load_locks_guard:
                    held = self._load_locks.get(mid)
                    if held is lock and not held.locked():
                        del self._load_locks[mid]

    def adopt_packed_entry(self, model_id: ModelId, entry: Any) -> None:
        """Hand over a transfer-ready ``PackedModelEntry`` that did NOT come
        from this runtime's own demotion — a peer fetch rebuilt it off the
        wire (protocol/peer_transfer.py). The next ``_load`` of ``model_id``
        consumes it via the promotion path: same pipelined device_put the
        warm tier replays, skipping the artifact read-back. One-shot and
        advisory: a MULTI-PROCESS mesh runtime drops it (cross-host group op
        streams must not depend on per-process residency; a single-process
        mesh promotes it through the sharded replay — ISSUE 20), and any
        promotion failure falls through to the full disk load."""
        if self.mesh_lockstep:
            return
        with self._adopted_lock:
            self._adopted[model_id] = entry

    def _fill_family_jit(self, entry: Any) -> None:
        """A demoted entry carries the family's live jit handle; a
        wire-adopted one can't. If the family executable is still resident
        this is a no-op (_promote shares it); otherwise build the same jit
        the disk path would so promotion installs a usable handle. Adoption
        is gated to single-process runtimes (mesh_lockstep), so the plain
        jit suffices — sharding comes from the committed params, and a
        mesh-bound family gets its apply rebound here just like the disk
        path would."""
        import jax

        model_def = entry.model_def
        apply_fn = (
            model_def.bind_mesh(self.mesh)
            if (self.mesh is not None and model_def.bind_mesh is not None)
            else model_def.apply
        )
        with self._jit_lock:
            if model_def.cache_key in self._jitted_by_key:
                return
            entry.jitted = jax.jit(apply_fn)

    def _load(self, model: Model) -> str:
        mid = model.identifier
        with self._adopted_lock:
            adopted = self._adopted.pop(mid, None)
        if adopted is not None:
            try:
                if adopted.jitted is None:
                    self._fill_family_jit(adopted)
                self._promote(model, adopted)
                return "host"
            except Exception as e:  # noqa: BLE001 - full path still works
                log.warning(
                    "promotion of adopted entry for %s failed (%s); "
                    "falling back to the full load path", mid, e,
                )
        if self._host_tier is not None:
            entry = self._host_tier.get(mid)
            if entry is not None:
                try:
                    self._promote(model, entry)
                    return "host"
                except Exception as e:  # noqa: BLE001 - full path still works
                    log.warning(
                        "host-tier promotion of %s failed (%s); "
                        "falling back to the full load path", mid, e,
                    )
                    self._host_tier.remove(mid)
        self._set_state(mid, ModelState.START)
        t0 = time.monotonic()
        with TRACER.span("load", model=str(mid), tier="disk") as load_span:
            self._load_traced(model, mid, t0, load_span)
        # Σ(stage)/wall: ~1.0 = strictly serialized stages, >1 = the
        # pipeline overlapped them (AOT compile / per-leaf dequant running
        # during the transfer). Annotated on the span AND observed as a
        # metric so bench artifacts surface the win without re-deriving it.
        if load_span.children and load_span.duration_s > 0:
            ratio = (
                sum(c.duration_s for c in load_span.children)
                / load_span.duration_s
            )
            load_span.attrs["cold_overlap_ratio"] = round(ratio, 3)
            if self.metrics is not None:
                self.metrics.cold_overlap_ratio.observe(ratio)
        if self.metrics is not None:
            # per-stage cold histograms: the in-production "where do my cold
            # seconds go" (and the int8 crossover: device_transfer +
            # device_dequant across artifact encodings on THIS link)
            for child in load_span.children:
                self.metrics.cold_stage_seconds.labels(child.name).observe(
                    child.duration_s
                )
        return "disk"

    def _promote(self, model: Model, entry: Any) -> None:
        """Host-tier promotion: stream the retained packed chunks back into
        HBM and rebind the retained executable handles. No provider fetch,
        no artifact read, no host decode, no warmup — the retained jit
        handle still carries the family's compiled dispatch cache (and the
        AOT entries rebound below route warmup-shaped calls), so the only
        wall time is the H2D replay itself."""
        import jax

        mid = model.identifier
        self._set_state(mid, ModelState.START)
        t0 = time.monotonic()
        hbm = 0
        try:
            with TRACER.span("load", model=str(mid), tier="host") as load_span:
                self._set_state(mid, ModelState.LOADING)
                rules = entry.model_def.partition_rules
                if self.mesh is not None and rules:
                    # sharded replay (ISSUE 20): rebuild host leaves and
                    # stream per-device shard chunks — the committed
                    # shardings must match what the disk load produced, or
                    # the revived executable would reshard on first call
                    from tfservingcache_tpu.parallel.sharding import (
                        param_shardings,
                    )

                    with TRACER.span(
                        "device_transfer", promoted=True, sharded=True
                    ):
                        host_params = unpack_entry_host(entry)
                        params = packed_device_put_sharded(
                            host_params,
                            param_shardings(host_params, rules, self.mesh),
                            buffer_depth=self.cfg.cold_pipeline_buffer_depth,
                        )
                        del host_params
                    dequant_s = 0.0
                else:
                    with TRACER.span("device_transfer", promoted=True):
                        params, dequant_s = promote_packed_entry(
                            entry, self._devices[0]
                        )
                if dequant_s > 0:
                    TRACER.attach(
                        load_span, "device_dequant", dequant_s, overlapped=True
                    )
                model_def = entry.model_def
                key = model_def.cache_key
                with self._jit_lock:
                    shared = self._jitted_by_key.get(key)
                    created = shared is None
                    if created:
                        # family executable died with its last HBM tenant;
                        # the tier entry's handle revives it (jit's dispatch
                        # cache lives on the function object, so prior
                        # compiles come back with it)
                        jitted = entry.jitted
                        self._jitted_by_key[key] = (jitted, 0)
                    else:
                        jitted = shared[0]
                if entry.aot_entries:
                    with self._aot_lock:
                        for k, v in entry.aot_entries.items():
                            self._aot_cache.setdefault(k, v)
                hbm = entry.hbm_bytes or tree_nbytes(params)
                loaded = LoadedModel(model_def, params, jitted, hbm)
                TRACER.annotate(hbm_bytes=hbm, promoted_from="host")
                try:
                    with TRACER.span("transfer_sync", pinned_by="promotion"):
                        jax.block_until_ready(params)
                    with self._jit_lock:
                        jfn, refs = self._jitted_by_key.get(key, (jitted, 0))
                        self._jitted_by_key[key] = (jfn, refs + 1)
                        try:
                            self._resident.put(mid, hbm, loaded)
                        except Exception:
                            jfn, refs = self._jitted_by_key[key]
                            if refs <= 1:
                                del self._jitted_by_key[key]
                            else:
                                self._jitted_by_key[key] = (jfn, refs - 1)
                            raise
                except Exception:
                    with self._jit_lock:
                        cur = self._jitted_by_key.get(key)
                        if created and cur is not None and cur[1] == 0:
                            del self._jitted_by_key[key]
                            self._drop_aot_family(key)
                    raise
                self._set_state(mid, ModelState.AVAILABLE)
        except Exception as e:
            self._set_state(mid, ModelState.END)
            raise RuntimeError_(f"failed to promote {mid}: {e}") from e
        dt = time.monotonic() - t0
        if self.metrics is not None:
            self.metrics.compile_duration.labels(
                self.metrics.model_label(mid.name, mid.version)
            ).observe(dt)
        self._update_gauges()
        log.info(
            "promoted %s from host tier in %.3fs (%d HBM bytes)", mid, dt, hbm
        )

    def _load_traced(
        self, model: Model, mid: ModelId, t0: float, load_span: Any
    ) -> None:
        import jax

        try:
            self._set_state(mid, ModelState.LOADING)
            with TRACER.span("artifact_read"):
                # always read int8 artifacts RAW (q + scales): which branch
                # dequantizes where is only known after the family's
                # partition rules are in hand
                model_def, host_params = load_artifact(
                    model.path, raw_quant=True
                )
            from tfservingcache_tpu.models.registry import QuantLeaf

            has_quant = any(
                isinstance(x, QuantLeaf)
                for x in jax.tree_util.tree_leaves(
                    host_params, is_leaf=lambda n: isinstance(n, QuantLeaf)
                )
            )
            pipelined = self.cold_pipeline_enabled
            captured: list | None = None  # host-tier chunk capture (pipelined)
            if pipelined and self.cfg.warmup:
                # first tenant of a family: get the AOT compile in flight
                # BEFORE the transfer starts so they overlap. (A streaming
                # provider fetch kicks this even earlier, off model.json —
                # but STALE reloads and non-streaming providers arrive here
                # with nothing in flight.)
                with self._jit_lock:
                    first_tenant = model_def.cache_key not in self._jitted_by_key
                if first_tenant:
                    self._precompile_async(
                        model_def, _abstract_post_dequant(host_params)
                    )
            if self.mesh is not None and model_def.partition_rules:
                # multi-chip model: params sharded over the chip group per the
                # family's partition rules; XLA partitions the computation and
                # inserts ICI collectives from the committed shardings.
                # Quant leaves dequantize on HOST first — the rules name
                # float leaves, not q/scale pairs.
                from tfservingcache_tpu.parallel.sharding import (
                    param_shardings,
                    shard_params,
                )

                if has_quant:
                    # its own stage: the int8 crossover comparison must see
                    # where the mesh path's dequant seconds go (host, here)
                    with TRACER.span("host_dequant"):
                        host_params = _dequantize_on_host(host_params)
                if pipelined:
                    # per-device packed-chunk streaming (ISSUE 20): the
                    # shard filter feeds each device only its own bytes,
                    # chunk assembly overlapping the previous chunk's
                    # device_put, and the AOT compile submitted above
                    # overlaps the whole transfer — the same pipeline the
                    # single-chip path runs, sharding-parameterized
                    with TRACER.span(
                        "device_transfer", pipelined=True, sharded=True
                    ):
                        params, _ = packed_device_put_pipelined(
                            host_params,
                            self._devices[0],
                            buffer_depth=self.cfg.cold_pipeline_buffer_depth,
                            shardings=param_shardings(
                                host_params,
                                model_def.partition_rules,
                                self.mesh,
                            ),
                        )
                else:
                    with TRACER.span("device_transfer"):
                        params = shard_params(
                            host_params, model_def.partition_rules, self.mesh
                        )
            elif pipelined:
                # pipelined packed path: host chunk assembly on a side
                # thread, device ops in the identical _pack_plan order on
                # this one, quant leaves dequantized as they land
                if self._host_tier is not None:
                    captured = []
                with TRACER.span("device_transfer", pipelined=True):
                    params, dequant_s = packed_device_put_pipelined(
                        host_params,
                        self._devices[0],
                        buffer_depth=self.cfg.cold_pipeline_buffer_depth,
                        capture=captured,
                    )
                if has_quant:
                    # the dequant dispatches ran INSIDE the transfer span;
                    # attach their accumulated time as the usual
                    # device_dequant stage so the histogram stays comparable
                    # across serialized and pipelined loads (quant-only, as
                    # in the serialized branch)
                    TRACER.attach(
                        load_span, "device_dequant", dequant_s, overlapped=True
                    )
            else:
                # packed path ships the raw int8 bytes — the transfer is the
                # cold-path bottleneck the int8 artifact exists to halve —
                # and dequantizes on device
                with TRACER.span("device_transfer"):
                    params = packed_device_put(host_params, self._devices[0])
                if has_quant:
                    # own span, quantized artifacts only: a no-op dequant
                    # sample per bf16 load would blend the histogram the
                    # cross-encoding comparison reads
                    with TRACER.span("device_dequant"):
                        params = _dequantize_on_device(params)
            key = model_def.cache_key
            # mesh-aware families (ring/context-parallel attention) build
            # their apply against THIS group's mesh; per-runtime jit cache
            # means the binding can't leak across groups
            apply_fn = (
                model_def.bind_mesh(self.mesh)
                if (self.mesh is not None and model_def.bind_mesh is not None)
                else model_def.apply
            )
            with self._jit_lock:
                entry = self._jitted_by_key.get(key)
                created = entry is None
                if created:
                    if self._mp_mesh:
                        # cross-process group: outputs must come back fully
                        # replicated so the leader process can read them (a
                        # sharded output is only partially addressable here)
                        from jax.sharding import NamedSharding, PartitionSpec

                        jitted = jax.jit(
                            apply_fn,
                            out_shardings=NamedSharding(self.mesh, PartitionSpec()),
                        )
                    else:
                        jitted = jax.jit(apply_fn)
                    # refcount 0 until this model is actually resident; the
                    # failure path below removes a 0-ref entry it created
                    self._jitted_by_key[key] = (jitted, 0)
                else:
                    jitted = entry[0]
            try:
                hbm = tree_nbytes(params)
                loaded = LoadedModel(model_def, params, jitted, hbm)
                TRACER.annotate(hbm_bytes=hbm, shared_executable=not created)
                if self.cfg.warmup and created:
                    # first tenant of a family: compile + pin before AVAILABLE.
                    # Siblings share the executable, so their warmup would be
                    # a pure extra device round trip — skip it and only force
                    # the (async) params transfer to completion instead.
                    aot = self._aot_wait(model_def) if pipelined else None
                    if aot is not None:
                        compiled, compile_s, started = aot
                        # the compile ran on the executor, overlapped with
                        # fetch/read/transfer: attach its TRUE duration as
                        # the usual compile_warmup stage (histogram and
                        # first-load classification stay comparable) while
                        # the wall only paid whatever wait remained
                        TRACER.attach(
                            load_span, "compile_warmup", compile_s,
                            start_s=started, family=model_def.family,
                            overlapped=True,
                        )
                        try:
                            with TRACER.span("transfer_sync", pinned_by="aot_warmup"):
                                self._warmup(loaded, compiled=compiled)
                        except Exception as e:  # noqa: BLE001 - jit always works
                            log.warning(
                                "AOT warmup for %s failed (%s); recompiling via jit",
                                model_def.family, e,
                            )
                            self._drop_aot(model_def)
                            with TRACER.span("compile_warmup", family=model_def.family):
                                self._warmup(loaded)
                    else:
                        with TRACER.span("compile_warmup", family=model_def.family):
                            self._warmup(loaded)  # compile here, outside the lock
                else:
                    # transfer is async: this sync is where the host<->HBM
                    # link's sustained rate actually shows up for siblings
                    with TRACER.span("transfer_sync"):
                        jax.block_until_ready(params)
                with self._jit_lock:
                    # increment + insert atomically w.r.t. evictions: an
                    # eviction of a same-family sibling between put and
                    # increment would otherwise free the shared executable
                    jfn, refs = self._jitted_by_key.get(key, (jitted, 0))
                    self._jitted_by_key[key] = (jfn, refs + 1)
                    try:
                        self._resident.put(mid, hbm, loaded)
                    except Exception:
                        jfn, refs = self._jitted_by_key[key]
                        if refs <= 1:
                            del self._jitted_by_key[key]
                        else:
                            self._jitted_by_key[key] = (jfn, refs - 1)
                        raise
            except Exception:
                with self._jit_lock:
                    cur = self._jitted_by_key.get(key)
                    if created and cur is not None and cur[1] == 0:
                        del self._jitted_by_key[key]  # don't pin an executable no one uses
                        self._drop_aot_family(key)
                raise
            # eager inclusive retain: the packed chunks are in hand right
            # now (captured off the pipelined transfer, or rebuilt from
            # host_params) — retaining at load time instead of only at
            # eviction means demotion is usually a pure LRU touch, never a
            # device_get, and a model evicted microseconds after load is
            # still promotable. Advisory: failure just means this model
            # reloads the slow way.
            self._retain_packed(mid, model_def, host_params, jitted, hbm, captured)
            self._set_state(mid, ModelState.AVAILABLE)
        except Exception as e:
            self._set_state(mid, ModelState.END)
            raise RuntimeError_(f"failed to load {mid}: {e}") from e
        dt = time.monotonic() - t0
        if self.metrics is not None:
            self.metrics.compile_duration.labels(
                self.metrics.model_label(mid.name, mid.version)
            ).observe(dt)
        self._update_gauges()
        log.info("loaded %s in %.2fs (%d HBM bytes)", mid, dt, hbm)

    def _warmup(self, loaded: LoadedModel, compiled: Any = None) -> None:
        """One tiny call per model at load: compiles the bucket-1 executable
        and pins params before the first real request hits. ``compiled`` (a
        pipelined load's AOT executable) is invoked directly — AOT
        compilation does not seed jax.jit's dispatch cache, so going through
        ``loaded.jitted`` here would pay the full compile a second time."""
        import jax

        inputs = {
            name: np.zeros(self._concrete_shape(spec, 1), spec.np_dtype())
            for name, spec in loaded.model_def.input_spec.items()
        }
        fn = compiled if compiled is not None else loaded.jitted
        out = fn(loaded.params, inputs)
        jax.block_until_ready(out)

    @staticmethod
    def _concrete_shape(spec: TensorSpec, batch: int) -> tuple[int, ...]:
        return tuple(batch if isinstance(d, str) else d for d in spec.norm_shape())

    # -- pipelined cold load (compile-while-transfer) -----------------------
    @property
    def mesh_lockstep(self) -> bool:
        """True when this runtime's device-op stream must stay LOCKSTEP — a
        pure function of the request sequence, never of host thread timing
        or per-process residency — which is what actually forces the
        serialized-load/coalesce-generate fallbacks. Before ISSUE 20 every
        mesh runtime was lockstep; now only cross-process groups are (each
        follower must replay the leader's exact op stream), plus any mesh
        with ``serving.mesh_fast_path`` off (the A/B lever). Consumers:
        adopt_packed_entry, the batcher's engine dispatch, and the local
        backend's engine construction."""
        return self.mesh is not None and (
            self._mp_mesh or not self.cfg.mesh_fast_path
        )

    @property
    def cold_pipeline_enabled(self) -> bool:
        """Pipelined cold loads run on single-chip AND single-process mesh
        runtimes (ISSUE 20): the sharded branch streams per-device shard
        chunks through ``packed_device_put_sharded``, feeding each device
        only its own bytes. Lockstep (cross-host) groups keep the strictly
        serialized path regardless of the config flag — their device-op
        stream must stay a pure function of the load sequence, never of
        host thread timing."""
        return bool(self.cfg.cold_load_pipeline) and not self.mesh_lockstep

    def precompile_from_meta(self, meta: Mapping[str, Any]) -> None:
        """Start the family AOT compile from artifact metadata alone —
        called by CacheManager's streaming fetch the moment model.json
        lands, while params.bin is still coming off the provider. Advisory:
        any failure just leaves the load on the compile-in-warmup path."""
        if not (self.cold_pipeline_enabled and self.cfg.warmup):
            return
        try:
            from tfservingcache_tpu.models.registry import (
                abstract_params_from_meta,
                build,
            )

            abs_params = abstract_params_from_meta(meta)
            if abs_params is None:
                return  # v1 artifact: no manifest to precompile from
            model_def = build(meta["family"], meta.get("config"))
            with self._jit_lock:
                if model_def.cache_key in self._jitted_by_key:
                    return  # family executable already live: nothing to hide
            self._precompile_async(model_def, abs_params)
        except Exception as e:  # noqa: BLE001 - advisory only
            log.debug("early precompile skipped: %s", e)

    def _warmup_sig(self, model_def: ModelDef) -> tuple:
        return tuple(sorted(
            (name, self._concrete_shape(spec, 1), spec.np_dtype().name)
            for name, spec in model_def.input_spec.items()
        ))

    @staticmethod
    def _inputs_sig(inputs: Mapping[str, np.ndarray]) -> tuple:
        return tuple(sorted(
            (name, tuple(a.shape), a.dtype.name) for name, a in inputs.items()
        ))

    def _precompile_async(self, model_def: ModelDef, abs_params: Any):
        """Submit (idempotently) the family's warmup-signature AOT compile;
        -> the in-flight Future, or None when already compiled."""
        key = (model_def.cache_key, self._warmup_sig(model_def))
        with self._aot_lock:
            if key in self._aot_cache:
                return None
            fut = self._aot_futures.get(key)
            if fut is not None:
                return fut
            if self._compile_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                self._compile_pool = ThreadPoolExecutor(
                    max_workers=1, thread_name_prefix="tpusc-precompile"
                )
            fut = self._compile_pool.submit(
                self._aot_compile, model_def, abs_params, key
            )
            self._aot_futures[key] = fut
            return fut

    def _aot_compile(  # jit-surface: AOT warmup, one-shot per key via _aot_futures under _aot_lock
        self, model_def: ModelDef, abs_params: Any, key: tuple
    ) -> tuple[Any, float, float]:
        import jax

        started = time.time()
        t0 = time.monotonic()
        try:
            abs_inputs = {
                name: jax.ShapeDtypeStruct(
                    self._concrete_shape(spec, 1), spec.np_dtype()
                )
                for name, spec in model_def.input_spec.items()
            }
            apply_fn = model_def.apply
            if self.mesh is not None and model_def.partition_rules:
                # mesh AOT (ISSUE 20): lower against SHARDED abstract params
                # — the executable the sharded pipelined load installs must
                # accept the committed layouts the transfer produces, or
                # _apply_fast would silently recompile via jit on first use
                from tfservingcache_tpu.parallel.sharding import (
                    param_shardings,
                )

                shardings = param_shardings(
                    abs_params, model_def.partition_rules, self.mesh
                )
                abs_params = jax.tree_util.tree_map(
                    lambda a, s: jax.ShapeDtypeStruct(
                        a.shape, a.dtype, sharding=s
                    ),
                    abs_params,
                    shardings,
                )
                if model_def.bind_mesh is not None:
                    apply_fn = model_def.bind_mesh(self.mesh)
            compiled = (
                jax.jit(apply_fn).lower(abs_params, abs_inputs).compile()
            )
        except BaseException:
            with self._aot_lock:
                self._aot_futures.pop(key, None)
            raise
        entry = (compiled, time.monotonic() - t0, started)
        with self._aot_lock:
            self._aot_cache[key] = entry
            self._aot_futures.pop(key, None)
        return entry

    def _aot_wait(self, model_def: ModelDef) -> tuple[Any, float, float] | None:
        """The family's warmup-signature AOT entry, waiting out an in-flight
        compile — None when never submitted or the compile failed."""
        key = (model_def.cache_key, self._warmup_sig(model_def))
        with self._aot_lock:
            entry = self._aot_cache.get(key)
            fut = self._aot_futures.get(key)
        if entry is not None:
            return entry
        if fut is None:
            return None
        try:
            return fut.result()
        except Exception as e:  # noqa: BLE001 - fall back to jit warmup
            log.warning(
                "AOT precompile of %s failed (%s); falling back to jit warmup",
                model_def.family, e,
            )
            return None

    def _drop_aot(self, model_def: ModelDef) -> None:
        with self._aot_lock:
            self._aot_cache.pop(
                (model_def.cache_key, self._warmup_sig(model_def)), None
            )

    def _drop_aot_family(self, cache_key: str) -> None:
        """Drop a family's AOT executables alongside its freed jit entry
        (last tenant evicted) — they must not outlive the executable they
        shadow."""
        with self._aot_lock:
            for k in [k for k in self._aot_cache if k[0] == cache_key]:
                del self._aot_cache[k]

    def _apply_fast(
        self, loaded: LoadedModel, padded: Mapping[str, np.ndarray]
    ) -> Any:
        """Run the forward through the family's AOT executable when this
        exact padded signature has one (a pipelined load's warmup shapes),
        else through jit dispatch. jax.jit never sees AOT-compiled programs,
        so without this routing the first predict after a pipelined load at
        the warmup shape would silently recompile."""
        # one uncontended acquire per predict (_aot_lock only ever guards
        # dict ops, never a compile); the common no-AOT case skips the
        # signature computation entirely
        key = entry = None
        with self._aot_lock:
            if self._aot_cache:
                key = (loaded.model_def.cache_key, self._inputs_sig(padded))
                entry = self._aot_cache.get(key)
        if entry is not None:
            try:
                return entry[0](loaded.params, dict(padded))
            except Exception as e:  # noqa: BLE001 - jit path always works
                log.warning(
                    "AOT executable rejected inputs (%s); using jit", e
                )
                with self._aot_lock:
                    self._aot_cache.pop(key, None)
        return loaded.jitted(loaded.params, padded)

    # -- predict ------------------------------------------------------------
    @_mesh_serialized
    def predict(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        output_filter: list[str] | None = None,
    ) -> dict[str, np.ndarray]:
        import jax

        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        spec = loaded.model_def.input_spec
        missing = set(spec) - set(inputs)
        if missing:
            raise RuntimeError_(f"missing inputs {sorted(missing)} for {model_id}")
        unknown = set(inputs) - set(spec)
        if unknown:
            raise RuntimeError_(f"unknown inputs {sorted(unknown)} for {model_id}")

        dyn_sizes, padded = self._pad_to_bucket(spec, inputs, loaded.model_def.axis_caps)
        out_spec = loaded.model_def.output_spec
        derived = loaded.model_def.derived_outputs
        if output_filter:
            names = list(output_filter)
        elif loaded.model_def.default_outputs:
            # family-declared serving default (LMs: last_token_logits) —
            # full outputs stay reachable via an explicit output_filter
            names = list(loaded.model_def.default_outputs)
        else:
            names = list(out_spec)
        unknown_out = [n for n in names if n not in out_spec and n not in derived]
        if unknown_out:
            raise RuntimeError_(
                f"output_filter names unknown outputs {unknown_out} for {model_id} "
                f"(available: {sorted(out_spec) + sorted(derived)})"
            )
        with TRACER.span("infer", model=str(model_id)):
            dev_out = self._apply_fast(loaded, padded)
            # select + un-pad ON DEVICE so device_get ships only the bytes
            # the caller asked for — for an LM, last_token_logits transfers
            # (B, V) instead of the padded (B', S', V) logits tensor
            selected: dict[str, Any] = {}
            for name in names:
                if name in derived:
                    fn, _dspec = derived[name]
                    selected[name] = fn(dev_out, dyn_sizes)
                    continue
                arr = dev_out[name]
                ospec = out_spec[name]
                if dyn_sizes:
                    for axis, axis_name in ospec.dynamic_axes():
                        true = dyn_sizes.get(axis_name)
                        if (
                            true is not None
                            and getattr(arr, "ndim", 0) > axis
                            and arr.shape[axis] > true
                        ):
                            arr = jax.lax.slice_in_dim(arr, 0, true, axis=axis)
                selected[name] = arr
            out = jax.device_get(selected)
        return {name: np.asarray(arr) for name, arr in out.items()}

    def _pad_to_bucket(
        self,
        spec: Mapping[str, TensorSpec],
        inputs: Mapping[str, np.ndarray],
        axis_caps: Mapping[str, int] | None = None,
    ) -> tuple[dict[str, int], dict[str, np.ndarray]]:
        """-> (true size per named dynamic axis, padded inputs).

        Every named dynamic axis ("batch", "seq", ...) is padded up to its own
        power-of-two bucket; the same name must agree across inputs. A capped
        axis (ModelDef.axis_caps, e.g. BERT's pos-table max_seq) clamps the
        bucket to the cap and rejects true sizes beyond it.
        """
        dyn_sizes: dict[str, int] = {}
        for name, s in spec.items():
            arr = np.asarray(inputs[name])
            for axis, axis_name in s.dynamic_axes():
                if arr.ndim <= axis:
                    raise RuntimeError_(
                        f"input {name!r} needs at least {axis + 1} dims, got shape {arr.shape}"
                    )
                size = arr.shape[axis]
                if axis_name in dyn_sizes and dyn_sizes[axis_name] != size:
                    raise RuntimeError_(
                        f"inconsistent {axis_name!r} dim: {dyn_sizes[axis_name]} vs "
                        f"{size} ({name!r})"
                    )
                dyn_sizes[axis_name] = size
        if not dyn_sizes:
            return {}, {k: np.asarray(v) for k, v in inputs.items()}
        caps = axis_caps or {}
        for axis_name, size in dyn_sizes.items():
            cap = caps.get(axis_name)
            if cap is not None and size > cap:
                raise RuntimeError_(
                    f"{axis_name!r} dim {size} exceeds this model's maximum {cap}"
                )
        buckets = {
            n: min(next_bucket(v), caps[n]) if n in caps else next_bucket(v)
            for n, v in dyn_sizes.items()
        }
        padded: dict[str, np.ndarray] = {}
        for name, s in spec.items():
            arr = np.asarray(inputs[name], dtype=s.np_dtype())
            pad = [(0, 0)] * arr.ndim
            changed = False
            for axis, axis_name in s.dynamic_axes():
                if buckets[axis_name] != arr.shape[axis]:
                    pad[axis] = (0, buckets[axis_name] - arr.shape[axis])
                    changed = True
            padded[name] = np.pad(arr, pad) if changed else arr
        return dyn_sizes, padded

    @_mesh_serialized
    def generate(
        self,
        model_id: ModelId,
        input_ids: np.ndarray,
        prompt_lengths: list[int] | None = None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        draft_model_id: ModelId | None = None,
        spec_tokens: int = 4,
        prefix_rows: int | None = None,
        spec_admitted: bool | None = None,
    ) -> np.ndarray:
        """KV-cached autoregressive decoding (models/generation.py).

        ``prefix_rows`` forces the prefix-cache decision (None = decide
        locally): a cross-host group's leader decides once and ships the
        decision in the work envelope so every process provably runs the
        same program (0 = full prefill, N = reuse exactly N cached rows; a
        follower that cannot honor N raises before any device op).

        Prompt seq, max_new_tokens AND the batch axis are padded to
        power-of-two buckets so one compiled generate program serves the
        whole bucket; output is truncated back to the requested rows/tokens.
        temperature/top_k are traced into the program (not static), so novel
        sampling configs never trigger a recompile. (B, max_new_tokens) int32.

        ``draft_model_id`` switches to greedy speculative decoding
        (models/speculative.py): the draft proposes ``spec_tokens`` tokens
        per round, this model verifies them in one chunked forward; output
        is bit-identical to its own greedy decode. Requires temperature 0
        and a loaded draft sharing the vocabulary.

        ``spec_admitted=True`` marks the draft-acceptance gate as already
        decided upstream (the group leader admits once in its envelope
        builder; re-admitting here would double-count the reprobe cadence).
        """
        import math as _math

        import jax

        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        if loaded.model_def.family not in ("transformer_lm", "moe_lm"):
            raise RuntimeError_(
                f"generate is supported for transformer_lm/moe_lm models, "
                f"not {loaded.model_def.family!r}"
            )
        draft = None
        if draft_model_id is not None:
            if temperature > 0.0:
                raise RuntimeError_(
                    "speculative decoding (draft_model) requires temperature 0 "
                    "— sampled acceptance is not implemented"
                )
            # spec_tokens is a jit STATIC arg fed from the request body: the
            # same compile-DoS vector _sample's docstring hardens temperature/
            # top_k against. Clamp to [1, 8] and round up to a power of two
            # so the whole space mints at most 4 programs (1, 2, 4, 8).
            if spec_tokens < 1:
                raise RuntimeError_(
                    f"spec_tokens must be >= 1, got {spec_tokens}"
                )
            spec_tokens = min(next_bucket(min(spec_tokens, 8)), 8)
            draft = self._resident.get(draft_model_id)
            if draft is None:
                raise ModelNotLoadedError(
                    f"draft model {draft_model_id} is not loaded"
                )
        from tfservingcache_tpu.models.generation import generate as gen

        ids = np.asarray(input_ids, np.int32)
        if ids.ndim != 2 or not ids.size:
            raise RuntimeError_(f"input_ids must be (batch, seq), got {ids.shape}")
        b, s = ids.shape
        if prompt_lengths is None:
            lengths = np.full((b,), s, np.int32)
        else:
            lengths = np.asarray(prompt_lengths, np.int32)
            if lengths.shape != (b,) or (lengths < 1).any() or (lengths > s).any():
                raise RuntimeError_(f"bad prompt_lengths {lengths!r} for shape {ids.shape}")
        if max_new_tokens < 1:
            raise RuntimeError_("max_new_tokens must be >= 1")
        if not _math.isfinite(temperature) or temperature < 0.0:
            raise RuntimeError_(f"temperature must be a finite value >= 0, got {temperature}")
        if top_k < 0:
            raise RuntimeError_(f"top_k must be >= 0, got {top_k}")
        max_seq = loaded.model_def.config["max_seq"]
        s_bucket = next_bucket(s)
        new_bucket = next_bucket(max_new_tokens)
        if s_bucket + new_bucket > max_seq:
            # bucket overshoot may exceed max_seq even when the true request
            # fits; fall back to exact sizes before rejecting
            s_bucket, new_bucket = s, max_new_tokens
            if s + max_new_tokens > max_seq:
                raise RuntimeError_(
                    f"prompt {s} + max_new_tokens {max_new_tokens} exceeds "
                    f"max_seq {max_seq}"
                )
        if s_bucket != s:
            ids = np.pad(ids, ((0, 0), (0, s_bucket - s)))
        # batch axis buckets too: a client-chosen batch size must not mint a
        # fresh compile per novel B (padding rows decode junk that's sliced
        # off below; prompt_length 1 keeps their mask valid)
        b_bucket = next_bucket(b)
        if b_bucket != b:
            ids = np.pad(ids, ((0, b_bucket - b), (0, 0)))
            lengths = np.pad(lengths, (0, b_bucket - b), constant_values=1)
        with TRACER.span(
            "generate", model=str(model_id), tokens=new_bucket, batch=b,
            draft=str(draft_model_id) if draft_model_id else "",
        ):
            if (
                draft is not None
                and spec_admitted is None
                and not self._spec_admit(model_id, draft_model_id)
            ):
                # sustained low acceptance: the draft is pure overhead, fall
                # back to plain greedy decode (identical output) until the
                # pair re-auditions
                TRACER.annotate(spec_gated=True)
                draft = None
            prefix_capable = (
                self._prefix_cache is not None and ids.shape[0] == 1
            )
            if prefix_rows is not None:
                if prefix_rows < 0:
                    # the leader runs the cache-LESS plain path (no
                    # return_cache, no insert): this process must run
                    # the identical program even if it has a cache
                    prefix_capable = False
                elif not prefix_capable:
                    # a forced prefix-machinery decision (miss included:
                    # its gen runs with return_cache, a different
                    # program than plain) this process cannot attempt
                    # must fail LOUDLY before any device op
                    raise RuntimeError_(
                        f"prefix-cache divergence for {model_id}: leader "
                        f"decided {prefix_rows} cached rows but this "
                        "process cannot run the prefix path "
                        "(prefix_cache_bytes mismatch across the group?)"
                    )
            if draft is not None:
                toks, rounds = self._speculative(
                    loaded, draft, model_id, ids, lengths, new_bucket,
                    max_new_tokens, spec_tokens,
                    forced_rows=prefix_rows if prefix_capable else None,
                    prefix_capable=prefix_capable,
                )
                self._spec_observe(
                    model_id, draft_model_id, new_bucket, rounds
                )
            else:
                toks = None
                if prefix_capable:
                    toks = self._prefix_generate(
                        loaded, model_id, ids, int(lengths[0]), new_bucket,
                        max_new_tokens, temperature, top_k, seed,
                        forced_rows=prefix_rows,
                    )
                if toks is None:
                    toks = gen(
                        loaded.model_def,
                        loaded.params,
                        ids,
                        prompt_lengths=lengths,
                        max_new_tokens=new_bucket,
                        temperature=temperature,
                        top_k=top_k,
                        rng=jax.random.PRNGKey(seed),
                    )
            if self._mp_mesh and not isinstance(toks, np.ndarray):
                # force the token array fully replicated so this process can
                # read it (inferred output sharding may split it across
                # hosts); all group processes execute this identity in
                # lockstep. The prefix path already returns host tokens.
                toks = self._replicated(toks)
            toks = np.asarray(jax.device_get(toks))
        return toks[:b, :max_new_tokens]

    # -- continuous-decode slot surface (ContinuousGenerateEngine) ----------
    def eos_id_of(self, model_id: ModelId) -> int | None:
        """The model's EOS token id when its config declares one (an
        optional ``eos_id`` key — toy artifacts and tests set it; absent
        means no early stopping). None when unset or the model is not
        resident."""
        loaded = self._resident.get(model_id, touch=False)
        if loaded is None:
            return None
        eos = loaded.model_def.config.get("eos_id")
        return None if eos is None else int(eos)

    def max_seq_of(self, model_id: ModelId) -> int | None:
        """The model's max sequence length when its config declares one.
        None when unset (non-LM families) or the model is not resident —
        callers treat None as "cannot pre-validate", not as unlimited."""
        loaded = self._resident.get(model_id, touch=False)
        if loaded is None:
            return None
        ms = loaded.model_def.config.get("max_seq")
        return None if ms is None else int(ms)

    @_mesh_serialized
    def slot_decode_state(
        self,
        model_id: ModelId,
        slots: int,
        page_tokens: int | None = None,
        arena_pages: int | None = None,
        share_prefix_bytes: int | None = None,
        arena_dtype: str | None = None,
        paged_kernel: bool | None = None,
    ) -> SlotDecodeState:
        """Create-or-get the model's slot state. One compiled decode-chunk
        program serves all ``slots`` lanes. ``page_tokens`` / ``arena_pages``
        default to the runtime's ServingConfig knobs; ``page_tokens == 0``
        keeps the dense (layers, slots, n_kv, max_seq, head_dim) slot array,
        ``> 0`` allocates the paged arena instead (``arena_pages == 0`` auto-
        sizes to slots x ceil(max_seq/page_tokens) — the dense-equivalent
        byte budget; with ``arena_dtype == "int8"`` the page count grows to
        fill the SAME byte budget, which is where the capacity win comes
        from). An existing state always wins; later callers' knobs
        are ignored, same as ``slots``.

        Allocation runs under a per-model once-guard, NOT under
        ``_slot_lock``: the array can be hundreds of MB (seconds of HBM
        traffic) and the map lock is taken by eviction/reset paths. The
        guard closes the first-admission race where two concurrent first
        requests each allocated a full slot array and one was thrown away.
        """
        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        if loaded.model_def.family != "transformer_lm":
            raise RuntimeError_(
                "continuous decode supports transformer_lm only, not "
                f"{loaded.model_def.family!r}"
            )
        with self._slot_lock:
            st = self._slot_states.get(model_id)
            if st is not None:
                return st
            guard = self._slot_init_guards.setdefault(
                model_id, threading.Lock()
            )
        with guard:
            with self._slot_lock:
                st = self._slot_states.get(model_id)
            if st is not None:
                return st  # the racer that held the guard built it
            st = self._build_slot_state(
                loaded, model_id, slots, page_tokens, arena_pages,
                share_prefix_bytes, arena_dtype, paged_kernel,
            )
            with self._slot_lock:
                st = self._slot_states.setdefault(model_id, st)
                self._slot_init_guards.pop(model_id, None)
            return st

    def _build_slot_state(
        self,
        loaded: LoadedModel,
        model_id: ModelId,
        slots: int,
        page_tokens: int | None,
        arena_pages: int | None,
        share_prefix_bytes: int | None = None,
        arena_dtype: str | None = None,
        paged_kernel: bool | None = None,
    ) -> SlotDecodeState:
        from tfservingcache_tpu.models.generation import (
            init_cache,
            init_paged_cache,
        )

        if page_tokens is None:
            page_tokens = int(getattr(self.cfg, "kv_page_tokens", 0))
        if arena_pages is None:
            arena_pages = int(getattr(self.cfg, "kv_arena_pages", 0))
        if share_prefix_bytes is None:
            share_prefix_bytes = int(
                getattr(self.cfg, "kv_share_prefix_bytes", 0)
            )
        if arena_dtype is None:
            arena_dtype = str(getattr(self.cfg, "kv_arena_dtype", "") or "")
        if paged_kernel is None:
            paged_kernel = bool(getattr(self.cfg, "kv_paged_kernel", True))
        # The fused Pallas decode kernel is single-chip-only (it indexes the
        # whole KV-head axis locally); on a mesh the gather+einsum reference
        # serves the sharded arena, pinned bitwise by tests/test_mesh_parity
        if self.mesh is not None:
            paged_kernel = False
        # Sharded arena (ISSUE 20): pages partition over the KV-head axis on
        # a fast-path mesh; a lockstep runtime never builds slot state (the
        # batcher routes it to coalesce), but keep it dense-host-identical
        arena_mesh = None if self.mesh_lockstep else self.mesh
        cfg = loaded.model_def.config
        max_seq = int(cfg["max_seq"])
        common = dict(
            model_id=model_id,
            cfg_key=tuple(sorted((k, v) for k, v in cfg.items())),
            family=loaded.model_def.family,
            slots=slots,
            max_seq=max_seq,
            tok=np.zeros((slots,), np.int32),
            pos=np.zeros((slots,), np.int32),
            active=np.zeros((slots,), bool),
            temps=np.zeros((slots,), np.float32),
            topks=np.zeros((slots,), np.int32),
        )
        if page_tokens and page_tokens > 0:
            page_tokens = int(page_tokens)
            pps = -(-max_seq // page_tokens)
            usable = int(arena_pages) if arena_pages else slots * pps
            if not arena_pages and arena_dtype == "int8":
                # Byte-matched auto-size: int8 pages are smaller (1-byte
                # payload + 4-byte f32 scale per row vs the dense itemsize),
                # so the SAME byte budget holds more pages — that growth IS
                # the int8 capacity win. Explicit kv_arena_pages is honored
                # verbatim (bench arms pass matched budgets themselves).
                import jax.numpy as jnp

                hd = int(cfg["d_model"]) // int(cfg["n_heads"])
                dense_item = jnp.dtype(
                    cfg.get("dtype", "bfloat16")
                ).itemsize
                usable = max(
                    usable, (usable * hd * dense_item) // (hd + 4)
                )
            # +1: page 0 is the trash page, permanently reserved
            cache = init_paged_cache(
                cfg, usable + 1, page_tokens, arena_dtype, mesh=arena_mesh
            )
            scales = None
            if "k_scale" in cache:
                scales = {"k": cache["k_scale"], "v": cache["v_scale"]}
            prefix_index = None
            if share_prefix_bytes and share_prefix_bytes > 0:
                from tfservingcache_tpu.runtime.prefix_cache import (
                    PagePrefixIndex,
                )

                page_nbytes = sum(
                    int(a.nbytes)
                    for a in (cache["k"], cache["v"],
                              *(scales.values() if scales else ()))
                ) // (usable + 1)
                prefix_index = PagePrefixIndex(
                    page_tokens, page_nbytes, int(share_prefix_bytes)
                )
            st = SlotDecodeState(
                k=cache["k"],
                v=cache["v"],
                scales=scales,
                arena_dtype=arena_dtype,
                kernel=bool(paged_kernel),
                page_tokens=page_tokens,
                arena_pages=usable,
                pages_per_slot=pps,
                block_tables=np.zeros((slots, pps), np.int32),
                free_pages=list(range(1, usable + 1)),
                page_refs=np.zeros((usable + 1,), np.int32),
                prefix_index=prefix_index,
                **common,
            )
            self._note_arena_bytes(st)
            return st
        cache = init_cache(cfg, slots, max_seq, mesh=arena_mesh)
        return SlotDecodeState(
            k=cache["k"], v=cache["v"],
            kernel=bool(paged_kernel), **common,
        )

    def _note_arena_bytes(self, state: SlotDecodeState) -> None:
        """Publish ``tpusc_gen_kv_arena_bytes{dtype}`` for a freshly built
        paged arena. Gauge semantics are "bytes currently allocated with
        this dtype label"; drop paths zero the label rather than tracking a
        cross-model sum (one continuous-decode model per runtime in
        practice — the engine keys slot state by model_id)."""
        if self.metrics is None or not state.page_tokens:
            return

        def actual(arr: Any) -> int:
            # Sharded arena (ISSUE 20): the gauge reports bytes actually
            # ALLOCATED on this host's devices — the per-shard sum, not the
            # logical array size (2x wrong on a 2-way KV-head split)
            shards = getattr(arr, "addressable_shards", None)
            if shards:
                return sum(int(s.data.nbytes) for s in shards)
            return int(arr.nbytes)

        label = state.arena_dtype or str(state.k.dtype)
        nbytes = actual(state.k) + actual(state.v)
        if state.scales is not None:
            nbytes += sum(actual(a) for a in state.scales.values())
        self.metrics.gen_kv_arena_bytes.labels(dtype=label).set(nbytes)

    def mesh_topology(self) -> dict | None:
        """Structural stamp for /monitoring/engine and bench artifacts
        (same rule as ``kernel_active``/``platform`` from BENCH_r09): a
        number without its topology is unreadable later. None off-mesh."""
        if self.mesh is None:
            return None
        return {
            "mesh_devices": int(self.mesh.devices.size),
            "mesh_axes": {k: int(v) for k, v in self.mesh.shape.items()},
            "mesh_fast_path": not self.mesh_lockstep,
        }

    def drop_slot_state(self, model_id: ModelId) -> None:
        with self._slot_lock:
            st = self._slot_states.pop(model_id, None)
        if st is not None and st.page_tokens and self.metrics is not None:
            label = st.arena_dtype or str(st.k.dtype)
            self.metrics.gen_kv_arena_bytes.labels(dtype=label).set(0)

    @_mesh_serialized
    def slot_prefill(
        self,
        model_id: ModelId,
        prompt: np.ndarray,          # (P,) true prompt tokens, no padding
        temperature: float,
        top_k: int,
        seed: int,
    ) -> tuple[int, Any, Any, bool]:
        """Admission prefill for one request: run the prompt through a
        (1, P_bucket)-row prefill (reusing a prefix-cache hit's rows when
        one exists — reuse ONLY; the continuous engine never inserts back,
        its completions live in the slot array, not in cache entries) and
        sample the request's first token. -> (first_token, k, v, prefix_hit)
        with k/v ready for ``slot_admit``."""
        tok, pk, pv, hit, _last = self._slot_prefill_impl(
            model_id, prompt, temperature, top_k, seed
        )
        return tok, pk, pv, hit

    def _slot_prefill_impl(  # static-bounded: cfg_key -- one value per resident model (model_def.config)
        self,
        model_id: ModelId,
        prompt: np.ndarray,
        temperature: float,
        top_k: int,
        seed: int,
    ) -> tuple[int, Any, Any, bool, Any]:
        """slot_prefill body, also returning the last-position logits (the
        5th element, a (1, V) f32 device array) — the shared-prefix
        publisher caches them so an exact re-admission can sample its first
        token without re-running the prefill."""
        import jax

        from tfservingcache_tpu.models.generation import (
            _slot_prefill_from_cache_jit,
            _slot_prefill_jit,
        )

        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        cfg = loaded.model_def.config
        cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        max_seq = int(cfg["max_seq"])
        rng = jax.random.PRNGKey(seed)
        temp = np.float32(temperature)
        tk = np.int32(top_k)

        hit = None
        if self._prefix_cache is not None:
            hit = self._prefix_cache.lookup(model_id, prompt)
            if hit is not None:
                s_pad = next_bucket(p - hit.valid_len)
                if hit.k.shape[3] + s_pad > max_seq:
                    hit = None  # padded hit would overflow the slot lane
            if self.metrics is not None:
                (self.metrics.prefix_cache_hits if hit is not None
                 else self.metrics.prefix_cache_misses).inc()
        if hit is not None:
            ids = prompt[None, :]
            suffix, suffix_len = self._prefix_suffix(ids, p, hit)
            tok, pk, pv, last = _slot_prefill_from_cache_jit(
                loaded.params, suffix,
                np.asarray([suffix_len], np.int32),
                hit.k, hit.v, np.asarray([hit.valid_len], np.int32),
                rng, temp, tk, cfg_key=cfg_key,
                family=loaded.model_def.family,
            )
        else:
            s_pad = next_bucket(p)
            if s_pad > max_seq:
                s_pad = p  # bucket overshoot: exact size (same rule as generate)
            ids = np.zeros((1, s_pad), np.int32)
            ids[0, :p] = prompt
            tok, pk, pv, last = _slot_prefill_jit(
                loaded.params, ids, np.asarray([p], np.int32),
                rng, temp, tk, cfg_key=cfg_key,
                family=loaded.model_def.family,
            )
        return int(np.asarray(tok)[0]), pk, pv, hit is not None, last

    # -- chunked prefill over the paged arena (ISSUE 19) ---------------------
    @_mesh_serialized
    def slot_prefill_chunk(  # static-bounded: cfg_key, chunk_size -- cfg_key is one value per resident model (model_def.config); chunk_size is one pow2 value per engine (serving.prefill_chunk_tokens)
        self,
        model_id: ModelId,
        state: SlotDecodeState,
        lane: int,
        tokens: np.ndarray,   # (t,) this chunk's prompt tokens, t <= chunk_size
        start: int,           # absolute position of tokens[0] in the prompt
        chunk_size: int,      # STATIC padded chunk width (engine-clamped pow2)
    ) -> np.ndarray:
        """Write one prefill chunk into ``lane``'s reserved pages and return
        the chunk's last REAL token logits as a (1, V) f32 host array. The
        engine calls this once per scheduler boundary while the lane sits in
        its PREFILLING state; on the final chunk it feeds the returned
        logits to ``sample_first_token`` with the request's own seed. The
        chunk is zero-padded up to ``chunk_size`` so one compiled program
        serves every chunk (pad rows land past the prompt end inside the
        reservation — or in the trash page past it — and are overwritten
        write-before-read by decode)."""
        import jax

        from tfservingcache_tpu.models.generation import (
            _paged_prefill_chunk_jit,
        )

        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        cfg = loaded.model_def.config
        cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        t = tokens.shape[0]
        if not 0 < t <= chunk_size:
            raise ValueError(
                f"prefill chunk of {t} tokens outside (0, {chunk_size}]"
            )
        toks = np.zeros((1, chunk_size), np.int32)
        toks[0, :t] = tokens
        table_row = np.asarray(state.block_tables[lane:lane + 1], np.int32)
        state.k, state.v, scales, last = _paged_prefill_chunk_jit(
            loaded.params, state.k, state.v, state.scales, table_row,
            toks, np.asarray([start], np.int32), np.asarray([t], np.int32),
            cfg_key=cfg_key, family=loaded.model_def.family,
            page_tokens=state.page_tokens, kernel=state.kernel,
        )
        if scales is not None:
            state.scales = scales
        return np.asarray(jax.device_get(last), np.float32)

    def sample_first_token(
        self,
        last: np.ndarray,     # (1, V) f32 last-position logits
        temperature: float,
        top_k: int,
        seed: int,
    ) -> int:
        """Sample a request's first token from prefill-final logits under
        its own seed — the same split-then-sample the prefill jits do, so a
        chunked prefill's first token matches a monolithic prefill of the
        same prompt under the same seed."""
        import jax

        from tfservingcache_tpu.models.generation import _sample_logits_jit

        tok = _sample_logits_jit(
            np.asarray(last, np.float32), jax.random.PRNGKey(seed),
            np.float32(temperature), np.int32(top_k),
        )
        return int(np.asarray(tok)[0])

    # -- shared-prefix KV over the paged arena (ISSUE 9) ---------------------
    def shared_prefix_plan(
        self,
        state: SlotDecodeState,
        prompt: np.ndarray,
    ) -> Any:
        """Longest viable page-aligned shared prefix for ``prompt`` from the
        state's radix index (None when sharing is off or nothing matches).
        Viability trim: the suffix prefill pads to a pow2 bucket, and
        cached_len + bucket must fit the lane — when it doesn't, shed
        mapped pages (each shed moves ``page_tokens`` tokens back into the
        suffix) until it does, mirroring the dense hit's overflow rule."""
        idx = getattr(state, "prefix_index", None)
        if idx is None:
            return None
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        plan = idx.lookup(prompt)
        if plan is None:
            return None
        if plan.kind == "exact":
            return plan
        while plan.n_full > 0 and \
                plan.covered + next_bucket(p - plan.covered) > state.max_seq:
            plan.pages.pop()
            plan.n_full -= 1
        if plan.n_full == 0:
            return None
        return plan

    @_mesh_serialized
    def slot_prefill_shared(  # static-bounded: cfg_key -- one value per resident model (model_def.config)
        self,
        model_id: ModelId,
        state: SlotDecodeState,
        prompt: np.ndarray,
        temperature: float,
        top_k: int,
        seed: int,
        plan: Any,
    ) -> tuple[int, Any, Any, str, Any]:
        """Admission prefill with shared-prefix reuse ->
        (first_token, pk, pv, kind, last_logits).

        ``plan.kind == "exact"``: zero prefill compute — the first token is
        sampled from the publisher's cached last-position logits under THIS
        request's seed (the same split-then-sample the prefill jits do, so
        it is byte-identical to a cold prefill of the same prompt);
        pk/pv are None and the caller skips slot_admit. ``"shared"``: gather
        the mapped full pages to dense rows and prefill only the suffix
        (kind stays "shared"). ``plan is None``: full/dense-cache path via
        _slot_prefill_impl; kind is "dense" on a legacy dense-cache hit,
        "miss" otherwise."""
        import jax

        from tfservingcache_tpu.models.generation import (
            _paged_gather_prefix_jit,
            _sample_logits_jit,
            _slot_prefill_from_cache_jit,
        )

        if plan is None:
            tok, pk, pv, hit, last = self._slot_prefill_impl(
                model_id, prompt, temperature, top_k, seed
            )
            return tok, pk, pv, ("dense" if hit else "miss"), last
        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        rng = jax.random.PRNGKey(seed)
        temp = np.float32(temperature)
        tk = np.int32(top_k)
        if plan.kind == "exact":
            tok = _sample_logits_jit(
                np.asarray(plan.logits, np.float32), rng, temp, tk
            )
            return int(np.asarray(tok)[0]), None, None, "exact", plan.logits
        cfg = loaded.model_def.config
        cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
        covered = plan.covered
        ck, cv = _paged_gather_prefix_jit(
            state.k, state.v, state.scales, np.asarray(plan.pages, np.int32)
        )
        suffix_len = p - covered
        s_pad = next_bucket(suffix_len)
        suffix = np.zeros((1, s_pad), np.int32)
        suffix[0, :suffix_len] = prompt[covered:]
        tok, pk, pv, last = _slot_prefill_from_cache_jit(
            loaded.params, suffix,
            np.asarray([suffix_len], np.int32),
            ck, cv, np.asarray([covered], np.int32),
            rng, temp, tk, cfg_key=cfg_key,
            family=loaded.model_def.family,
        )
        return int(np.asarray(tok)[0]), pk, pv, "shared", last

    @_mesh_serialized
    def slot_cow(self, state: SlotDecodeState, lane: int, slot: int) -> None:
        """Copy-on-write: give ``lane`` a private copy of the page behind
        its block-table ``slot`` before its first write lands there. The
        page copy + host table swap are data (one compiled program total),
        never a new decode-chunk signature. Raises when no free page exists
        — the admission protocol reserves cow_headroom precisely so this
        cannot happen."""
        from tfservingcache_tpu.models.generation import _page_copy_jit

        swap = state.cow_page(lane, slot)
        if swap is None:
            raise RuntimeError_(
                f"CoW for lane {lane} slot {slot}: free-list empty "
                "(cow_headroom was not reserved?)"
            )
        src, dst = swap
        state.k, state.v, state.scales = _page_copy_jit(
            state.k, state.v, state.scales, np.int32(src), np.int32(dst)
        )

    def shared_prefix_publish(
        self,
        state: SlotDecodeState,
        lane: int,
        prompt: np.ndarray,
        last_logits: Any,
    ) -> None:
        """After admitting ``lane``, publish its prompt's pages into the
        radix index so later same-prefix admissions can share them. Full
        page chunks are indexed IN PLACE (the index just increfs the lane's
        own pages — the lane only ever writes past the prompt). A partially
        filled boundary page is EAGER-COPIED into a fresh free page for the
        index (the lane keeps decoding into its original), so the indexed
        copy stays pristine — tail tokens + zeros — and neither side ever
        needs CoW against the other. Skipped silently when nothing
        page-aligned is shareable or no free page exists for the copy."""
        idx = getattr(state, "prefix_index", None)
        if idx is None:
            return
        from tfservingcache_tpu.models.generation import _page_copy_jit

        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        pt = state.page_tokens
        n_full = p // pt
        tail_len = p - n_full * pt
        lane_pg = state.lane_pages.get(lane)
        if lane_pg is None or len(lane_pg) < state.pages_needed(p):
            return
        if last_logits is not None:
            last_logits = np.asarray(last_logits, np.float32)
        boundary = None
        if tail_len and last_logits is not None and state.free_pages:
            src = lane_pg[n_full]
            boundary = state.free_pages.pop()
            state.k, state.v, state.scales = _page_copy_jit(
                state.k, state.v, state.scales,
                np.int32(src), np.int32(boundary)
            )
        added, released = idx.insert(
            prompt, lane_pg[:n_full], boundary, last_logits, state.page_refs
        )
        for pg in added:
            state.page_refs[pg] += 1
        for pg in released:
            n = int(state.page_refs[pg]) - 1
            state.page_refs[pg] = max(n, 0)
            if n <= 0:
                state.free_pages.append(pg)
        if boundary is not None and boundary not in added:
            state.free_pages.append(boundary)  # index declined the tail

    def reclaim_prefix_pages(
        self,
        state: SlotDecodeState,
        want_pages: int,
        protect: list | tuple = (),
    ) -> int:
        """Admission pressure valve: evict cold index-only prefix pages
        (zero lane refs, skipping ``protect`` — the blocked request's own
        plan pages) back onto the free-list so a live admission never loses
        a page fight to cold cache. Returns how many pages were freed."""
        idx = getattr(state, "prefix_index", None)
        if idx is None:
            return 0
        released = idx.reclaim(
            state.page_refs, want_pages, frozenset(int(p) for p in protect)
        )
        freed = 0
        for pg in released:
            n = int(state.page_refs[pg]) - 1
            state.page_refs[pg] = max(n, 0)
            if n <= 0:
                state.free_pages.append(pg)
                freed += 1
        return freed

    # -- conversation KV lifecycle (ISSUE 18) --------------------------------
    @_mesh_serialized
    def park_lane(self, state: SlotDecodeState, lane: int,
                  history: np.ndarray) -> Any:
        """Export a retiring lane's live pages for conversation parking
        (cache/conversation_kv.py): host copies of the pages covering
        ``history`` (the token prefix whose K/V rows are valid in the
        lane), raw arena dtype + int8 scales — NOT dequantized, so the
        parked bytes re-import bit-identical at half the dense footprint.
        Read-only on the arena: the caller still release_pages() the lane
        normally, so the conservation census never sees a parked page as a
        new reference source. None when the lane has nothing parkable
        (dense state, empty history, or a lane whose reservation no longer
        covers it — a crash-recovery race, not an error)."""
        import jax

        from tfservingcache_tpu.cache.conversation_kv import ParkedConversation
        from tfservingcache_tpu.models.generation import _pages_export_jit

        if not state.paged:
            return None
        history = np.asarray(history, np.int32).reshape(-1)
        if history.shape[0] <= 0:
            return None
        n = state.pages_needed(history.shape[0])
        pages = state.lane_pages.get(lane)
        if pages is None or len(pages) < n or n == 0:
            return None
        pg = np.asarray(pages[:n], np.int32)
        k, v, scales = _pages_export_jit(state.k, state.v, state.scales, pg)
        ks = vs = None
        if scales is not None:
            ks = np.asarray(jax.device_get(scales["k"]))
            vs = np.asarray(jax.device_get(scales["v"]))
        return ParkedConversation(
            model_id=str(state.model_id),
            history=history.copy(),
            pages_k=np.asarray(jax.device_get(k)),
            pages_v=np.asarray(jax.device_get(v)),
            k_scale=ks,
            v_scale=vs,
            page_tokens=state.page_tokens,
        )

    def plan_conversation_resume(
        self, state: SlotDecodeState, prompt: np.ndarray, parked: Any,
    ) -> tuple[int, int] | None:
        """Viability check for resuming ``prompt`` from a parked
        conversation: -> (covered, n_pages) — the longest common
        token prefix of the parked history and the new prompt (clamped so
        at least one suffix token remains to prefill), and the parked
        pages that cover it. ``covered`` need NOT be page-aligned:
        the suffix insert's write-before-read discipline overwrites the
        boundary page's stale tail exactly like a dense-cache hit. Sheds
        whole pages when covered + the suffix's pow2 bucket would overflow
        the lane (mirroring shared_prefix_plan's trim). None when nothing
        is resumable — wrong page size / arena layout / dtype, divergent
        first token, or the trim shed everything."""
        if parked is None or not state.paged:
            return None
        if int(parked.page_tokens) != state.page_tokens:
            return None
        shape = tuple(parked.pages_k.shape)
        arena = tuple(state.k.shape)
        if len(shape) != 5 or shape[0] != arena[0] or shape[2:] != arena[2:]:
            return None
        if str(np.dtype(parked.pages_k.dtype)) != str(state.k.dtype):
            return None
        if (state.scales is None) != (parked.k_scale is None):
            return None
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        hist = np.asarray(parked.history, np.int32).reshape(-1)
        m = min(p - 1, hist.shape[0])
        if m <= 0:
            return None
        eq = hist[:m] == prompt[:m]
        covered = m if eq.all() else int(np.argmax(~eq))
        # never resume past the pages actually parked
        covered = min(covered, int(shape[1]) * state.page_tokens)
        while covered > 0 and \
                covered + next_bucket(p - covered) > state.max_seq:
            covered = (state.pages_needed(covered) - 1) * state.page_tokens
        if covered <= 0:
            return None
        return covered, state.pages_needed(covered)

    @_mesh_serialized
    def slot_resume_prefill(  # static-bounded: cfg_key -- one value per resident model (model_def.config)
        self,
        model_id: ModelId,
        state: SlotDecodeState,
        lane: int,
        prompt: np.ndarray,
        parked: Any,
        covered: int,
        n_pages: int,
        temperature: float,
        top_k: int,
        seed: int,
    ) -> tuple[int, Any, Any, Any]:
        """Resume admission prefill: re-import the parked pages into the
        first ``n_pages`` of ``lane``'s freshly reserved PRIVATE pages
        (one batched donated scatter), gather the covered prefix dense,
        and prefill only the suffix -> (first_token, pk, pv, last_logits),
        with pk/pv ready for ``slot_admit(..., base_tokens=covered)``.
        Sampling parity is the exact-hit discipline (PR 9): the same
        split-then-sample as a full prefill under the same seed, over
        byte-identical K/V rows — so greedy AND seeded-sampling streams
        match a full re-prefill of the whole history."""
        import jax

        from tfservingcache_tpu.models.generation import (
            _paged_gather_prefix_jit,
            _pages_import_jit,
            _slot_prefill_from_cache_jit,
        )

        loaded = self._resident.get(model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        cfg = loaded.model_def.config
        cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p = prompt.shape[0]
        pages = np.asarray(state.lane_pages[lane][:n_pages], np.int32)
        pk_pg = np.ascontiguousarray(parked.pages_k[:, :n_pages])
        pv_pg = np.ascontiguousarray(parked.pages_v[:, :n_pages])
        pscales = None
        if state.scales is not None:
            pscales = {
                "k": np.ascontiguousarray(parked.k_scale[:, :n_pages]),
                "v": np.ascontiguousarray(parked.v_scale[:, :n_pages]),
            }
        state.k, state.v, state.scales = _pages_import_jit(
            state.k, state.v, state.scales, pages, pk_pg, pv_pg, pscales
        )
        ck, cv = _paged_gather_prefix_jit(
            state.k, state.v, state.scales, pages
        )
        suffix_len = p - covered
        s_pad = next_bucket(suffix_len)
        suffix = np.zeros((1, s_pad), np.int32)
        suffix[0, :suffix_len] = prompt[covered:]
        rng = jax.random.PRNGKey(seed)
        tok, pk, pv, last = _slot_prefill_from_cache_jit(
            loaded.params, suffix,
            np.asarray([suffix_len], np.int32),
            ck, cv, np.asarray([covered], np.int32),
            rng, np.float32(temperature), np.int32(top_k),
            cfg_key=cfg_key, family=loaded.model_def.family,
        )
        return int(np.asarray(tok)[0]), pk, pv, last

    @_mesh_serialized
    def slot_admit(self, state: SlotDecodeState, idx: int, pk: Any, pv: Any,
                   base_tokens: int = 0) -> None:
        """Copy an admitted request's prefill K/V into slot lane ``idx``
        (in-place via donation). The caller (scheduler thread) owns the host
        mirrors and sets tok/pos/active/temps/topks itself; for a paged
        state it must have reserved the lane's pages (reserve_pages) first —
        the insert scatters through the lane's block-table row.
        ``base_tokens`` is the shared-prefix boundary: prefill rows below it
        belong to read-only shared pages and are redirected to the trash
        page (the suffix prefill only produced junk there anyway)."""
        from tfservingcache_tpu.models.generation import (
            _paged_insert_jit,
            _slot_insert_jit,
        )

        if state.paged:
            state.k, state.v, state.scales = _paged_insert_jit(
                state.k, state.v, state.scales, pk, pv,
                np.asarray(state.block_tables[idx], np.int32),
                np.int32(base_tokens),
                page_tokens=state.page_tokens,
            )
            return
        state.k, state.v = _slot_insert_jit(
            state.k, state.v, pk, pv, np.int32(idx)
        )

    @_mesh_serialized
    def slot_decode_chunk(self, state: SlotDecodeState, chunk: int) -> np.ndarray:  # static-bounded: chunk -- engine clamps to a pow2 cover (batcher: min(chunk_tokens, _next_bucket(...)))
        """Advance every active lane by ``chunk`` decode steps in one
        dispatch; updates the state's device K/V and host tok/pos mirrors
        and returns the (S, chunk) emitted tokens. Raises
        ModelNotLoadedError when the model was evicted mid-decode (the
        engine fails its in-flight requests and drops the state)."""
        import jax

        from tfservingcache_tpu.models.generation import (
            _decode_chunk_jit,
            _paged_decode_chunk_jit,
        )

        loaded = self._resident.get(state.model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {state.model_id} is not loaded")
        state.chunk_counter += 1
        rngs = jax.random.split(
            jax.random.PRNGKey(state.chunk_counter), chunk
        )
        if state.paged:
            if _PAGECHECK:
                _check_trash_unreachable(state)
            (state.k, state.v, state.scales, tok, pos,
             toks) = _paged_decode_chunk_jit(
                loaded.params, state.k, state.v, state.scales,
                np.asarray(state.block_tables, np.int32),
                state.tok, state.pos, state.active, rngs,
                state.temps, state.topks,
                cfg_key=state.cfg_key, family=state.family, chunk=chunk,
                page_tokens=state.page_tokens, kernel=state.kernel,
            )
        else:
            state.k, state.v, tok, pos, toks = _decode_chunk_jit(
                loaded.params, state.k, state.v,
                state.tok, state.pos, state.active, rngs,
                state.temps, state.topks,
                cfg_key=state.cfg_key, family=state.family, chunk=chunk,
            )
        # np.array (not asarray): device_get hands back READ-ONLY views and
        # the scheduler writes these mirrors at the next admission
        state.tok = np.array(jax.device_get(tok), dtype=np.int32)
        state.pos = np.array(jax.device_get(pos), dtype=np.int32)
        return np.asarray(jax.device_get(toks))

    @_mesh_serialized
    def slot_attach_draft(self, state: SlotDecodeState, draft_id: ModelId,
                          spec_tokens: int = 4) -> SlotDecodeState:
        """Attach ``draft_id``'s decode state to ``state`` for in-engine
        speculative rounds (runtime/batcher.py under serving.spec_draft_model):
        builds the draft's own paged arena with the target's slot count and
        page size — auto-sized, quantized and kernel-gated exactly like the
        target's — and pins it on ``state.spec_draft`` so its lifecycle is
        the target state's (dropped together; NOT registered in
        ``_slot_states``). Idempotent for the same draft. The draft must be
        resident, share the target's vocabulary, and be a transformer_lm;
        the target state must be paged (the private-page discipline is what
        makes ragged rollback free). ``spec_tokens`` is clamped to the same
        {1,2,4,8} jit-signature buckets as the solo path."""
        if state.spec_draft is not None and state.spec_draft_id == draft_id:
            return state.spec_draft
        if not state.paged:
            raise RuntimeError_(
                "in-engine speculation requires a paged slot state "
                "(serving.kv_page_tokens > 0)"
            )
        loaded = self._resident.get(state.model_id)
        draft = self._resident.get(draft_id)
        if loaded is None or draft is None:
            missing = state.model_id if loaded is None else draft_id
            raise ModelNotLoadedError(f"model {missing} is not loaded")
        if draft.model_def.family != "transformer_lm":
            raise RuntimeError_(
                "continuous speculation supports transformer_lm drafts "
                f"only, not {draft.model_def.family!r}"
            )
        if (draft.model_def.config["vocab_size"]
                != loaded.model_def.config["vocab_size"]):
            raise RuntimeError_(
                "draft and target must share a vocabulary: "
                f"{draft.model_def.config['vocab_size']} vs "
                f"{loaded.model_def.config['vocab_size']}"
            )
        if spec_tokens < 1:
            raise RuntimeError_(
                f"spec_tokens must be >= 1, got {spec_tokens}"
            )
        d_st = self._build_slot_state(
            draft, draft_id, state.slots, state.page_tokens, 0, 0,
            state.arena_dtype, state.kernel,
        )
        # the build re-pointed the arena-bytes gauge at the draft; restore
        # the target's value — the gauge documents the SERVING arena (the
        # draft arena is spec overhead, visible via spec_* metrics instead)
        self._note_arena_bytes(state)
        # host mirrors alias the target's: both caches always sit at the
        # same accepted positions, so one array serves both censuses
        d_st.tok = state.tok
        d_st.pos = state.pos
        d_st.active = state.active
        state.spec_draft_id = draft_id
        state.spec_draft = d_st
        state.spec_tokens = min(next_bucket(min(int(spec_tokens), 8)), 8)
        return d_st

    @_mesh_serialized
    def slot_decode_spec_round(
        self, state: SlotDecodeState
    ) -> tuple[np.ndarray, np.ndarray]:
        """One speculative draft/verify round for every active lane —
        the spec counterpart of ``slot_decode_chunk``. Requires an attached
        draft (``slot_attach_draft``). Returns (toks (S, spec+1), accept
        (S,)): lane ``s`` emitted ``toks[s, :accept[s]]`` this round
        (accept == 0 for frozen lanes). Raises ModelNotLoadedError naming
        whichever half of the pair was evicted mid-decode — the engine
        detaches the draft and falls back to plain chunks on the draft,
        fails its rows on the target, exactly like ``slot_decode_chunk``."""
        import jax

        from tfservingcache_tpu.models.speculative import (
            _paged_spec_round_jit,
        )

        d_st = state.spec_draft
        if d_st is None:
            raise RuntimeError_("no draft attached (slot_attach_draft)")
        loaded = self._resident.get(state.model_id)
        if loaded is None:
            raise ModelNotLoadedError(f"model {state.model_id} is not loaded")
        d_loaded = self._resident.get(d_st.model_id)
        if d_loaded is None:
            raise ModelNotLoadedError(
                f"draft model {d_st.model_id} is not loaded"
            )
        # the draft mirrors may have been rebound by admission writes on
        # the target's arrays; re-alias before the census checks
        d_st.tok, d_st.pos, d_st.active = state.tok, state.pos, state.active
        state.chunk_counter += 1
        rng = jax.random.PRNGKey(state.chunk_counter)
        if _PAGECHECK:
            _check_trash_unreachable(state)
            _check_trash_unreachable(d_st)
        (state.k, state.v, state.scales,
         d_st.k, d_st.v, d_st.scales,
         tok, pos, toks, accept) = _paged_spec_round_jit(
            loaded.params, d_loaded.params,
            state.k, state.v, state.scales,
            d_st.k, d_st.v, d_st.scales,
            np.asarray(state.block_tables, np.int32),
            np.asarray(d_st.block_tables, np.int32),
            state.tok, state.pos, state.active,
            rng, state.temps, state.topks,
            cfg_t_key=state.cfg_key, cfg_d_key=d_st.cfg_key,
            family_t=state.family, family_d=d_st.family,
            spec=state.spec_tokens, page_tokens=state.page_tokens,
            kernel=state.kernel,
        )
        # np.array (not asarray): device_get hands back READ-ONLY views and
        # the scheduler writes these mirrors at the next admission
        state.tok = np.array(jax.device_get(tok), dtype=np.int32)
        state.pos = np.array(jax.device_get(pos), dtype=np.int32)
        d_st.tok, d_st.pos = state.tok, state.pos
        return (np.asarray(jax.device_get(toks)),
                np.array(jax.device_get(accept), dtype=np.int32))

    # -- unload / introspection --------------------------------------------
    def _on_evict(self, model_id: ModelId, entry: LRUEntry[LoadedModel]) -> None:
        self._set_state(model_id, ModelState.UNLOADING)
        if self._prefix_cache is not None:
            # an unloaded model's prefix KV must not outlive it in HBM
            self._prefix_cache.drop_model(model_id)
        # likewise the continuous engine's slot K/V (the engine's next
        # dispatch sees ModelNotLoadedError and fails its in-flight rows)
        self.drop_slot_state(model_id)
        with self._spec_lock:
            # acceptance history dies with either half of the pair (a
            # re-loaded model or new draft version starts fresh)
            for pair in [p for p in self._spec_health if model_id in p]:
                del self._spec_health[pair]
        # Demotion (HBM -> host tier). The eager retain at load time makes
        # the common case a pure O(1) LRU touch; only a model whose packed
        # entry was skipped (capacity) or tier-evicted while resident needs
        # re-creating from the device copy, and THAT work — device_get +
        # chunk repack, potentially seconds for a big model — is handed to
        # the demote worker. The evicting thread (often a loader that
        # triggered this eviction while holding its own load lock, or a
        # caller inside the slot-map critical section) never pays it, so a
        # slow demotion cannot block concurrent hits on other models. The
        # queue item holds the LoadedModel, keeping the device arrays alive
        # until the worker has copied them out.
        if self._host_tier is not None and not self._host_tier.touch(model_id):
            self._demote_queue.put(("demote", model_id, entry.payload))
        # Only the LRU's reference is dropped; in-flight predicts holding the
        # LoadedModel keep the device arrays alive until they finish, then XLA
        # frees the HBM when the last reference goes. (Nulling the fields here
        # would crash those in-flight calls.)
        key = entry.payload.model_def.cache_key
        with self._jit_lock:
            shared = self._jitted_by_key.get(key)
            if shared is not None:
                jitted, refs = shared
                if refs <= 1:
                    del self._jitted_by_key[key]  # last tenant gone: free the executable
                    self._drop_aot_family(key)
                else:
                    self._jitted_by_key[key] = (jitted, refs - 1)
        self._set_state(model_id, ModelState.END)
        # prune the per-model load lock so a 1000-tenant churn doesn't grow
        # the dict forever; a racer holding the popped lock only risks one
        # redundant (idempotent) load, never corruption
        with self._load_locks_guard:
            lock = self._load_locks.get(model_id)
            if lock is not None and not lock.locked():
                del self._load_locks[model_id]
        if self.metrics is not None:
            self.metrics.evictions.labels("hbm").inc()
        self._update_gauges()
        log.info("unloaded %s (freed %d HBM bytes)", model_id, entry.size_bytes)

    def unload(self, model_id: ModelId) -> None:
        self._resident.remove(model_id, run_callback=True)
        # _on_evict prunes _spec_health only when the model was RESIDENT;
        # an unload of a non-resident id (already evicted, or gate state
        # resurrected by a generate that finished after eviction) must
        # still drop the pair entries, or tenant churn grows the dict
        # forever (ISSUE 16 satellite — both roles of the pair)
        with self._spec_lock:
            for pair in [p for p in self._spec_health if model_id in p]:
                del self._spec_health[pair]

    def is_loaded(self, model_id: ModelId) -> bool:
        return self._resident.get(model_id, touch=False) is not None

    # -- host-RAM warm tier -------------------------------------------------
    @property
    def host_tier_enabled(self) -> bool:
        return self._host_tier is not None

    def host_tier_contains(self, model_id: ModelId) -> bool:
        """Advisory residency probe (router warmth / manager accounting)."""
        return self._host_tier is not None and model_id in self._host_tier

    def unload_and_discard(self, model_id: ModelId) -> None:
        """Disk-evict hook (CacheManager): drop HBM residency AND the
        host-tier entry. Tiers are inclusive downward — a host entry must
        imply its artifact is still on disk, or a promoted model could
        serve weights the store has already dropped and a later STALE check
        would have nothing to reconcile against. The trailing queue item
        runs AFTER any demotion the unload itself enqueued (single FIFO
        worker), so the discard wins regardless of interleaving."""
        self.unload(model_id)
        if self._host_tier is not None:
            self._host_tier.remove(model_id)
            self._demote_queue.put(("discard", model_id, None))

    def drain_demotions(self) -> None:
        """Block until every queued demotion/discard has run (tests/bench:
        makes tier contents deterministic before asserting on them)."""
        if self._demote_queue is not None:
            self._demote_queue.join()

    def _retain_packed(
        self,
        mid: ModelId,
        model_def: ModelDef,
        host_params: Any,
        jitted: Any,
        hbm_bytes: int,
        captured: list | None = None,
    ) -> None:
        """Insert/update ``mid``'s packed entry in the host tier. Advisory:
        never fails the surrounding load/demotion — worst case the model
        just reloads through the full path next time."""
        if self._host_tier is None:
            return
        try:
            entry = build_packed_entry(
                model_def, host_params, jitted, hbm_bytes, captured=captured
            )
            # snapshot the family's AOT executables: if the family dies in
            # HBM before this model promotes, rebinding these recovers the
            # warmup-shaped fast path without a recompile
            with self._aot_lock:
                entry.aot_entries = {
                    k: v
                    for k, v in self._aot_cache.items()
                    if k[0] == model_def.cache_key
                }
            self._host_tier.put(mid, entry)
        except Exception as e:  # noqa: BLE001 - advisory by design
            log.warning("host-tier retain of %s skipped: %s", mid, e)

    def _demote_loop(self) -> None:
        """Demote worker: the only thread that pays device_get + repack for
        models evicted without a retained entry, and the serialization
        point that orders discards after demotions."""
        while True:
            item = self._demote_queue.get()
            try:
                if item is None:
                    return
                kind, mid, payload = item
                if kind == "demote":
                    self._demote_impl(mid, payload)
                elif not self.is_loaded(mid):  # "discard"
                    self._host_tier.remove(mid)
            except Exception:  # noqa: BLE001 - worker must survive any job
                log.exception("host-tier demotion failed")
            finally:
                self._demote_queue.task_done()

    def _demote_impl(self, mid: ModelId, loaded: LoadedModel) -> None:
        import jax

        if self._host_tier is None or mid in self._host_tier:
            return
        if self.is_loaded(mid):
            # re-admitted while queued: its (re)load re-retained, and the
            # queued LoadedModel may be a stale generation — skip
            return
        host_params = jax.device_get(loaded.params)
        self._retain_packed(
            mid, loaded.model_def, host_params, loaded.jitted, loaded.hbm_bytes
        )

    def _replicated(self, t):  # jit-surface: one-time lazy replicate-out identity, memoized on self
        """Jitted identity with fully-replicated out_sharding (cached — a
        fresh lambda per call would retrace and recompile per request); all
        group processes execute it in lockstep."""
        import jax

        if self._replicate_out is None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._replicate_out = jax.jit(
                lambda x: x,
                out_shardings=NamedSharding(self.mesh, PartitionSpec()),
            )
        return self._replicate_out(t)

    def _spec_admit(self, target: ModelId, draft: ModelId) -> bool:
        """Should this request run its draft? False once sustained low
        acceptance disabled the pair; every SPEC_REPROBE_EVERY-th gated
        request re-auditions the draft so a workload shift can re-enable it.
        On a cross-host group only the LEADER holds an active gate — its
        decision rides the work envelope (draft dropped when gated), so
        every process still executes the same program."""
        if not self._spec_gate_active:
            return True
        with self._spec_lock:
            st = self._spec_health.get((target, draft))
            if st is None or not st["disabled"]:
                return True
            st["skipped"] += 1
            return st["skipped"] % SPEC_REPROBE_EVERY == 0

    def _spec_observe(self, target: ModelId, draft: ModelId, emitted: int,
                      rounds: int, engine: str = "solo") -> None:
        """Record one speculative generate's acceptance; flip the pair's
        disabled flag on a sustained low streak (VERDICT r5 #6 — the health
        signal existed since round 4 but nothing acted on it). ``engine``
        labels the cumulative counters (solo generate vs continuous spec
        rounds — the acceptance-rate trend per serving path)."""
        tpr = emitted / max(1, rounds)
        if self.metrics is not None:
            label = self.metrics.model_label(target.name, target.version)
            self.metrics.spec_tokens_per_round.labels(model=label).set(
                round(tpr, 3)
            )
            self.metrics.spec_accepted_tokens.labels(engine=engine).inc(
                int(emitted)
            )
            self.metrics.spec_rounds.labels(engine=engine).inc(int(rounds))
        if not self._spec_gate_active:
            return
        if not (self.is_loaded(target) and self.is_loaded(draft)):
            # either half unloaded mid-generate: recording would resurrect
            # the pair entry unload() just pruned (the setdefault below),
            # re-leaking gate state for a dead pair
            return
        with self._spec_lock:
            st = self._spec_health.setdefault(
                (target, draft),
                {"low_streak": 0, "disabled": False, "skipped": 0},
            )
            if tpr >= SPEC_MIN_TOKENS_PER_ROUND:
                if st["disabled"]:
                    log.info(
                        "draft %s re-enabled for %s (%.2f tokens/round)",
                        draft, target, tpr,
                    )
                st.update(low_streak=0, disabled=False, skipped=0)
                return
            st["low_streak"] += 1
            if not st["disabled"] and st["low_streak"] >= SPEC_DISABLE_AFTER:
                st["disabled"] = True
                st["skipped"] = 0
                log.warning(
                    "draft %s auto-disabled for %s: %d consecutive generates "
                    "below %.1f tokens/round (last %.2f) — speculative rounds "
                    "were doing more target work per token than plain decode; "
                    "falling back (re-audition every %d requests)",
                    draft, target, SPEC_DISABLE_AFTER,
                    SPEC_MIN_TOKENS_PER_ROUND, tpr, SPEC_REPROBE_EVERY,
                )
                if self.metrics is not None:
                    self.metrics.spec_draft_autodisabled.inc()

    def _prefix_generate(self, loaded, model_id, ids, prompt_len: int,
                         new_bucket: int, max_new: int, temperature: float,
                         top_k: int, seed: int,
                         forced_rows: int | None = None):
        """B=1 generate through the prefix KV cache: reuse the longest
        cached token-prefix's K/V rows, prefill only the suffix, and store
        the (prompt + completion) rows for the next turn. Output matches the
        plain path in exact arithmetic — same math at the same positions,
        shared decode-scan rng split structure — but the hit path's
        suffix-only prefill is a different matmul shape, so near-tied
        argmax/sampling under accelerator float reassociation can differ
        between hit and miss (same caveat as models/speculative.py); don't
        rely on seed-reproducibility across cache state.

        ``forced_rows`` (group mode): the leader's decision from the work
        envelope. Every process must run the SAME program, so a forced hit
        this cache cannot honor raises — BEFORE any device op — instead of
        silently prefilling a different shape into the group's collective."""
        import jax

        from tfservingcache_tpu.models.generation import (
            generate as gen,
            generate_from_cache,
        )

        prompt = ids[0, :prompt_len]
        rng = jax.random.PRNGKey(seed)
        hit = self._prefix_resolve(model_id, prompt, forced_rows)
        if hit is None:
            toks_d, k_full, v_full = gen(
                loaded.model_def, loaded.params, ids,
                prompt_lengths=np.array([prompt_len], np.int32),
                max_new_tokens=new_bucket, temperature=temperature,
                top_k=top_k, rng=rng, return_cache=True,
            )
        else:
            suffix, suffix_len = self._prefix_suffix(ids, prompt_len, hit)
            toks_d, k_full, v_full = generate_from_cache(
                loaded.model_def, loaded.params, suffix, suffix_len,
                hit.k, hit.v, hit.valid_len, max_new_tokens=new_bucket,
                temperature=temperature, top_k=top_k, rng=rng,
                return_cache=True,
            )
        return self._prefix_store(
            model_id, prompt, prompt_len, max_new, toks_d, k_full, v_full, hit
        )

    def _prefix_resolve(self, model_id, prompt, forced_rows: int | None):
        """Hit decision for the prefix paths (plain + speculative): local
        lookup, or the group leader's forced decision — which this process
        must honor exactly or fail loudly before any device op."""
        pc = self._prefix_cache
        if forced_rows == 0:
            pc.note_forced_miss()
            return None
        hit = pc.lookup(model_id, prompt)
        if forced_rows is not None and forced_rows > 0:
            if hit is None or hit.valid_len < forced_rows:
                raise RuntimeError_(
                    f"prefix-cache divergence for {model_id}: leader decided "
                    f"{forced_rows} cached rows, this process has "
                    f"{0 if hit is None else hit.valid_len} — group states "
                    "are out of lockstep (re-form required)"
                )
            if hit.valid_len > forced_rows:
                from tfservingcache_tpu.runtime.prefix_cache import PrefixEntry

                hit = PrefixEntry(hit.tokens[:forced_rows], hit.k, hit.v,
                                  forced_rows, hit.nbytes)
        return hit

    @staticmethod
    def _prefix_suffix(ids, prompt_len: int, hit):
        """(padded suffix ids, true suffix length) after ``hit``'s rows."""
        l_use = hit.valid_len
        suffix = ids[:1, l_use:prompt_len]
        suffix_len = prompt_len - l_use
        s_pad = next_bucket(suffix_len)
        if s_pad != suffix.shape[1]:
            suffix = np.pad(suffix, ((0, 0), (0, s_pad - suffix.shape[1])))
        return suffix, suffix_len

    def _prefix_store(self, model_id, prompt, prompt_len: int, max_new: int,
                      toks_d, k_full, v_full, hit):
        """Read back tokens, insert the (prompt + completion) rows for the
        next turn, record stats. Rows are valid through prompt_len +
        new_bucket (plain: the scan forwards the carry before sampling;
        speculative: the final-carry writeback) — but the entry must stop at
        the TRUE max_new: the bucket-padding generations were never returned
        to the client, so the next turn's prompt diverges exactly there and
        an entry containing them would never match again (review repro:
        max_new=5 bucketed to 8 made every conversation a permanent miss)."""
        import jax

        pc = self._prefix_cache
        if self._mp_mesh:
            # sharded result: force replication so THIS process can read the
            # tokens (same jitted identity the plain path uses); K/V stay
            # sharded — each process caches its own shards
            toks_d = self._replicated(toks_d)
        toks = np.asarray(jax.device_get(toks_d))
        valid = prompt_len + max_new
        entry_tokens = np.concatenate([prompt, toks[0, :max_new]])
        # store at the power-of-two FLOOR of the valid rows: only pow2 row
        # blocks may be cached (an odd width would mint a novel jit trace
        # shape on every later hit), the floor always fits the cache array,
        # and the tail it drops is at most half — the next turn still
        # reuses the bulk of the history
        l_store = 1 << (valid.bit_length() - 1)
        if l_store >= 16:
            pc.insert(
                model_id, entry_tokens[:l_store],
                k_full[:, :, :, :l_store, :], v_full[:, :, :, :l_store, :],
                l_store,
            )
        TRACER.annotate(prefix_hit=hit is not None,
                        prefix_rows=0 if hit is None else hit.valid_len)
        if self.metrics is not None:
            (self.metrics.prefix_cache_hits if hit is not None
             else self.metrics.prefix_cache_misses).inc()
            self.metrics.prefix_cache_bytes.set(pc.total_bytes)
        return toks

    def _speculative(self, loaded, draft, model_id, ids, lengths, new_bucket,
                     max_new: int, spec_tokens: int,
                     forced_rows: int | None, prefix_capable: bool):
        """Speculative decoding, prefix-cache aware (VERDICT r5 composition):
        when the cache is on and B=1, the TARGET's prefill starts from the
        cached prompt-prefix rows and the completion is inserted back — a
        draft-assisted conversation pays target prefill only for its new
        tokens from turn 2. Returns (tokens — host array on the prefix
        path, device array otherwise — and the verify-round count)."""
        from tfservingcache_tpu.models.speculative import speculative_generate

        if not prefix_capable:
            # device tokens returned as-is: generate()'s shared tail handles
            # the group replication + device_get exactly once
            toks, rounds = speculative_generate(
                loaded.model_def, loaded.params, draft.model_def,
                draft.params, ids, prompt_lengths=lengths,
                max_new_tokens=new_bucket, spec_tokens=spec_tokens,
                return_rounds=True,
            )
            return toks, int(rounds)

        prompt_len = int(lengths[0])
        prompt = ids[0, :prompt_len]
        hit = self._prefix_resolve(model_id, prompt, forced_rows)
        cached_kv = None
        if hit is not None:
            suffix, suffix_len = self._prefix_suffix(ids, prompt_len, hit)
            cached_kv = (suffix, suffix_len, hit.k, hit.v, hit.valid_len)
        toks_d, rounds, k_full, v_full = speculative_generate(
            loaded.model_def, loaded.params, draft.model_def, draft.params,
            ids, prompt_lengths=np.array([prompt_len], np.int32),
            max_new_tokens=new_bucket, spec_tokens=spec_tokens,
            return_rounds=True, return_cache=True, cached_kv=cached_kv,
        )
        toks = self._prefix_store(
            model_id, prompt, prompt_len, max_new, toks_d, k_full, v_full, hit
        )
        return toks, int(rounds)

    def resident_headroom(self) -> tuple[int | None, int]:
        """(free resident model slots or None if uncapped, free HBM bytes).
        Advisory snapshot for the assignment warmer: warming past this would
        evict actively-serving models (ADVICE r3: a post-remap sweep must
        help live traffic, not churn it)."""
        free_slots = (
            None if self._resident.max_items is None
            else max(0, self._resident.max_items - len(self._resident))
        )
        return free_slots, max(
            0, self.cfg.hbm_capacity_bytes - self._resident.total_bytes
        )

    def family_of(self, model_id: ModelId) -> str | None:
        """Family of a resident model (None when not loaded) — the generate
        coalescer keys on this: capacity-routed families (moe_lm) must not
        co-batch, their expert routing depends on batch composition."""
        loaded = self._resident.get(model_id, touch=False)
        return None if loaded is None else loaded.model_def.family

    def signature(self, model_id: ModelId):
        loaded = self._resident.get(model_id, touch=False)
        if loaded is None:
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        d = loaded.model_def
        # derived outputs advertised alongside concrete ones so clients can
        # discover filterable names via GetModelMetadata
        out_spec = dict(d.output_spec)
        out_spec.update({name: spec for name, (_fn, spec) in d.derived_outputs.items()})
        return d.input_spec, out_spec, d.method_name

    def check(self) -> None:
        """Health probe: the devices must answer a trivial computation
        (replaces the reference's probe-model GetModelStatus trick,
        cachemanager.go:76-89 — NOT_FOUND from a live backend = healthy)."""
        import jax
        import jax.numpy as jnp

        x = jax.device_put(jnp.ones((8,)), self._devices[0])
        if float(jnp.sum(x)) != 8.0:
            raise RuntimeError_("device smoke computation returned wrong result")

    @property
    def hbm_bytes_in_use(self) -> int:
        return self._resident.total_bytes

    def resident_models(self) -> list[ModelId]:
        return self._resident.keys_mru_first()

    def reset_group_state(self) -> None:
        """Drop every resident model plus the prefix KV and draft-acceptance
        histories — the clean slate a re-forming cross-host group resets to
        (parallel/multihost.py): after a follower death the survivors' (or a
        restarted follower's empty) states must match EXACTLY before the
        lockstep op stream resumes; re-deriving parity is impossible, so the
        group re-forms empty and cold-loads on demand like the reference's
        remapped ring keys (SURVEY §3.4)."""
        for mid in self.resident_models():
            self._resident.remove(mid, run_callback=True)
        if self._host_tier is not None:
            # drain first: the removals above may have queued demotions that
            # would otherwise repopulate the tier after the clear
            self.drain_demotions()
            self._host_tier.clear()
        if self._prefix_cache is not None:
            self._prefix_cache.clear()
        with self._slot_lock:
            self._slot_states.clear()
            self._slot_init_guards.clear()
        with self._spec_lock:
            self._spec_health.clear()

    def _update_gauges(self) -> None:
        # cost ledger: re-stamp every resident tenant's HBM level (and zero
        # the just-evicted — gauge_sync's owner-scoped sweep). Loads/evicts
        # are rare, so the O(resident) walk is off every request path.
        LEDGER.gauge_sync(
            "hbm_bytes",
            {
                str(mid): float(e.size_bytes)
                for mid, e in self._resident.items_lru_first()
            },
            owner=f"hbm:{id(self)}",
        )
        peak = RECORDER.observe_watermark(
            f"hbm_bytes:g{self.group}", float(self._resident.total_bytes)
        )
        if self.metrics is None:
            return
        self.metrics.hbm_bytes_in_use.labels(str(self.group)).set(self._resident.total_bytes)
        self.metrics.hbm_bytes_peak.labels(str(self.group)).set(peak)
        self.metrics.models_resident.labels(str(self.group)).set(len(self._resident))

    def close(self) -> None:
        if self._host_tier is not None:
            self._host_tier.close()  # put() no-ops from here on
            self._demote_queue.put(None)  # worker exits after queued jobs
            self._demote_thread.join(timeout=5.0)
        self._resident.clear()
        with self._adopted_lock:
            self._adopted.clear()
        with self._slot_lock:
            self._slot_states.clear()
            self._slot_init_guards.clear()
        with self._jit_lock:
            self._jitted_by_key.clear()
        with self._aot_lock:
            self._aot_cache.clear()
            self._aot_futures.clear()
            pool, self._compile_pool = self._compile_pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

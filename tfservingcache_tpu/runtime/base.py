"""Runtime interface.

Replaces the reference's TFServingController + external tensorflow_model_server
(pkg/cachemanager/servingcontroller.go:88-157): instead of desired-state
ReloadConfig RPCs against another process, the cache node drives an
in-process runtime with direct load/unload/predict calls. The lifecycle
state machine (START/LOADING/AVAILABLE/UNLOADING/END) is TF's
ModelVersionStatus enum, now tracked in-process (servingcontroller.go:29-54).

Methods are synchronous and thread-safe; async protocol backends call them
through an executor so JAX compile/infer never blocks the event loop.
"""

from __future__ import annotations

import abc
import threading
from typing import Mapping

import numpy as np

from tfservingcache_tpu.models.registry import TensorSpec
from tfservingcache_tpu.types import Model, ModelId, ModelState
from tfservingcache_tpu.utils.lockcheck import lockchecked


class RuntimeError_(Exception):
    """Runtime failure (load/predict). Underscore avoids shadowing builtins."""


class ModelNotLoadedError(RuntimeError_):
    pass


class LoadTimeoutError(RuntimeError_):
    """Cold-load deadline exceeded (fetch and/or compile overran
    ServingConfig.load_timeout_s). The reference hardcodes a 10 s model-fetch
    timeout (cmd/taskhandler/main.go:122) used as the AVAILABLE-poll deadline
    (cachemanager.go:176-193); here it bounds the whole fetch+compile path.
    Maps to HTTP 504 / gRPC DEADLINE_EXCEEDED at the protocol layer."""


class GroupUnhealthyError(RuntimeError_):
    """A cross-host group lost a follower (socket death / work timeout
    during a collective) and is torn down pending re-formation
    (parallel/multihost.py). Requests fail fast with this — they must not
    queue behind the wedged op — and the group's ring heartbeat fails so
    replicas and other groups absorb its traffic (the group-level analogue
    of the reference's dead-node ring remap, cluster.go:104-113). Maps to
    HTTP 503 / gRPC UNAVAILABLE."""


@lockchecked
class BaseRuntime(abc.ABC):
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_states": "_states_lock"}

    def __init__(self) -> None:
        self._states: dict[ModelId, ModelState] = {}
        self._states_lock = threading.Lock()

    # -- state machine ------------------------------------------------------
    def _set_state(self, model_id: ModelId, state: ModelState) -> None:
        with self._states_lock:
            self._states[model_id] = state

    def state(self, model_id: ModelId) -> ModelState:
        with self._states_lock:
            return self._states.get(model_id, ModelState.UNKNOWN)

    def states_for(self, name: str) -> dict[ModelId, ModelState]:
        """All known versions of ``name`` (the ModelService status view;
        reference GetModelStates, servingcontroller.go:140-157)."""
        with self._states_lock:
            return {m: s for m, s in self._states.items() if m.name == name}

    # -- core ---------------------------------------------------------------
    @abc.abstractmethod
    def ensure_loaded(self, model: Model) -> str | None:
        """Make ``model`` servable (idempotent); blocks until AVAILABLE or
        raises. The artifact is already on local disk at ``model.path``.
        May return the residency tier that served the call ("hbm" | "host"
        | "disk") for the ``tpusc_reload_source`` accounting; a ``None``
        return is read as a full disk load."""

    @abc.abstractmethod
    def is_loaded(self, model_id: ModelId) -> bool: ...

    @abc.abstractmethod
    def predict(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        output_filter: list[str] | None = None,
    ) -> dict[str, np.ndarray]: ...

    @abc.abstractmethod
    def unload(self, model_id: ModelId) -> None: ...

    def unload_and_discard(self, model_id: ModelId) -> None:
        """Disk-evict hook: drop HBM residency AND any intermediate-tier
        state (host-RAM packed chunks) so no tier retains a model whose
        backing artifact is gone. Runtimes without extra tiers inherit the
        plain unload."""
        self.unload(model_id)

    @abc.abstractmethod
    def signature(self, model_id: ModelId) -> tuple[dict[str, TensorSpec], dict[str, TensorSpec], str]:
        """-> (input_spec, output_spec, method_name) for a loaded model."""

    def generate(
        self,
        model_id: ModelId,
        input_ids: np.ndarray,
        prompt_lengths=None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        draft_model_id: ModelId | None = None,
        spec_tokens: int = 4,
    ) -> np.ndarray:
        """KV-cached autoregressive decoding (tpusc extension verb); runtimes
        without a decoder path keep this default."""
        raise RuntimeError_("this runtime does not support generation")

    # Does this runtime overlap cold-load stages? CacheManager consults this
    # (duck-typed via getattr) to decide whether a streaming provider fetch
    # is worth wiring up; the base default keeps fakes and CPU-only runtimes
    # on the plain fetch path.
    cold_pipeline_enabled: bool = False

    def precompile_from_meta(self, meta) -> None:
        """Advisory hint: artifact metadata is available (the provider fetch
        may still be streaming params bytes) — a pipelined runtime starts
        AOT-compiling the family executable now. Must never raise into the
        fetch path; the default does nothing."""

    @abc.abstractmethod
    def check(self) -> None:
        """Raise when the runtime/accelerator is unhealthy."""

    @property
    @abc.abstractmethod
    def hbm_bytes_in_use(self) -> int: ...

    def close(self) -> None:  # pragma: no cover - trivial default
        pass

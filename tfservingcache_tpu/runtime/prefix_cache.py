"""Prefix KV cache: reuse a prompt's (and its generation's) device-resident
K/V rows across ``:generate`` requests.

No reference counterpart (the reference proxies opaque Predicts). The
serving pattern this targets is conversational: turn N's prompt extends
turn N-1's prompt + completion, so the expensive prefill over the shared
history is paid once. Entries store a power-of-two row block — the CALLER
(_prefix_generate) slices to the pow2 floor of the valid rows, so hits
never mint novel jit trace shapes — plus the exact token ids those rows
encode; a lookup matches the longest cached entry whose tokens are a
prefix of the new prompt, token-for-token (no hash-collision risk).

Byte-budgeted LRU, OFF by default (``serving.prefix_cache_bytes = 0``):
entries hold real HBM. Cross-host groups are supported (VERDICT r5 #7):
each process caches its own K/V shards, the LEADER's hit decision rides the
work envelope (``peek`` + ``generate(prefix_rows=...)``) so every process
provably runs the same program, and group re-formation resets all caches to
empty together. Entries are bucketed per model so one tenant's scan never
pays for another's, and ``drop_model`` is O(that model's entries).
"""

from __future__ import annotations

import itertools
import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

import numpy as np

from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.lockcheck import lockchecked


@dataclass
class PrefixEntry:
    tokens: np.ndarray          # (L,) int32 — what the valid rows encode
    k: Any                      # (layers, 1, n_kv, Lpad, hd) device array
    v: Any
    valid_len: int              # L <= Lpad
    nbytes: int


# lookup() linear-scans one model's entries under the global lock; this cap
# keeps the B=1 :generate hot path O(small) no matter how large the byte
# budget is (ADVICE r4). 32 concurrent conversations per tenant model before
# the model's own LRU starts dropping the coldest thread.
_MAX_ENTRIES_PER_MODEL = 32


@lockchecked
class PrefixCache:
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_by_model": "_lock",
        "_recency": "_lock",
        "_total": "_lock",
    }

    def __init__(self, capacity_bytes: int) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        # per-model LRU of entries (token bytes -> entry), with a global
        # recency order across models for byte-budget eviction
        self._by_model: dict[ModelId, OrderedDict[bytes, PrefixEntry]] = {}
        self._recency: OrderedDict[tuple[ModelId, bytes], None] = OrderedDict()
        self._total = 0
        self.hits = 0
        self.misses = 0

    def _best_match(self, model_id: ModelId,  # lock-held: _lock
                    prompt: np.ndarray) -> tuple[bytes | None, int]:
        """(backing key, usable rows) of the longest entry whose tokens are
        a STRICT prefix of ``prompt`` (strict: at least one suffix token must
        remain to prefill — the forward needs a non-empty block). Callers
        hold the lock. The ONE matching rule: ``lookup`` (mutating) and
        ``peek`` (the group leader's envelope decision) must never diverge,
        so they share this."""
        best_tok, best = None, 0
        for tok_bytes, ent in self._by_model.get(model_id, {}).items():
            usable = min(ent.valid_len, prompt.shape[0] - 1)
            if usable < 1 or usable <= best:
                continue
            if np.array_equal(ent.tokens[:usable], prompt[:usable]):
                best_tok, best = tok_bytes, usable
        return best_tok, best

    def lookup(self, model_id: ModelId, prompt: np.ndarray) -> PrefixEntry | None:
        """Longest strict-prefix entry (see _best_match), counted + touched."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            best_tok, usable = self._best_match(model_id, prompt)
            best: PrefixEntry | None = None
            if best_tok is not None:
                ent = self._by_model[model_id][best_tok]
                if usable < ent.valid_len:
                    # partially usable entry: present it at the usable
                    # length (rows beyond it are junk the suffix prefill
                    # overwrites)
                    ent = PrefixEntry(ent.tokens[:usable], ent.k, ent.v,
                                      usable, ent.nbytes)
                best = ent
            if best is not None:
                self._recency.move_to_end((model_id, best_tok))
                # keep the per-model order LRU too: the entry cap below
                # evicts from its front
                self._by_model[model_id].move_to_end(best_tok)
                self.hits += 1
            else:
                self.misses += 1
        return best

    def insert(self, model_id: ModelId, tokens: np.ndarray, k, v,
               valid_len: int) -> None:
        tokens = np.asarray(tokens, np.int32)[:valid_len]
        nbytes = int(k.nbytes) + int(v.nbytes)
        if nbytes > self.capacity_bytes:
            return  # one entry over budget: don't thrash the whole cache
        tok_bytes = tokens.tobytes()
        with self._lock:
            model_entries = self._by_model.setdefault(model_id, OrderedDict())
            old = model_entries.pop(tok_bytes, None)
            if old is not None:
                self._total -= old.nbytes
                self._recency.pop((model_id, tok_bytes), None)
            while self._total + nbytes > self.capacity_bytes and self._recency:
                (ev_mid, ev_tok), _ = self._recency.popitem(last=False)
                ev = self._by_model.get(ev_mid, {}).pop(ev_tok, None)
                if ev is not None:
                    self._total -= ev.nbytes
            model_entries[tok_bytes] = PrefixEntry(tokens, k, v, valid_len,
                                                   nbytes)
            self._recency[(model_id, tok_bytes)] = None
            self._total += nbytes
            while len(model_entries) > _MAX_ENTRIES_PER_MODEL:
                ev_tok, ev = model_entries.popitem(last=False)
                self._total -= ev.nbytes
                self._recency.pop((model_id, ev_tok), None)

    def peek(self, model_id: ModelId, prompt: np.ndarray) -> int:
        """Usable row count of the best entry for ``prompt`` WITHOUT touching
        recency or hit/miss counters (0 = miss). A cross-host group's leader
        peeks under its op lock to form the envelope decision; the real
        lookup happens inside generate on every process."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            return self._best_match(model_id, prompt)[1]

    def note_forced_miss(self) -> None:
        """Stats for a miss decided upstream (group envelope forced_rows=0):
        the local lookup was bypassed, the miss still happened."""
        with self._lock:
            self.misses += 1

    def drop_model(self, model_id: ModelId) -> None:
        """Model unloaded/evicted: its prefix KV must go with it."""
        with self._lock:
            entries = self._by_model.pop(model_id, None)
            if not entries:
                return
            for tok_bytes, ent in entries.items():
                self._total -= ent.nbytes
                self._recency.pop((model_id, tok_bytes), None)

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def __len__(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._by_model.values())

    def clear(self) -> None:
        with self._lock:
            self._by_model.clear()
            self._recency.clear()
            self._total = 0


# =============================================================================
# Cross-request shared-prefix KV over the PAGED arena (ISSUE 9 / ROADMAP
# item 3). Unlike PrefixCache above — which stores its own dense K/V row
# blocks and serves the B=1 solo path — PagePrefixIndex stores no K/V at
# all: it is a radix index from token-prefix to the ARENA PAGES that
# already hold that prefix's K/V, so N concurrent same-prompt rows map the
# same physical pages read-only instead of each prefilling a private copy.
# =============================================================================


@dataclass
class SharedPrefixPlan:
    """One admission's shared-prefix decision, produced by
    ``PagePrefixIndex.lookup`` (and trimmed for viability by
    ``TPUModelRuntime.shared_prefix_plan``) and consumed by the continuous
    scheduler's reservation + prefill + CoW steps.

    ``kind == "shared"``: map ``pages`` (full page-aligned chunks of the
    prompt) read-only and prefill only the suffix. ``kind == "exact"``: the
    whole prompt is indexed — map ``pages`` plus ``boundary_page`` (the
    index-held copy of the partially-filled last page, when ``tail_len >
    0``) and skip prefill compute entirely; the first token is sampled from
    ``logits`` (the publisher's last-position prefill logits) under the new
    request's own seed, so sampling parity with a cold prefill holds
    token-for-token."""

    kind: str                        # "exact" | "shared"
    pages: list[int]                 # full-chunk pages, prompt order
    n_full: int                      # == len(pages)
    page_tokens: int = 0
    boundary_page: int | None = None  # exact only, tail_len > 0
    tail_len: int = 0                # prompt tokens inside the boundary page
    logits: np.ndarray | None = None  # (1, V) f32 — exact only

    @property
    def covered(self) -> int:
        """Prompt tokens whose K/V the mapped full pages already hold."""
        return self.n_full * self.page_tokens

    def mapped_pages(self) -> list[int]:
        out = list(self.pages)
        if self.kind == "exact" and self.boundary_page is not None:
            out.append(self.boundary_page)
        return out


class _RadixNode:
    """One full ``page_tokens``-token chunk of some indexed prompt. The
    node's page holds exactly that chunk's K/V; children extend the prefix
    by one more full chunk; ``tails`` terminate prompts mid-page."""

    __slots__ = ("page", "children", "tails", "last_used")

    def __init__(self, page: int = 0) -> None:
        self.page = page
        self.children: dict[bytes, _RadixNode] = {}
        self.tails: dict[bytes, _Tail] = {}
        self.last_used = 0


@dataclass
class _Tail:
    """Terminal entry for a prompt that ends mid-page (or page-aligned):
    the index-held pristine copy of the boundary page (``page`` — None when
    the prompt is page-aligned and there is nothing mid-page to hold) plus
    the publisher's last-position prefill logits, which is what lets an
    exact re-admission skip prefill compute entirely."""

    page: int | None
    logits: np.ndarray               # (1, V) f32
    tail_len: int
    last_used: int = 0
    nbytes: int = 0


class PagePrefixIndex:
    """Radix index token-prefix -> (arena page list, cached first-token
    logits) for ONE model's paged slot state (runtime/model_runtime.py
    SlotDecodeState.prefix_index). Single-threaded by construction: the
    model's continuous-scheduler thread owns the slot state's host mirrors
    and is the only caller, so there is no lock (same ownership rule as
    block_tables / free_pages).

    Refcount protocol: the index holds one reference per node/tail page it
    stores, mirrored into ``SlotDecodeState.page_refs`` by the CALLER
    (insert/evict return the page lists to incref/decref) — the index
    never touches the free-list itself, so the conservation invariant
    (every page free XOR trash XOR referenced) is enforceable in one
    place. Byte budget counts pinned pages (+ cached logits); eviction
    drops the coldest LEAF first, preferring pages with zero lane
    references (``page_refs == index refs``) so evicting actually frees
    arena memory, and ``reclaim`` lets admission pressure override the
    budget entirely rather than block a request behind cold cache pages."""

    def __init__(self, page_tokens: int, page_nbytes: int,
                 capacity_bytes: int) -> None:
        self.page_tokens = int(page_tokens)
        self.page_nbytes = int(page_nbytes)
        self.capacity_bytes = int(capacity_bytes)
        self._root = _RadixNode()
        self._held: dict[int, int] = {}   # page -> index refs (normally 1)
        self._clock = itertools.count(1)
        self._bytes = 0
        self.hits = 0
        self.exact_hits = 0
        self.misses = 0

    # -- read side -----------------------------------------------------------
    @property
    def total_bytes(self) -> int:
        return self._bytes

    def held_pages(self) -> dict[int, int]:
        """page -> index reference count (for conservation checks and the
        shared/cached page-split observability)."""
        return dict(self._held)

    def lookup(self, prompt: np.ndarray) -> SharedPrefixPlan | None:
        """Longest page-aligned indexed prefix of ``prompt`` — an exact
        terminal match (full skip) beats any partial one. Touches recency
        along the matched path and counts hit/miss."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p, pt = prompt.shape[0], self.page_tokens
        stamp = next(self._clock)
        node, pages, i = self._root, [], 0
        while (i + 1) * pt <= p:
            child = node.children.get(prompt[i * pt:(i + 1) * pt].tobytes())
            if child is None:
                break
            child.last_used = stamp
            pages.append(child.page)
            node, i = child, i + 1
        tail = node.tails.get(prompt[i * pt:].tobytes())
        if tail is not None:
            tail.last_used = stamp
            self.hits += 1
            self.exact_hits += 1
            return SharedPrefixPlan(
                "exact", pages, i, page_tokens=pt, boundary_page=tail.page,
                tail_len=tail.tail_len, logits=tail.logits,
            )
        if i > 0 and i * pt >= p:
            # page-aligned prompt with no cached logits: at least one
            # suffix token must remain to prefill (the forward needs a
            # non-empty block — same strictness as PrefixCache._best_match)
            i -= 1
            pages.pop()
        if i == 0:
            self.misses += 1
            return None
        self.hits += 1
        return SharedPrefixPlan("shared", pages, i, page_tokens=pt)

    # -- write side ----------------------------------------------------------
    def insert(
        self,
        prompt: np.ndarray,
        full_pages: list[int],
        boundary_page: int | None,
        logits: np.ndarray | None,
        page_refs: np.ndarray,
    ) -> tuple[list[int], list[int]]:
        """Publish an admitted lane's prompt: ``full_pages`` are the lane's
        block-table entries covering the prompt's full page chunks (shared
        chunks dedup onto existing nodes — no double ref), ``boundary_page``
        is a PRISTINE COPY of the partially-filled last page (made by the
        caller before the lane's decode writes dirty the original).
        Returns ``(added, released)``: pages the index newly references
        (caller increfs) and pages budget eviction released (caller decrefs
        and recycles). A declined ``boundary_page`` is returned in neither
        list — the caller puts it back on the free-list."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pt = self.page_tokens
        stamp = next(self._clock)
        added: list[int] = []
        node = self._root
        for i, pg in enumerate(full_pages):
            key = prompt[i * pt:(i + 1) * pt].tobytes()
            child = node.children.get(key)
            if child is None:
                child = _RadixNode(page=int(pg))
                node.children[key] = child
                self._held[child.page] = self._held.get(child.page, 0) + 1
                self._bytes += self.page_nbytes
                added.append(child.page)
            # an existing node keeps ITS page (it already holds this
            # chunk's K/V); the publisher's duplicate page stays private
            child.last_used = stamp
            node = child
        rem_key = prompt[len(full_pages) * pt:].tobytes()
        if logits is not None and rem_key not in node.tails:
            logits = np.asarray(logits, np.float32)
            tail_len = prompt.shape[0] - len(full_pages) * pt
            if boundary_page is not None or tail_len == 0:
                nbytes = int(logits.nbytes)
                if boundary_page is not None:
                    nbytes += self.page_nbytes
                    self._held[int(boundary_page)] = (
                        self._held.get(int(boundary_page), 0) + 1
                    )
                    added.append(int(boundary_page))
                node.tails[rem_key] = _Tail(
                    None if boundary_page is None else int(boundary_page),
                    logits, tail_len, stamp, nbytes,
                )
                self._bytes += nbytes
        released = self._evict(page_refs, self.capacity_bytes)
        return added, released

    def reclaim(self, page_refs: np.ndarray, want_pages: int,
                protect: frozenset = frozenset()) -> list[int]:
        """Admission pressure: release up to ``want_pages`` ZERO-LANE-REF
        pages regardless of the byte budget (dropping coldest leaves
        first), never touching ``protect`` (the pages the blocked
        request's own share plan maps). The cache must never win a page
        fight against a live admission."""
        return self._evict(
            page_refs, target_bytes=None, want_pages=want_pages,
            protect=protect, zero_ref_only=True,
        )

    def _leaf_candidates(self):
        """Yield every removable leaf: (node-or-tail marker, parent, key,
        last_used, pages). Rebuilt per eviction round — the index is
        budget-capped small, so clarity beats an intrusive heap."""
        stack = [(self._root, None, None)]
        while stack:
            node, parent, key = stack.pop()
            for k, t in node.tails.items():
                yield ("tail", node, k, t.last_used,
                       [] if t.page is None else [t.page])
            if (parent is not None and not node.children
                    and not node.tails):
                yield ("node", parent, key, node.last_used, [node.page])
            for k, child in node.children.items():
                stack.append((child, node, k))

    def _evict(self, page_refs, target_bytes, want_pages: int = 0,
               protect: frozenset = frozenset(),
               zero_ref_only: bool = False) -> list[int]:
        released: list[int] = []
        freed_pages = 0
        while True:
            if target_bytes is not None and self._bytes <= target_bytes \
                    and not want_pages:
                break
            if want_pages and freed_pages >= want_pages:
                break
            best = None
            for cand in self._leaf_candidates():
                kind, holder, key, last_used, pages = cand
                if any(pg in protect for pg in pages):
                    continue
                # zero lane refs: every reference on the page is the
                # index's own -> dropping it actually frees arena memory
                zero_ref = all(
                    int(page_refs[pg]) <= self._held.get(pg, 0)
                    for pg in pages
                )
                if zero_ref_only and not (zero_ref and pages):
                    continue
                rank = (0 if zero_ref else 1, last_used)
                if best is None or rank < best[0]:
                    best = (rank, cand)
            if best is None:
                break
            kind, holder, key, _, pages = best[1]
            if kind == "tail":
                tail = holder.tails.pop(key)
                self._bytes -= tail.nbytes
            else:
                holder.children.pop(key)
                self._bytes -= self.page_nbytes
            for pg in pages:
                n = self._held.get(pg, 0) - 1
                if n <= 0:
                    self._held.pop(pg, None)
                else:
                    self._held[pg] = n
                released.append(pg)
                freed_pages += 1
        return released

"""Prefix KV cache: reuse a prompt's (and its generation's) device-resident
K/V rows across ``:generate`` requests.

No reference counterpart (the reference proxies opaque Predicts). The
serving pattern this targets is conversational: turn N's prompt extends
turn N-1's prompt + completion, so the expensive prefill over the shared
history is paid once. Entries store the PADDED cache block (power-of-two
row bucket — one jitted copy shape per bucket) plus the exact token ids
those rows encode; a lookup matches the longest cached entry whose tokens
are a prefix of the new prompt, token-for-token (no hash-collision risk).

Byte-budgeted LRU, OFF by default (``serving.prefix_cache_bytes = 0``):
entries hold real HBM. Single-group runtimes only — a cross-host group's
leader and followers could disagree on hits and diverge their op streams.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

import numpy as np

from tfservingcache_tpu.types import ModelId


@dataclass
class PrefixEntry:
    tokens: np.ndarray          # (L,) int32 — what the valid rows encode
    k: Any                      # (layers, 1, n_kv, Lpad, hd) device array
    v: Any
    valid_len: int              # L <= Lpad
    nbytes: int


def _bucket(n: int) -> int:
    """Power-of-two row bucket with a 16-row floor (one jitted copy shape
    per bucket); shares the runtime's next_bucket rather than re-coding it."""
    from tfservingcache_tpu.runtime.model_runtime import next_bucket

    return max(16, next_bucket(n))


class PrefixCache:
    def __init__(self, capacity_bytes: int) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        # LRU: key -> entry; key includes the model and the entry's token
        # bytes (exact, not a hash)
        self._entries: OrderedDict[tuple, PrefixEntry] = OrderedDict()
        self._total = 0
        self.hits = 0
        self.misses = 0

    @staticmethod
    def _key(model_id: ModelId, tokens: np.ndarray) -> tuple:
        return (model_id, tokens.tobytes())

    def lookup(self, model_id: ModelId, prompt: np.ndarray) -> PrefixEntry | None:
        """Longest entry whose tokens are a strict prefix of ``prompt``
        (strict: at least one suffix token must remain to prefill — the
        forward needs a non-empty block)."""
        prompt = np.asarray(prompt, np.int32)
        best: PrefixEntry | None = None
        best_key: tuple | None = None
        with self._lock:
            for key, ent in self._entries.items():
                if key[0] != model_id:
                    continue
                usable = min(ent.valid_len, prompt.shape[0] - 1)
                if usable < 1 or (best is not None and usable <= best.valid_len):
                    continue
                if np.array_equal(ent.tokens[:usable], prompt[:usable]):
                    if usable < ent.valid_len:
                        # partially usable entry: present it at the usable
                        # length (rows beyond it are junk the suffix prefill
                        # overwrites)
                        ent = PrefixEntry(ent.tokens[:usable], ent.k, ent.v,
                                          usable, ent.nbytes)
                    best = ent
                    best_key = key  # the BACKING key — a truncated view's
                    #                 rebuilt key would never match it
            if best is not None:
                self._entries.move_to_end(best_key)  # LRU recency touch
                self.hits += 1
            else:
                self.misses += 1
        return best

    def insert(self, model_id: ModelId, tokens: np.ndarray, k, v,
               valid_len: int) -> None:
        tokens = np.asarray(tokens, np.int32)[:valid_len]
        nbytes = int(k.nbytes) + int(v.nbytes)
        if nbytes > self.capacity_bytes:
            return  # one entry over budget: don't thrash the whole cache
        key = self._key(model_id, tokens)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._total -= old.nbytes
            while self._total + nbytes > self.capacity_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._total -= evicted.nbytes
            self._entries[key] = PrefixEntry(tokens, k, v, valid_len, nbytes)
            self._total += nbytes

    def drop_model(self, model_id: ModelId) -> None:
        """Model unloaded/evicted: its prefix KV must go with it."""
        with self._lock:
            for key in [k for k in self._entries if k[0] == model_id]:
                self._total -= self._entries.pop(key).nbytes

    @property
    def total_bytes(self) -> int:
        return self._total

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._total = 0

"""Prefix KV cache: reuse a prompt's (and its generation's) device-resident
K/V rows across ``:generate`` requests.

No reference counterpart (the reference proxies opaque Predicts). The
serving pattern this targets is conversational: turn N's prompt extends
turn N-1's prompt + completion, so the expensive prefill over the shared
history is paid once. Entries store a power-of-two row block — the CALLER
(_prefix_generate) slices to the pow2 floor of the valid rows, so hits
never mint novel jit trace shapes — plus the exact token ids those rows
encode; a lookup matches the longest cached entry whose tokens are a
prefix of the new prompt, token-for-token (no hash-collision risk).

Byte-budgeted LRU, OFF by default (``serving.prefix_cache_bytes = 0``):
entries hold real HBM. Cross-host groups are supported (VERDICT r5 #7):
each process caches its own K/V shards, the LEADER's hit decision rides the
work envelope (``peek`` + ``generate(prefix_rows=...)``) so every process
provably runs the same program, and group re-formation resets all caches to
empty together. Entries are bucketed per model so one tenant's scan never
pays for another's, and ``drop_model`` is O(that model's entries).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass
from typing import Any

import numpy as np

from tfservingcache_tpu.types import ModelId


@dataclass
class PrefixEntry:
    tokens: np.ndarray          # (L,) int32 — what the valid rows encode
    k: Any                      # (layers, 1, n_kv, Lpad, hd) device array
    v: Any
    valid_len: int              # L <= Lpad
    nbytes: int


# lookup() linear-scans one model's entries under the global lock; this cap
# keeps the B=1 :generate hot path O(small) no matter how large the byte
# budget is (ADVICE r4). 32 concurrent conversations per tenant model before
# the model's own LRU starts dropping the coldest thread.
_MAX_ENTRIES_PER_MODEL = 32


class PrefixCache:
    def __init__(self, capacity_bytes: int) -> None:
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        # per-model LRU of entries (token bytes -> entry), with a global
        # recency order across models for byte-budget eviction
        self._by_model: dict[ModelId, OrderedDict[bytes, PrefixEntry]] = {}
        self._recency: OrderedDict[tuple[ModelId, bytes], None] = OrderedDict()
        self._total = 0
        self.hits = 0
        self.misses = 0

    def _best_match(self, model_id: ModelId,
                    prompt: np.ndarray) -> tuple[bytes | None, int]:
        """(backing key, usable rows) of the longest entry whose tokens are
        a STRICT prefix of ``prompt`` (strict: at least one suffix token must
        remain to prefill — the forward needs a non-empty block). Callers
        hold the lock. The ONE matching rule: ``lookup`` (mutating) and
        ``peek`` (the group leader's envelope decision) must never diverge,
        so they share this."""
        best_tok, best = None, 0
        for tok_bytes, ent in self._by_model.get(model_id, {}).items():
            usable = min(ent.valid_len, prompt.shape[0] - 1)
            if usable < 1 or usable <= best:
                continue
            if np.array_equal(ent.tokens[:usable], prompt[:usable]):
                best_tok, best = tok_bytes, usable
        return best_tok, best

    def lookup(self, model_id: ModelId, prompt: np.ndarray) -> PrefixEntry | None:
        """Longest strict-prefix entry (see _best_match), counted + touched."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            best_tok, usable = self._best_match(model_id, prompt)
            best: PrefixEntry | None = None
            if best_tok is not None:
                ent = self._by_model[model_id][best_tok]
                if usable < ent.valid_len:
                    # partially usable entry: present it at the usable
                    # length (rows beyond it are junk the suffix prefill
                    # overwrites)
                    ent = PrefixEntry(ent.tokens[:usable], ent.k, ent.v,
                                      usable, ent.nbytes)
                best = ent
            if best is not None:
                self._recency.move_to_end((model_id, best_tok))
                # keep the per-model order LRU too: the entry cap below
                # evicts from its front
                self._by_model[model_id].move_to_end(best_tok)
                self.hits += 1
            else:
                self.misses += 1
        return best

    def insert(self, model_id: ModelId, tokens: np.ndarray, k, v,
               valid_len: int) -> None:
        tokens = np.asarray(tokens, np.int32)[:valid_len]
        nbytes = int(k.nbytes) + int(v.nbytes)
        if nbytes > self.capacity_bytes:
            return  # one entry over budget: don't thrash the whole cache
        tok_bytes = tokens.tobytes()
        with self._lock:
            model_entries = self._by_model.setdefault(model_id, OrderedDict())
            old = model_entries.pop(tok_bytes, None)
            if old is not None:
                self._total -= old.nbytes
                self._recency.pop((model_id, tok_bytes), None)
            while self._total + nbytes > self.capacity_bytes and self._recency:
                (ev_mid, ev_tok), _ = self._recency.popitem(last=False)
                ev = self._by_model.get(ev_mid, {}).pop(ev_tok, None)
                if ev is not None:
                    self._total -= ev.nbytes
            model_entries[tok_bytes] = PrefixEntry(tokens, k, v, valid_len,
                                                   nbytes)
            self._recency[(model_id, tok_bytes)] = None
            self._total += nbytes
            while len(model_entries) > _MAX_ENTRIES_PER_MODEL:
                ev_tok, ev = model_entries.popitem(last=False)
                self._total -= ev.nbytes
                self._recency.pop((model_id, ev_tok), None)

    def peek(self, model_id: ModelId, prompt: np.ndarray) -> int:
        """Usable row count of the best entry for ``prompt`` WITHOUT touching
        recency or hit/miss counters (0 = miss). A cross-host group's leader
        peeks under its op lock to form the envelope decision; the real
        lookup happens inside generate on every process."""
        prompt = np.asarray(prompt, np.int32)
        with self._lock:
            return self._best_match(model_id, prompt)[1]

    def note_forced_miss(self) -> None:
        """Stats for a miss decided upstream (group envelope forced_rows=0):
        the local lookup was bypassed, the miss still happened."""
        with self._lock:
            self.misses += 1

    def drop_model(self, model_id: ModelId) -> None:
        """Model unloaded/evicted: its prefix KV must go with it."""
        with self._lock:
            entries = self._by_model.pop(model_id, None)
            if not entries:
                return
            for tok_bytes, ent in entries.items():
                self._total -= ent.nbytes
                self._recency.pop((model_id, tok_bytes), None)

    @property
    def total_bytes(self) -> int:
        return self._total

    def __len__(self) -> int:
        return sum(len(d) for d in self._by_model.values())

    def clear(self) -> None:
        with self._lock:
            self._by_model.clear()
            self._recency.clear()
            self._total = 0

"""FakeRuntime — the in-process test double for the model runtime.

SURVEY.md §4's core lesson: the reference could never test its
fetch/evict/reload state machine because the backend lived in another
process; this fake makes the CacheManager's most subtle code testable
(configurable latency/failures, call recording, real state transitions).
"""

from __future__ import annotations

import threading
import time
from typing import Mapping

import numpy as np

from tfservingcache_tpu.models.registry import TensorSpec
from tfservingcache_tpu.runtime.base import BaseRuntime, ModelNotLoadedError, RuntimeError_
from tfservingcache_tpu.types import Model, ModelId, ModelState
from tfservingcache_tpu.utils.lockcheck import lockchecked


@lockchecked
class FakeRuntime(BaseRuntime):
    """predict(x) = x * version + bias, so tests can tell versions apart."""

    _tpusc_guarded = {"_loaded": "_lock"}

    def __init__(
        self,
        load_delay_s: float = 0.0,
        fail_loads: set[ModelId] | None = None,
        bias: float = 0.0,
        max_loaded: int | None = None,
    ) -> None:
        super().__init__()
        self.load_delay_s = load_delay_s
        self.fail_loads = fail_loads or set()
        self.bias = bias
        self.max_loaded = max_loaded
        self.loads: list[ModelId] = []
        self.unloads: list[ModelId] = []
        self.predicts: list[ModelId] = []
        self.concurrent_loads = 0
        self.max_concurrent_loads = 0
        self._loaded: dict[ModelId, Model] = {}
        self._lock = threading.Lock()

    def ensure_loaded(self, model: Model) -> None:
        mid = model.identifier
        with self._lock:
            if mid in self._loaded:
                return
            self.concurrent_loads += 1
            self.max_concurrent_loads = max(self.max_concurrent_loads, self.concurrent_loads)
            self._set_state(mid, ModelState.LOADING)
        try:
            if self.load_delay_s:
                time.sleep(self.load_delay_s)
            if mid in self.fail_loads:
                self._set_state(mid, ModelState.END)
                raise RuntimeError_(f"fake load failure for {mid}")
            with self._lock:
                if self.max_loaded is not None and len(self._loaded) >= self.max_loaded:
                    lru = next(iter(self._loaded))
                    del self._loaded[lru]
                    self.unloads.append(lru)
                    self._set_state(lru, ModelState.END)
                self._loaded[mid] = model
                self.loads.append(mid)
                self._set_state(mid, ModelState.AVAILABLE)
        finally:
            with self._lock:
                self.concurrent_loads -= 1

    def is_loaded(self, model_id: ModelId) -> bool:
        with self._lock:
            return model_id in self._loaded

    def resident_headroom(self) -> tuple[int | None, int]:
        # mirrors TPUModelRuntime.resident_headroom (byte budget uncapped
        # here: the fake sizes nothing)
        with self._lock:
            free = (
                None if self.max_loaded is None
                else max(0, self.max_loaded - len(self._loaded))
            )
        return free, 1 << 60

    def predict(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        output_filter: list[str] | None = None,
    ) -> dict[str, np.ndarray]:
        if not self.is_loaded(model_id):
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        self.predicts.append(model_id)
        x = np.asarray(inputs["x"], dtype=np.float32)
        out = {"y": x * model_id.version + self.bias}
        if output_filter:
            out = {k: v for k, v in out.items() if k in output_filter}
        return out

    def unload(self, model_id: ModelId) -> None:
        with self._lock:
            if model_id in self._loaded:
                del self._loaded[model_id]
                self.unloads.append(model_id)
                self._set_state(model_id, ModelState.END)

    def signature(self, model_id: ModelId):
        if not self.is_loaded(model_id):
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        return (
            {"x": TensorSpec("float32", (-1,))},
            {"y": TensorSpec("float32", (-1,))},
            "tensorflow/serving/predict",
        )

    def generate(
        self,
        model_id: ModelId,
        input_ids,
        prompt_lengths=None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int = 0,
        draft_model_id=None,
        spec_tokens: int = 4,
    ):
        import numpy as np

        if not self.is_loaded(model_id):
            raise ModelNotLoadedError(f"model {model_id} is not loaded")
        b = np.asarray(input_ids).shape[0]
        # deterministic fake: token id == model version
        return np.full((b, max_new_tokens), model_id.version, np.int32)

    def check(self) -> None:
        pass

    @property
    def hbm_bytes_in_use(self) -> int:
        # lock: iterating an unlocked dict races a concurrent load's insert
        # (RuntimeError: dictionary changed size during iteration)
        with self._lock:
            return sum(m.size_on_disk for m in self._loaded.values())

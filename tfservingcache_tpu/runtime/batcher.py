"""Continuous (pipelined) micro-batching for the in-process runtime.

Reference-parity rationale: the reference delegates request batching to TF
Serving's ``--enable_batching`` (the sidecar never sees tensors); with
inference in-process, the batcher moves here. TPU-first motivation: one
batched MXU dispatch amortizes per-call host->device overhead — the dominant
warm-path cost for small models — and a power-of-two padded batch keeps the
jit cache small (runtime._pad_to_bucket already buckets the batch axis).

Continuous-batching design (no timed window): batches for one
(model, non-batch shape, filter) key are serialized on a per-key gate. The
first arrival becomes the leader of the next batch and acquires the gate;
while a previous batch occupies the device, later arrivals keep joining the
leader's pending batch, and the moment the gate frees the batch closes and
runs as ONE runtime.predict, outputs split back by each caller's row count.
The accumulation window is therefore exactly the device's own busy time:

  - strictly sequential traffic acquires an uncontended gate and runs
    immediately — no timed wait is ever inserted (the added latency is the
    gate bookkeeping itself, small but not literally zero);
  - saturating traffic coalesces into device-call-sized batches without any
    window-length tuning (the classic latency/throughput knob dissolves).

Whether coalescing wins over independent dispatch is an empirical, shape-
dependent question — bench.py measures warm QPS batcher on vs off with
varied payloads; round 2's "batcher loses 31%" verdict was measured with
identical repeated payloads a transport cache could answer, so trust only
the varied-payload numbers.

Calls are thread-blocking by design — they arrive on the protocol backend's
executor threads (protocol/local_backend.py), never on the event loop.

Models whose inputs have no named "batch" axis fall through unbatched.
"""

from __future__ import annotations

import secrets
import threading
from dataclasses import dataclass, field
from typing import Mapping

import numpy as np

from tfservingcache_tpu.runtime.base import BaseRuntime
from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.tracing import TRACER

log = get_logger("runtime.batcher")


# the coalescer predicts which runtime compile bucket a request lands in —
# it must be the runtime's own bucketing function, not a copy that can drift
from tfservingcache_tpu.runtime.model_runtime import next_bucket as _next_bucket


class _Gate:
    """A counted gate admitting up to ``limit`` concurrent holders.

    One mutex per key (round-2 design) serialized ALL device calls for a
    model: with the device/transport busy for RTT seconds, at most one batch
    was ever in flight, while the unbatched path pipelines ``clients``
    independent calls through the transport — the batcher *lost* throughput
    on any link whose round-trip dominates device time (the r2 31% and the
    r3 preview's 3x REST regression). A bounded semaphore keeps the
    accumulate-while-busy behavior (leaders still block once ``limit``
    batches are in flight, and arrivals join the blocked leader's batch)
    while letting ``limit`` batches overlap host codec + transfer + compute."""

    def __init__(self, limit: int) -> None:
        self._sem = threading.BoundedSemaphore(limit)
        self._count = threading.Lock()
        self.in_use = 0

    def __enter__(self) -> "_Gate":
        self._sem.acquire()
        with self._count:
            self.in_use += 1
        return self

    def __exit__(self, *exc) -> None:
        with self._count:
            self.in_use -= 1
        self._sem.release()


class _GateMap:
    """Per-key device gates with bounded growth (shared by MicroBatcher and
    GenerateCoalescer): bound how many batches per key are in flight so
    arrivals during a saturated device accumulate into the next batch.
    Pruning keeps only in-use gates; losing an idle gate only costs a
    coalescing opportunity (or briefly exceeds the in-flight bound), never
    correctness."""

    def __init__(self, max_entries: int = 4096, limit: int = 4) -> None:
        self._lock = threading.Lock()
        self._gates: dict[tuple, _Gate] = {}
        self._max = max_entries
        self._limit = max(1, limit)

    def get(self, key: tuple) -> _Gate:
        with self._lock:
            gate = self._gates.get(key)
            if gate is None:
                if len(self._gates) > self._max:
                    self._gates = {
                        k: g for k, g in self._gates.items() if g.in_use
                    }
                gate = self._gates.setdefault(key, _Gate(self._limit))
            return gate


@dataclass
class _Slot:
    inputs: Mapping[str, np.ndarray]
    rows: int
    done: threading.Event = field(default_factory=threading.Event)
    result: dict[str, np.ndarray] | None = None
    error: BaseException | None = None


@dataclass
class _Pending:
    slots: list[_Slot] = field(default_factory=list)
    rows: int = 0
    closed: bool = False                  # no further joiners


class MicroBatcher:
    def __init__(
        self,
        runtime: BaseRuntime,
        max_batch: int = 64,
        wait_timeout_s: float = 600.0,
        metrics=None,
        max_inflight: int = 4,
    ) -> None:
        self.runtime = runtime
        self.max_batch = max_batch
        # generous: a follower may sit behind the leader's cold jit compile
        self.wait_timeout_s = wait_timeout_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._pending: dict[tuple, _Pending] = {}
        self._gates = _GateMap(limit=max_inflight)
        # signature() results are static per loaded model — cache the derived
        # axis maps so the hot path doesn't rebuild spec dicts per request
        self._axes_cache: dict[ModelId, dict[str, int] | None] = {}
        self._out_axes_cache: dict[ModelId, dict[str, int | None]] = {}
        # observability
        self.batches = 0
        self.batched_requests = 0

    # -- key/axis helpers ---------------------------------------------------
    def _batch_axes(self, model_id: ModelId) -> dict[str, int] | None:
        """Input name -> axis index of its named "batch" axis; None when any
        input OR output lacks one. An output with no batch axis is reduced
        OVER the batch (a scalar score, a pooled aggregate): coalescing would
        compute it across other callers' rows — wrong answers and a
        cross-request leak — so such models always run solo."""
        with self._lock:
            if model_id in self._axes_cache:
                return self._axes_cache[model_id]
        input_spec, output_spec, _ = self.runtime.signature(model_id)
        axes: dict[str, int] | None = {}
        for name, spec in input_spec.items():
            ax = [i for i, n in spec.dynamic_axes() if n == "batch"]
            if not ax:
                axes = None
                break
            axes[name] = ax[0]
        if axes is not None:
            for spec in output_spec.values():
                if not any(n == "batch" for _, n in spec.dynamic_axes()):
                    axes = None
                    break
        out_axes: dict[str, int | None] = {}
        for name, spec in output_spec.items():
            batch_axes = [a for a, n in spec.dynamic_axes() if n == "batch"]
            out_axes[name] = batch_axes[0] if batch_axes else None
        with self._lock:
            if len(self._axes_cache) > 4096:  # bound growth across tenants
                self._axes_cache.clear()
                self._out_axes_cache.clear()
            self._axes_cache[model_id] = axes
            self._out_axes_cache[model_id] = out_axes
        return axes

    def _key(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        axes: Mapping[str, int],
        output_filter: list[str] | None,
    ) -> tuple | None:
        """Batchable only when every input's batch-axis row count agrees and
        all non-batch dims match across joiners (exact-shape coalescing)."""
        if set(inputs) != set(axes):
            return None  # wrong input set: let runtime.predict raise cleanly
        rows = None
        sig = []
        for name in sorted(inputs):
            arr = np.asarray(inputs[name])
            ax = axes.get(name)
            if ax is None or arr.ndim <= ax:
                return None
            if rows is None:
                rows = arr.shape[ax]
            elif arr.shape[ax] != rows:
                return None
            rest = tuple(d for i, d in enumerate(arr.shape) if i != ax)
            sig.append((name, str(arr.dtype), rest))
        return (model_id, tuple(sig), tuple(output_filter or ()))

    def _gate(self, key: tuple) -> _Gate:
        return self._gates.get(key)

    # -- core ---------------------------------------------------------------
    def predict(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        output_filter: list[str] | None = None,
    ) -> dict[str, np.ndarray]:
        axes = self._batch_axes(model_id)
        key = self._key(model_id, inputs, axes, output_filter) if axes else None
        if key is None:
            return self.runtime.predict(model_id, inputs, output_filter)

        first = sorted(inputs)[0]
        rows = int(np.asarray(inputs[first]).shape[axes[first]])
        if rows >= self.max_batch:
            # already at/over the cap on its own: run solo, never join a batch
            return self.runtime.predict(model_id, inputs, output_filter)
        slot = _Slot(inputs=inputs, rows=rows)
        with self._lock:
            pend = self._pending.get(key)
            if pend is not None and pend.rows + rows > self.max_batch:
                # max_batch is a hard cap: the full batch keeps its leader,
                # this request starts (and leads) a fresh one
                pend.closed = True
                self._pending.pop(key, None)
                pend = None
            leader = pend is None
            if leader:
                pend = _Pending()
                self._pending[key] = pend
            pend.slots.append(slot)
            pend.rows += rows
            if pend.rows >= self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
        if self.metrics is not None:
            self.metrics.batcher_queue_depth.labels("predict").inc()

        if not leader:
            if not slot.done.wait(self.wait_timeout_s):
                raise TimeoutError(f"batched predict for {model_id} timed out")
            if slot.error is not None:
                raise slot.error
            assert slot.result is not None
            return slot.result

        # Leader: acquire the per-key gate. If a previous batch is on the
        # device this blocks, and every arrival in the meantime joins OUR
        # pend — the accumulation window IS the device's busy time. On an
        # idle gate we pass straight through: no timed wait, no added
        # latency for sequential traffic.
        with self._gate(key):
            with self._lock:
                if not pend.closed:
                    pend.closed = True
                    self._pending.pop(key, None)
            slots = pend.slots
            # the batch leaves the queue for the device the moment its leader
            # holds the gate — success or failure, these are no longer queued
            if self.metrics is not None:
                self.metrics.batcher_queue_depth.labels("predict").dec(len(slots))
            try:
                if len(slots) == 1:
                    out = self.runtime.predict(model_id, slot.inputs, output_filter)
                    slot.result = out
                    return out
                with TRACER.span(
                    "microbatch", model=str(model_id), requests=len(slots), rows=pend.rows
                ):
                    cat = {
                        name: np.concatenate(
                            [np.asarray(s.inputs[name]) for s in slots], axis=axes[name]
                        )
                        for name in slots[0].inputs
                    }
                    out = self.runtime.predict(model_id, cat, output_filter)
                    self.batches += 1
                    self.batched_requests += len(slots)
                    if self.metrics is not None:
                        self.metrics.coalesced_batches.labels("predict").inc()
                        self.metrics.coalesced_requests.labels("predict").inc(len(slots))
                    self._scatter(model_id, slots, out)
                assert slot.result is not None
                return slot.result
            except BaseException as e:
                for s in slots:
                    if s is not slot and s.result is None and s.error is None:
                        s.error = e
                        s.done.set()
                raise
            finally:
                for s in slots:
                    if s is not slot:
                        s.done.set()

    def _scatter(self, model_id: ModelId, slots: list[_Slot], out: dict[str, np.ndarray]) -> None:
        """Split batched outputs back per caller by row ranges.

        `_batch_axes` guarantees every output of a batchable model declares a
        batch axis, so a missing axis or a batch-dim length that disagrees
        with the total row count means the model's spec lies about its actual
        output shape. That MUST fail the whole batch: silently handing each
        caller the full concatenated array would leak other callers' rows."""
        with self._lock:
            out_axes = dict(self._out_axes_cache.get(model_id, {}))
        offsets = []
        start = 0
        for s in slots:
            offsets.append((start, start + s.rows))
            start += s.rows

        for name, arr in out.items():
            ax = out_axes.get(name)
            a = np.asarray(arr)
            if ax is None or a.ndim <= ax or a.shape[ax] != start:
                raise ValueError(
                    f"batched output {name!r} of {model_id} has shape {a.shape}, "
                    f"expected batch axis {ax} of length {start}; refusing to "
                    f"scatter (would leak rows across requests)"
                )

        for i, s in enumerate(slots):
            lo, hi = offsets[i]
            s.result = {
                name: np.take(arr, range(lo, hi), axis=out_axes[name])
                for name, arr in out.items()
            }


@dataclass
class _GenSlot:
    ids: np.ndarray                       # (rows, s_i) int32 prompts
    lengths: np.ndarray                   # (rows,) true prompt lengths
    max_new: int
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    error: BaseException | None = None


@dataclass
class _GenPending:
    slots: list[_GenSlot] = field(default_factory=list)
    rows: int = 0
    closed: bool = False


class GenerateCoalescer:
    """Continuous batching for ``:generate`` — the verb LM clients actually
    call (VERDICT r2 next-round #8). Same gate design as MicroBatcher: the
    accumulation window is the device's own busy time, so sequential traffic
    pays nothing and saturating traffic coalesces into one prefill+decode
    program per batch.

    Coalescing key: (model, prompt-seq bucket, new-token bucket, temperature,
    top_k) — the runtime pads to the same buckets, so joiners share one
    compiled program; sampling params must match because one program invokes
    one (traced) temperature/top_k for every row. Requests with an explicit
    ``seed`` NEVER coalesce: their contract is a reproducible solo sample
    stream, which a shared batch draw would silently break.
    """

    def __init__(
        self,
        runtime: BaseRuntime,
        max_batch: int = 32,
        wait_timeout_s: float = 600.0,
        metrics=None,
        max_inflight: int = 2,
    ) -> None:
        self.runtime = runtime
        self.max_batch = max_batch
        self.wait_timeout_s = wait_timeout_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._pending: dict[tuple, _GenPending] = {}
        # generate programs run for seconds: 2 in flight overlaps host prep
        # with device decode without piling long jobs behind each other
        self._gates = _GateMap(limit=max_inflight)
        self.batches = 0
        self.batched_requests = 0

    def _gate(self, key: tuple) -> _Gate:
        return self._gates.get(key)

    def generate(
        self,
        model_id: ModelId,
        input_ids: np.ndarray,
        prompt_lengths: list[int] | None = None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int | None = None,
    ) -> np.ndarray:
        ids = np.asarray(input_ids, np.int32)
        family = getattr(self.runtime, "family_of", lambda _m: None)(model_id)
        if (
            seed is not None
            or ids.ndim != 2
            or ids.shape[0] >= self.max_batch
            or family != "transformer_lm"
        ):
            # seeded = reproducible solo; malformed shapes fall through so the
            # runtime raises its own clean error; capacity-routed families
            # (moe_lm) never co-batch — expert capacity is computed over the
            # whole flattened batch, so co-batched strangers would change
            # which of THIS request's tokens the router drops
            return self.runtime.generate(
                model_id, ids, prompt_lengths=prompt_lengths,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, seed=seed if seed is not None else secrets.randbits(31),
            )
        rows, s = ids.shape
        if prompt_lengths is None:
            lengths = np.full((rows,), s, np.int32)
        else:
            lengths = np.asarray(prompt_lengths, np.int32)
            if lengths.shape != (rows,) or (lengths < 1).any() or (lengths > s).any():
                # invalid per-request params must fail ONLY this request: run
                # solo so the runtime's clean error can't poison a batch of
                # innocent coalesced callers
                return self.runtime.generate(
                    model_id, ids, prompt_lengths=prompt_lengths,
                    max_new_tokens=max_new_tokens, temperature=temperature,
                    top_k=top_k, seed=secrets.randbits(31),
                )
        key = (
            model_id, _next_bucket(s), _next_bucket(max_new_tokens),
            float(temperature), int(top_k),
        )
        slot = _GenSlot(ids=ids, lengths=lengths, max_new=max_new_tokens)
        with self._lock:
            pend = self._pending.get(key)
            if pend is not None and pend.rows + rows > self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
                pend = None
            leader = pend is None
            if leader:
                pend = _GenPending()
                self._pending[key] = pend
            pend.slots.append(slot)
            pend.rows += rows
            if pend.rows >= self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
        if self.metrics is not None:
            self.metrics.batcher_queue_depth.labels("generate").inc()

        if not leader:
            if not slot.done.wait(self.wait_timeout_s):
                raise TimeoutError(f"batched generate for {model_id} timed out")
            if slot.error is not None:
                raise slot.error
            assert slot.result is not None
            return slot.result

        with self._gate(key):
            with self._lock:
                if not pend.closed:
                    pend.closed = True
                    self._pending.pop(key, None)
            slots = pend.slots
            if self.metrics is not None:
                self.metrics.batcher_queue_depth.labels("generate").dec(len(slots))
            try:
                if len(slots) == 1:
                    out = self.runtime.generate(
                        model_id, slot.ids, prompt_lengths=list(slot.lengths),
                        max_new_tokens=slot.max_new, temperature=temperature,
                        top_k=top_k, seed=secrets.randbits(31),
                    )
                    slot.result = out
                    return out
                with TRACER.span(
                    "generate_coalesce", model=str(model_id),
                    requests=len(slots), rows=pend.rows,
                ):
                    s_max = max(sl.ids.shape[1] for sl in slots)
                    cat = np.concatenate(
                        [
                            np.pad(sl.ids, ((0, 0), (0, s_max - sl.ids.shape[1])))
                            for sl in slots
                        ]
                    )
                    cat_len = np.concatenate([sl.lengths for sl in slots])
                    toks = self.runtime.generate(
                        model_id, cat, prompt_lengths=list(cat_len),
                        max_new_tokens=max(sl.max_new for sl in slots),
                        temperature=temperature, top_k=top_k,
                        seed=secrets.randbits(31),
                    )
                    self.batches += 1
                    self.batched_requests += len(slots)
                    if self.metrics is not None:
                        self.metrics.coalesced_batches.labels("generate").inc()
                        self.metrics.coalesced_requests.labels("generate").inc(len(slots))
                    lo = 0
                    for sl in slots:
                        hi = lo + sl.ids.shape[0]
                        sl.result = toks[lo:hi, : sl.max_new]
                        lo = hi
                assert slot.result is not None
                return slot.result
            except BaseException as e:
                for sl in slots:
                    if sl is not slot and sl.result is None and sl.error is None:
                        sl.error = e
                        sl.done.set()
                raise
            finally:
                for sl in slots:
                    if sl is not slot:
                        sl.done.set()

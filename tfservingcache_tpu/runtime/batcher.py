"""Continuous (pipelined) micro-batching for the in-process runtime.

Reference-parity rationale: the reference delegates request batching to TF
Serving's ``--enable_batching`` (the sidecar never sees tensors); with
inference in-process, the batcher moves here. TPU-first motivation: one
batched MXU dispatch amortizes per-call host->device overhead — the dominant
warm-path cost for small models — and a power-of-two padded batch keeps the
jit cache small (runtime._pad_to_bucket already buckets the batch axis).

Continuous-batching design (no timed window): batches for one
(model, non-batch shape, filter) key are serialized on a per-key gate. The
first arrival becomes the leader of the next batch and acquires the gate;
while a previous batch occupies the device, later arrivals keep joining the
leader's pending batch, and the moment the gate frees the batch closes and
runs as ONE runtime.predict, outputs split back by each caller's row count.
The accumulation window is therefore exactly the device's own busy time:

  - strictly sequential traffic acquires an uncontended gate and runs
    immediately — no timed wait is ever inserted (the added latency is the
    gate bookkeeping itself, small but not literally zero);
  - saturating traffic coalesces into device-call-sized batches without any
    window-length tuning (the classic latency/throughput knob dissolves).

Whether coalescing wins over independent dispatch is an empirical, shape-
dependent question — bench.py measures warm QPS batcher on vs off with
varied payloads; round 2's "batcher loses 31%" verdict was measured with
identical repeated payloads a transport cache could answer, so trust only
the varied-payload numbers.

Calls are thread-blocking by design — they arrive on the protocol backend's
executor threads (protocol/local_backend.py), never on the event loop.

Models whose inputs have no named "batch" axis fall through unbatched.
"""

from __future__ import annotations

import collections
import secrets
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

import numpy as np

from tfservingcache_tpu.runtime.base import (
    BaseRuntime,
    ModelNotLoadedError,
    RuntimeError_,
)
from tfservingcache_tpu.lab import faults as lab_faults
from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.accounting import LEDGER
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger
from tfservingcache_tpu.utils.tracing import TRACER, current_ids

log = get_logger("runtime.batcher")


# the coalescer predicts which runtime compile bucket a request lands in —
# it must be the runtime's own bucketing function, not a copy that can drift
from tfservingcache_tpu.runtime.model_runtime import next_bucket as _next_bucket


@lockchecked
class _Gate:
    """A counted gate admitting up to ``limit`` concurrent holders.

    One mutex per key (round-2 design) serialized ALL device calls for a
    model: with the device/transport busy for RTT seconds, at most one batch
    was ever in flight, while the unbatched path pipelines ``clients``
    independent calls through the transport — the batcher *lost* throughput
    on any link whose round-trip dominates device time (the r2 31% and the
    r3 preview's 3x REST regression). A bounded semaphore keeps the
    accumulate-while-busy behavior (leaders still block once ``limit``
    batches are in flight, and arrivals join the blocked leader's batch)
    while letting ``limit`` batches overlap host codec + transfer + compute."""

    _tpusc_guarded = {"in_use": "_count"}

    def __init__(self, limit: int) -> None:
        self._sem = threading.BoundedSemaphore(limit)
        self._count = threading.Lock()
        self.in_use = 0

    def __enter__(self) -> "_Gate":
        self._sem.acquire()
        with self._count:
            self.in_use += 1
        return self

    def __exit__(self, *exc) -> None:
        with self._count:
            self.in_use -= 1
        self._sem.release()


@lockchecked
class _GateMap:
    """Per-key device gates with bounded growth (shared by MicroBatcher and
    GenerateCoalescer): bound how many batches per key are in flight so
    arrivals during a saturated device accumulate into the next batch.
    Pruning keeps only in-use gates; losing an idle gate only costs a
    coalescing opportunity (or briefly exceeds the in-flight bound), never
    correctness."""

    _tpusc_guarded = {"_gates": "_lock"}

    def __init__(self, max_entries: int = 4096, limit: int = 4) -> None:
        self._lock = threading.Lock()
        self._gates: dict[tuple, _Gate] = {}
        self._max = max_entries
        self._limit = max(1, limit)

    def get(self, key: tuple) -> _Gate:
        with self._lock:
            gate = self._gates.get(key)
            if gate is None:
                if len(self._gates) > self._max:
                    self._gates = {
                        k: g for k, g in self._gates.items() if g.in_use
                    }
                gate = self._gates.setdefault(key, _Gate(self._limit))
            return gate


@dataclass
class _Slot:
    inputs: Mapping[str, np.ndarray]
    rows: int
    done: threading.Event = field(default_factory=threading.Event)
    result: dict[str, np.ndarray] | None = None
    error: BaseException | None = None


@dataclass
class _Pending:
    slots: list[_Slot] = field(default_factory=list)
    rows: int = 0
    closed: bool = False                  # no further joiners


@lockchecked
class MicroBatcher:
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_pending": "_lock",
        "_axes_cache": "_lock",
        "_out_axes_cache": "_lock",
    }

    def __init__(
        self,
        runtime: BaseRuntime,
        max_batch: int = 64,
        wait_timeout_s: float = 600.0,
        metrics=None,
        max_inflight: int = 4,
    ) -> None:
        self.runtime = runtime
        self.max_batch = max_batch
        # generous: a follower may sit behind the leader's cold jit compile
        self.wait_timeout_s = wait_timeout_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._pending: dict[tuple, _Pending] = {}
        self._gates = _GateMap(limit=max_inflight)
        # signature() results are static per loaded model — cache the derived
        # axis maps so the hot path doesn't rebuild spec dicts per request
        self._axes_cache: dict[ModelId, dict[str, int] | None] = {}
        self._out_axes_cache: dict[ModelId, dict[str, int | None]] = {}
        # observability
        self.batches = 0
        self.batched_requests = 0

    # -- key/axis helpers ---------------------------------------------------
    def _batch_axes(self, model_id: ModelId) -> dict[str, int] | None:
        """Input name -> axis index of its named "batch" axis; None when any
        input OR output lacks one. An output with no batch axis is reduced
        OVER the batch (a scalar score, a pooled aggregate): coalescing would
        compute it across other callers' rows — wrong answers and a
        cross-request leak — so such models always run solo."""
        with self._lock:
            if model_id in self._axes_cache:
                return self._axes_cache[model_id]
        input_spec, output_spec, _ = self.runtime.signature(model_id)
        axes: dict[str, int] | None = {}
        for name, spec in input_spec.items():
            ax = [i for i, n in spec.dynamic_axes() if n == "batch"]
            if not ax:
                axes = None
                break
            axes[name] = ax[0]
        if axes is not None:
            for spec in output_spec.values():
                if not any(n == "batch" for _, n in spec.dynamic_axes()):
                    axes = None
                    break
        out_axes: dict[str, int | None] = {}
        for name, spec in output_spec.items():
            batch_axes = [a for a, n in spec.dynamic_axes() if n == "batch"]
            out_axes[name] = batch_axes[0] if batch_axes else None
        with self._lock:
            if len(self._axes_cache) > 4096:  # bound growth across tenants
                self._axes_cache.clear()
                self._out_axes_cache.clear()
            self._axes_cache[model_id] = axes
            self._out_axes_cache[model_id] = out_axes
        return axes

    def _key(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        axes: Mapping[str, int],
        output_filter: list[str] | None,
    ) -> tuple | None:
        """Batchable only when every input's batch-axis row count agrees and
        all non-batch dims match across joiners (exact-shape coalescing)."""
        if set(inputs) != set(axes):
            return None  # wrong input set: let runtime.predict raise cleanly
        rows = None
        sig = []
        for name in sorted(inputs):
            arr = np.asarray(inputs[name])
            ax = axes.get(name)
            if ax is None or arr.ndim <= ax:
                return None
            if rows is None:
                rows = arr.shape[ax]
            elif arr.shape[ax] != rows:
                return None
            rest = tuple(d for i, d in enumerate(arr.shape) if i != ax)
            sig.append((name, str(arr.dtype), rest))
        return (model_id, tuple(sig), tuple(output_filter or ()))

    def _gate(self, key: tuple) -> _Gate:
        return self._gates.get(key)

    # -- core ---------------------------------------------------------------
    def predict(
        self,
        model_id: ModelId,
        inputs: Mapping[str, np.ndarray],
        output_filter: list[str] | None = None,
    ) -> dict[str, np.ndarray]:
        axes = self._batch_axes(model_id)
        key = self._key(model_id, inputs, axes, output_filter) if axes else None
        if key is None:
            return self.runtime.predict(model_id, inputs, output_filter)

        first = sorted(inputs)[0]
        rows = int(np.asarray(inputs[first]).shape[axes[first]])
        if rows >= self.max_batch:
            # already at/over the cap on its own: run solo, never join a batch
            return self.runtime.predict(model_id, inputs, output_filter)
        slot = _Slot(inputs=inputs, rows=rows)
        with self._lock:
            pend = self._pending.get(key)
            if pend is not None and pend.rows + rows > self.max_batch:
                # max_batch is a hard cap: the full batch keeps its leader,
                # this request starts (and leads) a fresh one
                pend.closed = True
                self._pending.pop(key, None)
                pend = None
            leader = pend is None
            if leader:
                pend = _Pending()
                self._pending[key] = pend
            pend.slots.append(slot)
            pend.rows += rows
            if pend.rows >= self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
        if self.metrics is not None:
            self.metrics.batcher_queue_depth.labels("predict").inc()

        if not leader:
            if not slot.done.wait(self.wait_timeout_s):
                raise TimeoutError(f"batched predict for {model_id} timed out")
            if slot.error is not None:
                raise slot.error
            assert slot.result is not None
            return slot.result

        # Leader: acquire the per-key gate. If a previous batch is on the
        # device this blocks, and every arrival in the meantime joins OUR
        # pend — the accumulation window IS the device's busy time. On an
        # idle gate we pass straight through: no timed wait, no added
        # latency for sequential traffic.
        with self._gate(key):
            with self._lock:
                if not pend.closed:
                    pend.closed = True
                    self._pending.pop(key, None)
            slots = pend.slots
            # the batch leaves the queue for the device the moment its leader
            # holds the gate — success or failure, these are no longer queued
            if self.metrics is not None:
                self.metrics.batcher_queue_depth.labels("predict").dec(len(slots))
            try:
                if len(slots) == 1:
                    out = self.runtime.predict(model_id, slot.inputs, output_filter)
                    slot.result = out
                    return out
                with TRACER.span(
                    "microbatch", model=str(model_id), requests=len(slots), rows=pend.rows
                ):
                    cat = {
                        name: np.concatenate(
                            [np.asarray(s.inputs[name]) for s in slots], axis=axes[name]
                        )
                        for name in slots[0].inputs
                    }
                    out = self.runtime.predict(model_id, cat, output_filter)
                    self.batches += 1
                    self.batched_requests += len(slots)
                    if self.metrics is not None:
                        self.metrics.coalesced_batches.labels("predict").inc()
                        self.metrics.coalesced_requests.labels("predict").inc(len(slots))
                    self._scatter(model_id, slots, out)
                assert slot.result is not None
                return slot.result
            except BaseException as e:
                for s in slots:
                    if s is not slot and s.result is None and s.error is None:
                        s.error = e
                        s.done.set()
                raise
            finally:
                for s in slots:
                    if s is not slot:
                        s.done.set()

    def _scatter(self, model_id: ModelId, slots: list[_Slot], out: dict[str, np.ndarray]) -> None:
        """Split batched outputs back per caller by row ranges.

        `_batch_axes` guarantees every output of a batchable model declares a
        batch axis, so a missing axis or a batch-dim length that disagrees
        with the total row count means the model's spec lies about its actual
        output shape. That MUST fail the whole batch: silently handing each
        caller the full concatenated array would leak other callers' rows."""
        with self._lock:
            out_axes = dict(self._out_axes_cache.get(model_id, {}))
        offsets = []
        start = 0
        for s in slots:
            offsets.append((start, start + s.rows))
            start += s.rows

        for name, arr in out.items():
            ax = out_axes.get(name)
            a = np.asarray(arr)
            if ax is None or a.ndim <= ax or a.shape[ax] != start:
                raise ValueError(
                    f"batched output {name!r} of {model_id} has shape {a.shape}, "
                    f"expected batch axis {ax} of length {start}; refusing to "
                    f"scatter (would leak rows across requests)"
                )

        for i, s in enumerate(slots):
            lo, hi = offsets[i]
            s.result = {
                name: np.take(arr, range(lo, hi), axis=out_axes[name])
                for name, arr in out.items()
            }


@dataclass
class _GenSlot:
    ids: np.ndarray                       # (rows, s_i) int32 prompts
    lengths: np.ndarray                   # (rows,) true prompt lengths
    max_new: int
    enqueue_t: float = field(default_factory=time.monotonic)
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    error: BaseException | None = None


@dataclass
class _GenPending:
    slots: list[_GenSlot] = field(default_factory=list)
    rows: int = 0
    closed: bool = False


@lockchecked
class GenerateCoalescer:
    """Continuous batching for ``:generate`` — the verb LM clients actually
    call (VERDICT r2 next-round #8). Same gate design as MicroBatcher: the
    accumulation window is the device's own busy time, so sequential traffic
    pays nothing and saturating traffic coalesces into one prefill+decode
    program per batch.

    Coalescing key: (model, prompt-seq bucket, new-token bucket, temperature,
    top_k) — the runtime pads to the same buckets, so joiners share one
    compiled program; sampling params must match because one program invokes
    one (traced) temperature/top_k for every row. Requests with an explicit
    ``seed`` NEVER coalesce: their contract is a reproducible solo sample
    stream, which a shared batch draw would silently break.
    """

    _tpusc_guarded = {"_pending": "_lock"}

    def __init__(
        self,
        runtime: BaseRuntime,
        max_batch: int = 32,
        wait_timeout_s: float = 600.0,
        metrics=None,
        max_inflight: int = 2,
    ) -> None:
        self.runtime = runtime
        self.max_batch = max_batch
        self.wait_timeout_s = wait_timeout_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._pending: dict[tuple, _GenPending] = {}
        # generate programs run for seconds: 2 in flight overlaps host prep
        # with device decode without piling long jobs behind each other
        self._gates = _GateMap(limit=max_inflight)
        self.batches = 0
        self.batched_requests = 0

    def _gate(self, key: tuple) -> _Gate:
        return self._gates.get(key)

    def generate(
        self,
        model_id: ModelId,
        input_ids: np.ndarray,
        prompt_lengths: list[int] | None = None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int | None = None,
    ) -> np.ndarray:
        ids = np.asarray(input_ids, np.int32)
        family = getattr(self.runtime, "family_of", lambda _m: None)(model_id)
        if ids.ndim == 2 and family == "transformer_lm":
            # oversized prompts must fail loudly AT SUBMIT (mirroring the
            # continuous engine): before this check they joined a pending
            # batch, the leader's drain raised for everyone, and joiners
            # saw only an opaque timeout after wait_timeout_s
            max_seq = getattr(
                self.runtime, "max_seq_of", lambda _m: None
            )(model_id)
            if max_seq is not None and ids.shape[1] + max_new_tokens > max_seq:
                raise ValueError(
                    f"prompt {ids.shape[1]} + max_new_tokens "
                    f"{max_new_tokens} exceeds max_seq {max_seq}"
                )
        if (
            seed is not None
            or ids.ndim != 2
            or ids.shape[0] >= self.max_batch
            or family != "transformer_lm"
        ):
            # seeded = reproducible solo; malformed shapes fall through so the
            # runtime raises its own clean error; capacity-routed families
            # (moe_lm) never co-batch — expert capacity is computed over the
            # whole flattened batch, so co-batched strangers would change
            # which of THIS request's tokens the router drops
            return self.runtime.generate(
                model_id, ids, prompt_lengths=prompt_lengths,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, seed=seed if seed is not None else secrets.randbits(31),
            )
        rows, s = ids.shape
        if prompt_lengths is None:
            lengths = np.full((rows,), s, np.int32)
        else:
            lengths = np.asarray(prompt_lengths, np.int32)
            if lengths.shape != (rows,) or (lengths < 1).any() or (lengths > s).any():
                # invalid per-request params must fail ONLY this request: run
                # solo so the runtime's clean error can't poison a batch of
                # innocent coalesced callers
                return self.runtime.generate(
                    model_id, ids, prompt_lengths=prompt_lengths,
                    max_new_tokens=max_new_tokens, temperature=temperature,
                    top_k=top_k, seed=secrets.randbits(31),
                )
        key = (
            model_id, _next_bucket(s), _next_bucket(max_new_tokens),
            float(temperature), int(top_k),
        )
        slot = _GenSlot(ids=ids, lengths=lengths, max_new=max_new_tokens)
        with self._lock:
            pend = self._pending.get(key)
            if pend is not None and pend.rows + rows > self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
                pend = None
            leader = pend is None
            if leader:
                pend = _GenPending()
                self._pending[key] = pend
            pend.slots.append(slot)
            pend.rows += rows
            if pend.rows >= self.max_batch:
                pend.closed = True
                self._pending.pop(key, None)
        if self.metrics is not None:
            self.metrics.batcher_queue_depth.labels("generate").inc()

        if not leader:
            if not slot.done.wait(self.wait_timeout_s):
                raise TimeoutError(f"batched generate for {model_id} timed out")
            if slot.error is not None:
                raise slot.error
            assert slot.result is not None
            return slot.result

        with self._gate(key):
            with self._lock:
                if not pend.closed:
                    pend.closed = True
                    self._pending.pop(key, None)
            slots = pend.slots
            if self.metrics is not None:
                self.metrics.batcher_queue_depth.labels("generate").dec(len(slots))
                # head-of-line stall, on the SAME metric the continuous
                # engine records its slot wait: decoding starts on every
                # joiner's behalf the moment its leader holds the gate
                now = time.monotonic()
                for sl in slots:
                    self.metrics.gen_admission_wait.labels("coalesce").observe(
                        max(0.0, now - sl.enqueue_t)
                    )
            try:
                if len(slots) == 1:
                    dev_t0 = time.monotonic()
                    out = self.runtime.generate(
                        model_id, slot.ids, prompt_lengths=list(slot.lengths),
                        max_new_tokens=slot.max_new, temperature=temperature,
                        top_k=top_k, seed=secrets.randbits(31),
                    )
                    dev_t1 = time.monotonic()
                    slot.result = out
                    wasted = self._observe_waste(model_id, [slot], slot.max_new)
                    self._finish_drain(
                        model_id, [slot], slot.max_new, dev_t0, dev_t1, wasted
                    )
                    return out
                with TRACER.span(
                    "generate_coalesce", model=str(model_id),
                    requests=len(slots), rows=pend.rows,
                ):
                    s_max = max(sl.ids.shape[1] for sl in slots)
                    cat = np.concatenate(
                        [
                            np.pad(sl.ids, ((0, 0), (0, s_max - sl.ids.shape[1])))
                            for sl in slots
                        ]
                    )
                    cat_len = np.concatenate([sl.lengths for sl in slots])
                    dev_t0 = time.monotonic()
                    toks = self.runtime.generate(
                        model_id, cat, prompt_lengths=list(cat_len),
                        max_new_tokens=max(sl.max_new for sl in slots),
                        temperature=temperature, top_k=top_k,
                        seed=secrets.randbits(31),
                    )
                    dev_t1 = time.monotonic()
                    self.batches += 1
                    self.batched_requests += len(slots)
                    if self.metrics is not None:
                        self.metrics.coalesced_batches.labels("generate").inc()
                        self.metrics.coalesced_requests.labels("generate").inc(len(slots))
                    lo = 0
                    for sl in slots:
                        hi = lo + sl.ids.shape[0]
                        sl.result = toks[lo:hi, : sl.max_new]
                        lo = hi
                    wasted = self._observe_waste(
                        model_id, slots, max(sl.max_new for sl in slots)
                    )
                    self._finish_drain(
                        model_id, slots, max(sl.max_new for sl in slots),
                        dev_t0, dev_t1, wasted,
                    )
                assert slot.result is not None
                return slot.result
            except BaseException as e:
                for sl in slots:
                    if sl is not slot and sl.result is None and sl.error is None:
                        sl.error = e
                        sl.done.set()
                raise
            finally:
                for sl in slots:
                    if sl is not slot:
                        sl.done.set()

    def _observe_waste(
        self, model_id: ModelId, slots: list[_GenSlot], batch_max_new: int
    ) -> int:
        """Post-hoc padded-step accounting: the batch's scan computed
        ``next_bucket(batch_max_new)`` decode steps for EVERY row, so a row
        that hit EOS (when the model declares one) or whose own max_new was
        below the batch's kept burning steps until the drain. An estimate —
        the runtime falls back to exact sizes on bucket overshoot — but the
        comparison the metric exists for (coalesce vs continuous on one
        workload) uses models/workloads where the bucket estimate is exact.
        Returns the wasted-step count (the flight ring records it too)."""
        eos = getattr(self.runtime, "eos_id_of", lambda _m: None)(model_id)
        steps = _next_bucket(batch_max_new)
        wasted = 0
        for sl in slots:
            if sl.result is None:
                continue
            for row in np.asarray(sl.result):
                useful = row.shape[0]
                if eos is not None:
                    hits = np.flatnonzero(row == eos)
                    if hits.size:
                        useful = int(hits[0]) + 1
                wasted += steps - useful
        if wasted > 0 and self.metrics is not None:
            self.metrics.gen_wasted_steps.labels("coalesce").inc(wasted)
        return wasted

    def _finish_drain(
        self,
        model_id: ModelId,
        slots: list[_GenSlot],
        batch_max_new: int,
        dev_t0: float,
        dev_t1: float,
        wasted: int,
    ) -> None:
        """Flight-ring entry + phase clocks for one batch drain. The
        coalescer's analogue of the continuous engine's chunk boundary:
        every member admits at gate acquisition and retires at the drain,
        so admitted == retired == the batch size. Phases: queue = gate
        stall (the same value gen_admission_wait observed), decode = the
        batched device call (prefill is not separable from decode inside
        the fused generate program), respond = scatter back to rows."""
        end_t = time.monotonic()
        rows = sum(sl.ids.shape[0] for sl in slots)
        # cost ledger: the batched device call's wall time lands on this
        # tenant as decode (prefill is fused into the generate program and
        # not separable); tokens_out excludes the padded-step waste.
        LEDGER.note_step(
            str(model_id), "coalesce",
            decode_s=max(0.0, dev_t1 - dev_t0),
            tokens_in=sum(
                sl.ids.shape[0] * sl.ids.shape[1] for sl in slots
            ),
            tokens_out=max(0, rows * _next_bucket(batch_max_new) - wasted),
        )
        RECORDER.record(
            str(model_id), "coalesce",
            step_ms=(dev_t1 - dev_t0) * 1e3,
            chunk=_next_bucket(batch_max_new),
            active=rows, admitted=len(slots), retired=len(slots),
            wasted=wasted,
        )
        ids_ctx = current_ids()
        for sl in slots:
            phases = {
                "queue": max(0.0, dev_t0 - sl.enqueue_t),
                "decode": dev_t1 - dev_t0,
                "respond": max(0.0, end_t - dev_t1),
            }
            if self.metrics is not None:
                for ph, v in phases.items():
                    # the coalescer predates priority classes: everything
                    # it serves is class=normal
                    self.metrics.observe_phase(ph, "coalesce", "normal", v)
            RECORDER.note_phases(
                str(model_id), "coalesce", phases,
                trace_id=ids_ctx[0] if ids_ctx else None,
            )
        TRACER.annotate_root(
            priority="normal",  # the coalescer has no priority classes
            # first token materializes when the whole batch lands
            ttft_ms=round(
                max(0.0, dev_t1 - min(sl.enqueue_t for sl in slots)) * 1e3, 3
            ),
            phase_queue_ms=round(
                max(0.0, dev_t0 - min(sl.enqueue_t for sl in slots)) * 1e3, 3
            ),
            phase_decode_ms=round((dev_t1 - dev_t0) * 1e3, 3),
            phase_respond_ms=round(max(0.0, end_t - dev_t1) * 1e3, 3),
        )


# priority classes for the continuous engine's SLO-aware admission
# (REST/gRPC `priority`, default normal): rank order is what admission and
# preemption compare — smaller rank wins pages
_PRIORITY_RANKS = {"high": 0, "normal": 1, "low": 2}


@dataclass
class _ContinuousReq:
    """One ROW of a continuous generate (multi-row requests split into
    per-row units so each row admits and retires independently)."""

    prompt: np.ndarray                    # (P,) true prompt tokens
    max_new: int
    temperature: float
    top_k: int
    enqueue_t: float = field(default_factory=time.monotonic)
    done: threading.Event = field(default_factory=threading.Event)
    tokens: list[int] = field(default_factory=list)
    error: BaseException | None = None
    admitted_t: float | None = None
    first_tok_t: float | None = None
    finish_t: float | None = None
    prefix_hit: bool = False
    prefill_s: float = 0.0                # slot_prefill wall time (phase clock)
    # crash-recovery budget consumed (scheduler-thread only): each engine
    # crash that requeues this row bumps it; past the engine's
    # max_recoveries the row fails instead — a prompt that deterministically
    # crashes the engine must not respawn scheduler threads forever
    recoveries: int = 0
    # conversation KV lifecycle (ISSUE 18): rows carrying a conversation id
    # park their decode state at retirement and resume from a parked
    # ancestor at admission (suffix-only prefill). None = park/resume off
    # for this row.
    conversation_id: str | None = None
    # tokens actually run through prefill across this row's life (every
    # admission, including crash-recovery replays) — the O(new tokens)
    # evidence surface: a resumed row's total stays ~suffix-sized where a
    # cold replay pays the whole history again
    prefill_tokens: int = 0
    # SLO-aware engine (ISSUE 19). priority class -> rank (high=0, normal=1,
    # low=2); admission picks min (rank, seq), so all-normal traffic
    # degenerates to today's exact FIFO (seq is engine-monotonic and
    # survives preemption/crash requeues).
    priority: str = "normal"
    rank: int = 1
    seq: int = 0
    # per-token stream callback (single-row requests only; exceptions are
    # swallowed once and the callback dropped — a broken client must not
    # kill the scheduler thread)
    on_token: Callable[[int], None] | None = None
    # times this row was preempted off a lane (bounded by
    # engine.preempt_limit so a page-starved class can't be parked forever)
    preemptions: int = 0
    # ParkedConversation from a preemption park — checked at re-admission
    # BEFORE the conversation tier, giving the O(new tokens) resume without
    # requiring the row to carry a conversation_id
    preempt_parked: Any = None
    # chunked-prefill carry (serving.prefill_chunk_tokens > 0): tokens of
    # pf_prompt written so far (None = not PREFILLING), the full prompt
    # being written (includes crash-recovered emitted tokens), and the
    # first-token seed drawn at admission
    pf_pos: int | None = None
    pf_prompt: np.ndarray | None = None
    pf_seed: int = 0


@lockchecked
class _ContinuousScheduler:
    """One model's decode loop: a dedicated thread that admits pending rows
    into free slot lanes at chunk boundaries, dispatches the compiled
    decode-chunk program over the slot array, and retires rows the moment
    they hit EOS or their own max_new_tokens — freeing the lane for the
    next pending row instead of waiting for a batch drain."""

    _tpusc_guarded = {"pending": "cv", "stopped": "cv"}

    def __init__(self, engine: "ContinuousGenerateEngine", model_id: ModelId) -> None:
        self.engine = engine
        self.model_id = model_id
        self.cv = threading.Condition()
        self.pending: collections.deque[_ContinuousReq] = collections.deque()
        self.stopped = False
        # speculative decoding (ISSUE 16): set when the configured draft
        # pair turned out structurally incompatible (family/vocab/dense) —
        # permanent for this scheduler, so the warning logs once and every
        # later boundary decodes plain without re-raising. Scheduler-thread
        # only, like `lanes`/`state`.
        self._spec_broken = False
        self.thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"tpusc-cdecode-{model_id.name}",
        )
        self.thread.start()

    def submit(self, reqs: list[_ContinuousReq]) -> None:
        with self.cv:
            if self.stopped:
                raise RuntimeError_("continuous generate engine is closed")
            self.pending.extend(reqs)
            if self.engine.metrics is not None:
                self.engine.metrics.batcher_queue_depth.labels("generate").inc(
                    len(reqs)
                )
            self.cv.notify()

    def _fail(self, reqs: list[_ContinuousReq], err: BaseException) -> None:
        for r in reqs:
            if r.error is None and not r.done.is_set():
                r.error = err
                r.done.set()

    def _triage(
        self,
        inflight: list[_ContinuousReq],
        queued: list[_ContinuousReq],
        err: BaseException,
    ) -> list[_ContinuousReq]:
        """Crash triage: split casualties into survivors (requeued into the
        replacement scheduler — interrupted rows first, so FIFO order is
        preserved across the respawn) and doomed rows (recovery off, or past
        the per-row recovery budget). Each survivor counts once in
        ``tpusc_requests_recovered_total`` — reason ``mid_decode`` for rows
        whose partial decode is re-prefilled, ``queued`` for rows that only
        change queues."""
        eng = self.engine
        if queued and eng.metrics is not None:
            # the drained rows' queue-depth contribution: survivors re-count
            # at re-submit, so without this the gauge double-counts them
            # (and doomed rows would leak it forever)
            eng.metrics.batcher_queue_depth.labels("generate").dec(len(queued))
        if not eng.recovery:
            self._fail(inflight + queued, err)
            return []
        survivors: list[_ContinuousReq] = []
        doomed: list[_ContinuousReq] = []
        for reason, rows in (("mid_decode", inflight), ("queued", queued)):
            for r in rows:
                if r.done.is_set():
                    continue
                r.recoveries += 1
                if r.recoveries > eng.max_recoveries:
                    doomed.append(r)
                    continue
                # a row caught mid chunked-prefill restarts from chunk 0 on
                # the fresh scheduler (the crashed state's pages are gone);
                # stale carry would make re-admission treat it as PREFILLING
                r.pf_pos = None
                r.pf_prompt = None
                survivors.append(r)
                if eng.metrics is not None:
                    eng.metrics.requests_recovered.labels(reason).inc()
        if doomed:
            self._fail(doomed, err)
        return survivors

    def _resolve_draft_id(self, rt, name: str) -> ModelId | None:
        """Map the spec_draft_model knob ("name" or "name@version") to a
        RESIDENT ModelId, newest version first for a bare name. None when
        nothing resident matches — the scheduler just retries next boundary
        (the backend ensure-loads the draft on the generate path, so the
        first boundary after that load attaches)."""
        if "@" in name:
            base, _, ver = name.rpartition("@")
            try:
                want = ModelId(base, int(ver))
            except ValueError:
                return None
            return want if rt.is_loaded(want) else None
        best = None
        for mid in rt.resident_models():
            if mid.name == name and (best is None or mid.version > best.version):
                best = mid
        return best

    def _spec_setup(self, rt, state, lanes) -> None:
        """Attach (or detach) the configured draft model on this scheduler's
        slot state. Attach only happens with every lane idle: rows admitted
        while the draft is attached reserve + prefill BOTH arenas, so a
        mid-flight attach would leave live lanes with no draft pages and the
        draft-side page census would see active lanes mapping trash."""
        eng = self.engine
        st_draft = getattr(state, "spec_draft", None)
        if st_draft is not None:
            # keep the pair only while the draft stays resident; on
            # eviction detach and fall back to plain chunks (re-attach
            # happens at the next all-idle boundary if it reloads)
            if not rt.is_loaded(state.spec_draft_id):
                state.spec_draft = None
                state.spec_draft_id = None
                state.spec_tokens = 0
            return
        if self._spec_broken or state is None:
            return
        if not getattr(state, "paged", False):
            return
        if not hasattr(rt, "slot_attach_draft"):
            return
        name = eng.spec_draft_model
        if name is None:
            name = str(
                getattr(getattr(rt, "cfg", None), "spec_draft_model", "") or ""
            )
        if not name:
            return
        if any(l is not None for l in lanes):
            return
        draft_id = self._resolve_draft_id(rt, name)
        if draft_id is None or draft_id == self.model_id:
            return
        spec = eng.spec_tokens
        if spec is None:
            spec = int(getattr(getattr(rt, "cfg", None), "spec_tokens", 4) or 4)
        try:
            rt.slot_attach_draft(state, draft_id, spec)
            log.info(
                "continuous spec attach model=%s draft=%s spec_tokens=%d",
                self.model_id, draft_id, state.spec_tokens,
            )
        except ModelNotLoadedError:
            # evicted between resolve and attach: transient, retry later
            pass
        except RuntimeError_ as e:
            self._spec_broken = True
            log.warning(
                "continuous spec disabled model=%s draft=%s: %s",
                self.model_id, draft_id, e,
            )

    def _loop(self) -> None:
        rt = self.engine.runtime
        lanes: list[_ContinuousReq | None] = [None] * self.engine.slots
        state = None
        while True:
            with self.cv:
                while (
                    not self.pending
                    and not any(l is not None for l in lanes)
                    and not self.stopped
                ):
                    self.cv.wait()
                if self.stopped:
                    doomed = [l for l in lanes if l is not None]
                    doomed += list(self.pending)
                    self.pending.clear()
                    break
            try:
                state = self._step(rt, state, lanes)
            except BaseException as e:  # noqa: BLE001 - triage the in-flight rows
                # eviction mid-decode (ModelNotLoadedError) or a device
                # failure: the slot state may hold poisoned K/V, so it is
                # always dropped. With recovery on (the default), in-flight
                # and queued rows move to a FRESH scheduler thread where
                # admission re-prefills prompt + tokens-emitted-so-far —
                # the prefix cache makes the replay cheap and greedy streams
                # stay token-identical. Rows past their recovery budget, and
                # every row when recovery is off, get the error as before.
                with self.cv:
                    inflight = [l for l in lanes if l is not None]
                    queued = list(self.pending)
                    self.pending.clear()
                lanes = [None] * self.engine.slots
                survivors = self._triage(inflight, queued, e)
                RECORDER.dump(
                    "engine_crash", model=str(self.model_id),
                    error=repr(e),
                    failed_rows=len(inflight) + len(queued) - len(survivors),
                    recovered_rows=len(survivors),
                )
                try:
                    rt.drop_slot_state(self.model_id)
                except Exception:  # noqa: BLE001 - best-effort cleanup
                    pass
                state = None
                self.engine._set_active(self.model_id, 0)
                self.engine._set_pages(self.model_id, 0, 0)
                if survivors:
                    if self.engine._respawn(self, survivors) is not None:
                        # the replacement scheduler owns the model (and the
                        # survivors) from here; this thread is done
                        return
                    # engine closing mid-crash: nowhere to requeue
                    self._fail(survivors, e)
        self._fail(doomed, RuntimeError_("continuous generate engine closed"))
        self.engine._set_active(self.model_id, 0)
        self.engine._set_pages(self.model_id, 0, 0)

    def _step(self, rt, state, lanes):
        """One chunk boundary: admit into free lanes, then advance all
        active lanes by one compiled chunk. Called only from self.thread."""
        eng = self.engine
        # scenario-lab hook (lab/faults.py): kill_engine raises here — the
        # same path an organic device failure takes through _loop's triage —
        # and freeze_scheduler sleeps this thread, aging the queue. Disarmed
        # (every production default) this is one bool read.
        lab_faults.fire("engine_step", model=str(self.model_id))
        step_t0 = time.monotonic()
        eos = getattr(rt, "eos_id_of", lambda _m: None)(self.model_id)
        free = [i for i, l in enumerate(lanes) if l is None]
        if state is not None:
            # draft attach/detach happens at the boundary, before admission,
            # so every row admitted below sees the final spec configuration
            # (page budgets include draft headroom iff the draft is on)
            self._spec_setup(rt, state, lanes)
        admitted_any = False
        admitted_n = 0
        retired_n = 0
        prefix_hits_n = 0
        prefill_s_sum = 0.0
        tokens_in_n = 0
        while free:
            with self.cv:
                if not self.pending:
                    break
                # admission orders by (priority rank, submit seq): strict
                # class precedence, FIFO inside a class. With every queued
                # row the same class this is min-seq = the leftmost row —
                # exactly the old popleft, so priority-free traffic keeps
                # its byte-identical admission order. O(n) scan; the queue
                # is bounded by client concurrency.
                best = 0
                for qi in range(1, len(self.pending)):
                    r = self.pending[qi]
                    b = self.pending[best]
                    if (r.rank, r.seq) < (b.rank, b.seq):
                        best = qi
                req = self.pending[best]
                del self.pending[best]
                if eng.metrics is not None:
                    eng.metrics.batcher_queue_depth.labels("generate").dec()
            reserved_idx = None
            d_st = None
            d_pk = d_pv = None
            try:
                if state is None:
                    if eng.page_tokens is None and \
                            eng.share_prefix_bytes is None and \
                            eng.arena_dtype is None and \
                            eng.paged_kernel is None:
                        # no engine-level override: the runtime's ServingConfig
                        # decides (and stub runtimes keep their 2-arg surface)
                        state = rt.slot_decode_state(self.model_id, eng.slots)
                    else:
                        kw = {}
                        if eng.page_tokens is not None:
                            kw["page_tokens"] = eng.page_tokens
                            kw["arena_pages"] = eng.arena_pages
                        if eng.share_prefix_bytes is not None:
                            kw["share_prefix_bytes"] = eng.share_prefix_bytes
                        if eng.arena_dtype is not None:
                            kw["arena_dtype"] = eng.arena_dtype
                        if eng.paged_kernel is not None:
                            kw["paged_kernel"] = eng.paged_kernel
                        state = rt.slot_decode_state(
                            self.model_id, eng.slots, **kw
                        )
                    # fresh state: every lane is idle, so the draft (if
                    # configured and resident) can attach right away
                    self._spec_setup(rt, state, lanes)
                d_st = getattr(state, "spec_draft", None)
                prompt = req.prompt
                remaining = req.max_new - len(req.tokens)
                if req.tokens:
                    # crash-recovered row (tokens were emitted before the
                    # old scheduler died): re-prefill prompt + emitted
                    # tokens, so the next sampled token continues the stream
                    # exactly where it broke — greedy output is identical to
                    # an uninterrupted decode, and a shared-prefix hit on
                    # the original prompt makes the replay cheap
                    prompt = np.concatenate(
                        [prompt, np.asarray(req.tokens, np.int32)]
                    )
                p = prompt.shape[0]
                if p + remaining > state.max_seq:
                    req.error = RuntimeError_(
                        f"prompt {p} + max_new_tokens {remaining} exceeds "
                        f"max_seq {state.max_seq}"
                    )
                    req.done.set()
                    continue
                plan = None
                kind = None
                resume = None   # (parked, covered, n_pages) when resuming
                share = getattr(state, "prefix_index", None) is not None
                if getattr(state, "paged", False):
                    # admission is gated on free PAGES, not just free lanes:
                    # the row's whole prompt + max_new budget is reserved up
                    # front so a mid-decode row can never starve for a page.
                    # With a draft attached the budget grows by spec_tokens
                    # of headroom — a verify round started one token short
                    # of max_new still writes K/V rows at pos..pos+spec, and
                    # those writes must land on pages this row owns (never
                    # shared/trash), so the overshoot is reserved up front
                    # and handed back through release_pages at retirement.
                    headroom = state.spec_tokens if d_st is not None else 0
                    budget = min(p + remaining + headroom,
                                 state.pages_per_slot * state.page_tokens)
                    need = state.pages_needed(budget)
                    if need > state.arena_pages:
                        req.error = RuntimeError_(
                            f"request needs {need} KV pages "
                            f"({budget} tokens) but the arena has only "
                            f"{state.arena_pages}"
                        )
                        req.done.set()
                        continue
                    idx = free[-1]  # the lane free.pop() will hand out below
                    shared_pages = ()
                    cow_headroom = 0
                    if req.preempt_parked is not None and \
                            hasattr(rt, "plan_conversation_resume"):
                        # preempted row coming back: its own parked pages
                        # beat both the conversation tier and the radix
                        # index — they cover prompt + every emitted token,
                        # so the resume prefill is O(1) (the single row the
                        # park could not cover)
                        rplan = rt.plan_conversation_resume(
                            state, prompt, req.preempt_parked
                        )
                        if rplan is not None:
                            resume = (req.preempt_parked, rplan[0], rplan[1])
                    if resume is None and req.conversation_id and \
                            eng.conversation_tier is not None and \
                            hasattr(rt, "plan_conversation_resume"):
                        # resume beats cold prefill AND the shared-prefix
                        # plan: parked pages cover the whole history (prompt
                        # + prior turns' emitted tokens), where the radix
                        # index at best covers what is still arena-resident.
                        # The lookup PEEKS, so a lane that crashes mid-decode
                        # can resume again from the same ancestor.
                        parked, _outcome = eng.conversation_tier.get(
                            req.conversation_id, str(self.model_id)
                        )
                        if parked is not None:
                            rplan = rt.plan_conversation_resume(
                                state, prompt, parked
                            )
                            if rplan is not None:
                                resume = (parked, rplan[0], rplan[1])
                    if share and resume is None:
                        plan = rt.shared_prefix_plan(state, prompt)
                        if plan is not None:
                            # map the indexed prefix read-only; reserve only
                            # the private remainder. An exact hit with a
                            # mid-page tail also needs one CoW page in hand
                            # — its first decode write lands in the shared
                            # boundary page.
                            shared_pages = plan.mapped_pages()
                            if plan.kind == "exact" and plan.tail_len > 0:
                                cow_headroom = 1
                    ok = state.reserve_pages(
                        idx, budget, shared_pages, cow_headroom
                    )
                    if not ok and share:
                        # page pressure: cold index-only prefix pages must
                        # lose the fight to a live admission (protecting the
                        # plan's own mapped pages), else sharing would turn
                        # the blocks-never-fails queue into a deadlock
                        want = (max(0, need - len(shared_pages)) + cow_headroom
                                - len(state.free_pages))
                        if want > 0 and rt.reclaim_prefix_pages(
                            state, want, shared_pages
                        ):
                            ok = state.reserve_pages(
                                idx, budget, shared_pages, cow_headroom
                            )
                    if ok and d_st is not None:
                        # the draft arena mirrors the reservation (its rows
                        # for pos..pos+spec are written every round). No
                        # shared pages: the draft state has no prefix index,
                        # every draft page is private by construction. The
                        # cap keeps a shorter draft max_seq from deadlocking
                        # (the auto-sized draft arena always covers slots x
                        # pages_per_slot, so a capped reservation succeeds
                        # whenever the lane itself is free).
                        d_budget = min(
                            budget, d_st.pages_per_slot * d_st.page_tokens
                        )
                        if not d_st.reserve_pages(idx, d_budget):
                            state.release_pages(idx)
                            ok = False
                    if not ok and hasattr(rt, "park_lane"):
                        # priority preemption (ISSUE 19): a higher-class
                        # arrival that still can't reserve parks the
                        # lowest-class decoding lane's KV (pages are COPIES
                        # through the PR 18 codec, so the conservation
                        # census stays exact), requeues it for an
                        # O(new tokens) parked-KV resume, and retries the
                        # reservation. One victim may not free enough —
                        # keep hunting until the reserve succeeds or no
                        # preemptible lane remains.
                        while not ok:
                            vidx = self._pick_victim(lanes, req)
                            if vidx is None or not self._preempt(
                                rt, state, lanes, vidx
                            ):
                                break
                            # the victim's lane frees too — at the FRONT of
                            # the free list, so free[-1] (the lane reserved
                            # as `idx` above) is untouched
                            free.insert(0, vidx)
                            ok = state.reserve_pages(
                                idx, budget, shared_pages, cow_headroom
                            )
                            if ok and d_st is not None:
                                d_budget = min(
                                    budget,
                                    d_st.pages_per_slot * d_st.page_tokens,
                                )
                                if not d_st.reserve_pages(idx, d_budget):
                                    state.release_pages(idx)
                                    ok = False
                                    break
                    if not ok:
                        # arena exhausted: the queue BLOCKS, never fails —
                        # the row goes back to the FRONT (FIFO preserved)
                        # and retirements below recycle pages for the next
                        # chunk boundary's retry. Can't deadlock: with no
                        # active lanes every page is free or reclaimable
                        # from the prefix index, and need <= arena_pages
                        # was checked above.
                        with self.cv:
                            self.pending.appendleft(req)
                            if eng.metrics is not None:
                                eng.metrics.batcher_queue_depth.labels(
                                    "generate"
                                ).inc()
                        RECORDER.dump(
                            "page_exhaustion", model=str(self.model_id),
                            needed_pages=need, free_pages=len(state.free_pages),
                            arena_pages=state.arena_pages,
                        )
                        break
                    reserved_idx = idx
                pf0 = time.monotonic()
                seed = secrets.randbits(31)
                if (
                    reserved_idx is not None
                    and eng.prefill_chunk_tokens > 0
                    and resume is None and plan is None and d_st is None
                    and p > eng.prefill_chunk_tokens
                    and hasattr(rt, "slot_prefill_chunk")
                ):
                    # chunked-prefill interleaving (ISSUE 19): pages are
                    # reserved but NOTHING is written yet — the lane enters
                    # its PREFILLING state and _prefill_phase advances it
                    # one fixed-size chunk per boundary while other lanes
                    # keep decoding between chunks. pos holds the past-
                    # reservation sentinel so the decode chunk's frozen
                    # rewrite of this inactive lane hits the trash-page
                    # redirect, never the reserved rows the chunks fill.
                    # Resume/shared hits and spec-draft engines keep the
                    # single-dispatch path (their prefill is already the
                    # short suffix, or the draft arena must mirror it).
                    idx = free.pop()
                    req.pf_prompt = prompt
                    req.pf_pos = 0
                    req.pf_seed = seed
                    now = time.monotonic()
                    req.admitted_t = now
                    state.active[idx] = False
                    state.pos[idx] = state.pages_per_slot * state.page_tokens
                    state.temps[idx] = req.temperature
                    state.topks[idx] = req.top_k
                    lanes[idx] = req
                    eng.admitted += 1
                    admitted_any = True
                    admitted_n += 1
                    if eng.metrics is not None:
                        eng.metrics.gen_admission_wait.labels(
                            "continuous"
                        ).observe(max(0.0, now - req.enqueue_t))
                    continue
                if resume is not None and reserved_idx is not None:
                    # O(new tokens) turn resume: parked pages re-import into
                    # the lane's private reservation, only the suffix past
                    # the common history prefix runs through prefill
                    tok, pk, pv, last = rt.slot_resume_prefill(
                        self.model_id, state, reserved_idx, prompt,
                        resume[0], resume[1], resume[2],
                        req.temperature, req.top_k, seed,
                    )
                    kind = "resume"
                    hit = True
                    req.preempt_parked = None
                elif share:
                    tok, pk, pv, kind, last = rt.slot_prefill_shared(
                        self.model_id, state, prompt, req.temperature,
                        req.top_k, seed, plan,
                    )
                    hit = kind != "miss"
                else:
                    tok, pk, pv, hit = rt.slot_prefill(
                        self.model_id, prompt, req.temperature,
                        req.top_k, seed=seed,
                    )
                    last = None
                if d_st is not None and reserved_idx is not None:
                    # greedy draft prefill (temperature 0, sampled token
                    # ignored — only the draft's K/V rows matter). Runs even
                    # on an exact target prefix hit: the draft arena has no
                    # prefix index to skip into.
                    _, d_pk, d_pv, _ = rt.slot_prefill(
                        state.spec_draft_id, prompt, 0.0, 0, seed=seed,
                    )
            except BaseException as e:  # noqa: BLE001
                # the req is already out of `pending` and not yet in `lanes`
                # — without this the _loop doom sweep would miss it and its
                # waiter would block until timeout
                if reserved_idx is not None:
                    state.release_pages(reserved_idx)
                    if d_st is not None:
                        d_st.release_pages(reserved_idx)
                self._fail([req], e)
                raise
            now = time.monotonic()
            req.prefill_s = now - pf0
            req.admitted_t = now
            if req.first_tok_t is None:
                # a recovered row keeps its ORIGINAL first-token stamp —
                # TTFT is a client-experienced clock, and the client saw
                # its first token before the crash
                req.first_tok_t = now
            req.prefix_hit = hit
            self._emit(req, int(tok))
            if kind == "exact":
                pass  # zero prefill compute
            elif kind == "resume":
                req.prefill_tokens += p - resume[1]
            elif kind == "shared":
                req.prefill_tokens += p - plan.covered
            else:
                req.prefill_tokens += p
            eng.admitted += 1
            admitted_any = True
            admitted_n += 1
            prefill_s_sum += req.prefill_s
            tokens_in_n += p
            if hit:
                prefix_hits_n += 1
                if eng.metrics is not None:
                    # exact = radix full-skip (zero prefill compute);
                    # resume = parked-conversation re-import (suffix-only
                    # prefill over re-imported pages); shared = radix
                    # partial hit AND legacy dense-cache reuse (both paid
                    # only a suffix prefill)
                    eng.metrics.gen_prefix_hits.labels(
                        "continuous",
                        kind if kind in ("exact", "resume") else "shared",
                    ).inc()
            if eng.metrics is not None:
                eng.metrics.gen_admission_wait.labels("continuous").observe(
                    max(0.0, now - req.enqueue_t)
                )
            if (eos is not None and int(tok) == eos) or remaining <= 1:
                # done at prefill: the lane was never consumed
                if reserved_idx is not None:
                    self._retire_pages(state, reserved_idx, req)
                req.finish_t = now
                req.done.set()
                retired_n += 1
                continue
            idx = free.pop()
            if pk is None:
                # exact shared-prefix hit: the prompt's K/V already lives in
                # the mapped pages — nothing to insert. Its first decode
                # write (pos = p) lands mid-way into the SHARED boundary
                # page, so that one page is CoW'd now, while the headroom
                # page reserved for it is guaranteed free (same scheduler
                # turn, nothing ran in between).
                if plan is not None and plan.tail_len > 0:
                    rt.slot_cow(state, idx, plan.n_full)
            elif kind == "resume":
                # suffix-only insert over the re-imported pages: rows below
                # the resume boundary already hold the parked bytes (the
                # lane owns them privately — no trash redirect needed for
                # correctness, but the suffix prefill only produced junk
                # there, same as the shared case)
                rt.slot_admit(state, idx, pk, pv, base_tokens=resume[1])
            elif plan is not None and kind == "shared":
                # suffix-only insert: rows below the shared boundary stay in
                # the read-only mapped pages, the jit redirects them to trash
                rt.slot_admit(state, idx, pk, pv, base_tokens=plan.covered)
            else:
                rt.slot_admit(state, idx, pk, pv)
            if share and pk is not None:
                # publish this lane's prompt pages so later same-prefix
                # admissions share them (exact hits are already indexed)
                rt.shared_prefix_publish(state, idx, prompt, last)
            if d_pk is not None:
                # the draft lane rides the same index: its prompt K/V lands
                # on the pages reserved above, all private
                rt.slot_admit(d_st, idx, d_pk, d_pv)
            state.tok[idx] = int(tok)
            state.pos[idx] = p
            state.active[idx] = True
            state.temps[idx] = req.temperature
            state.topks[idx] = req.top_k
            lanes[idx] = req
        if admitted_any:
            eng._set_active(
                self.model_id, sum(l is not None for l in lanes)
            )
        pf_chunks = 0
        if eng.prefill_chunk_tokens > 0 and state is not None:
            # chunked-prefill interleave: every PREFILLING lane advances
            # exactly ONE chunk per boundary, so a long prompt's prefill is
            # spread across boundaries instead of monopolizing one dispatch
            pf_chunks, pf_toks, pf_s, pf_retired = self._prefill_phase(
                rt, state, lanes, eos
            )
            retired_n += pf_retired
            prefill_s_sum += pf_s
            tokens_in_n += pf_toks
            if pf_retired:
                eng._set_active(
                    self.model_id, sum(l is not None for l in lanes)
                )
        self._update_page_gauge(state)
        if not any(l is not None and l.pf_pos is None for l in lanes):
            if admitted_n or retired_n or pf_chunks:
                # prefill-only boundary (every admitted row finished at its
                # first token, or every occupied lane is still PREFILLING):
                # still a ring entry, with no chunk dispatched
                self._record_step(
                    state, 0, 0, admitted_n, retired_n, 0, step_t0,
                    prefix_hits_n, prefill_s_sum, tokens_in_n,
                )
            return state
        # chunk clamped to the pow2 cover of the largest remaining budget:
        # when every active row needs < chunk_tokens more, a smaller
        # compiled chunk (log2-bounded program count) trims the overshoot.
        # PREFILLING lanes are excluded everywhere below — the decode jit
        # freezes them (active=False) and their emit rows are junk.
        max_remaining = max(
            l.max_new - len(l.tokens)
            for l in lanes if l is not None and l.pf_pos is None
        )
        chunk = max(1, min(eng.chunk_tokens, _next_bucket(max_remaining)))
        active_rows = sum(
            l is not None and l.pf_pos is None for l in lanes
        )
        d_st = getattr(state, "spec_draft", None)
        use_spec = (
            d_st is not None
            and rt.is_loaded(state.spec_draft_id)
            and getattr(rt, "_spec_admit", lambda *_a: False)(
                self.model_id, state.spec_draft_id
            )
            # a round with zero greedy lanes is pure draft overhead (every
            # sampled row forces accept=1), so it falls back to plain decode
            and any(
                l is not None and float(state.temps[i]) <= 0.0
                for i, l in enumerate(lanes)
            )
        )
        spec_span = state.spec_tokens if use_spec else 0
        if getattr(state, "paged", False) and \
                getattr(state, "page_refs", None) is not None:
            # copy-on-write safety net: no lane may write into a page it
            # doesn't solely own. Admission already CoW'd the only shareable
            # write target (the exact-hit boundary page) and a chunk only
            # advances into the lane's own private reservation, so this
            # never fires in the designed protocol — it is the refcount
            # invariant's last line of defense, not a fast path. A spec
            # round writes K/V rows at pos..pos+spec in one dispatch, so
            # the net covers every page that span touches, not just pos's.
            for cidx, creq in enumerate(lanes):
                if creq is None:
                    continue
                first = int(state.pos[cidx]) // state.page_tokens
                last = min(
                    (int(state.pos[cidx]) + spec_span) // state.page_tokens,
                    state.pages_per_slot - 1,
                )
                for slot in range(first, last + 1):
                    pg = int(state.block_tables[cidx, slot])
                    if pg and int(state.page_refs[pg]) > 1:
                        rt.slot_cow(state, cidx, slot)
        accept = None
        if use_spec:
            try:
                toks, accept = rt.slot_decode_spec_round(state)
            except ModelNotLoadedError as e:
                if rt.is_loaded(self.model_id):
                    # the draft was evicted between the residency check and
                    # the round: detach and decode plain — target lanes are
                    # untouched (the round failed before any state update)
                    log.info(
                        "continuous spec detach model=%s (%s)",
                        self.model_id, e,
                    )
                    state.spec_draft = None
                    state.spec_draft_id = None
                    state.spec_tokens = 0
                else:
                    raise
        if accept is None:
            toks = rt.slot_decode_chunk(state, chunk)
        else:
            # ring/ledger semantics: a spec round can emit up to spec+1
            # tokens per lane in one dispatch — that is its "chunk"
            chunk = state.spec_tokens + 1
        eng.chunks += 1
        now = time.monotonic()
        wasted = 0
        drafted = spec_span * active_rows if accept is not None else 0
        accepted = int(accept.sum()) if accept is not None else 0
        for idx, req in enumerate(lanes):
            if req is None or req.pf_pos is not None:
                continue
            # spec rounds emit a VARIABLE per-row prefix (the accepted
            # draft run + the verify's correction token); plain chunks
            # emit exactly `chunk` tokens per live lane
            n_emit = chunk if accept is None else int(accept[idx])
            for j in range(n_emit):
                t = int(toks[idx, j])
                self._emit(req, t)
                if (eos is not None and t == eos) or len(req.tokens) >= req.max_new:
                    # retire NOW: steps the chunk computed past this point
                    # were for a finished request — the waste continuous
                    # batching exists to bound (< chunk, vs batch-drain
                    # padding under coalesce). Under spec this also drops
                    # accepted tokens past a mid-round EOS.
                    wasted += n_emit - (j + 1)
                    state.active[idx] = False
                    lanes[idx] = None
                    if getattr(state, "paged", False):
                        self._retire_pages(state, idx, req)
                    req.finish_t = now
                    req.done.set()
                    retired_n += 1
                    break
        if wasted and eng.metrics is not None:
            eng.metrics.gen_wasted_steps.labels("continuous").inc(wasted)
        if accept is not None and hasattr(rt, "_spec_observe"):
            # acceptance health + cumulative counters: one verify round per
            # active lane this boundary
            rt._spec_observe(
                self.model_id, state.spec_draft_id, accepted, active_rows,
                engine="continuous",
            )
        eng._set_active(self.model_id, sum(l is not None for l in lanes))
        self._update_page_gauge(state)
        self._record_step(
            state, chunk, active_rows, admitted_n, retired_n, wasted, step_t0,
            prefix_hits_n, prefill_s_sum, tokens_in_n,
            drafted=drafted, accepted=accepted,
            emitted=accepted if accept is not None else None,
        )
        return state

    def _record_step(
        self, state, chunk, active, admitted, retired, wasted, step_t0,
        prefix_hits=0, prefill_s=0.0, tokens_in=0,
        drafted=0, accepted=0, emitted=None,
    ) -> None:
        """One flight-recorder ring entry per chunk boundary, plus the
        oldest-queued-age gauge (`gen_admission_wait` only observes at
        admission — a row starved behind page exhaustion is invisible there
        until it finally admits; this gauge shows it starving)."""
        eng = self.engine
        with self.cv:
            depth = len(self.pending)
            oldest_t = self.pending[0].enqueue_t if depth else None
        wait_ms = (
            0.0 if oldest_t is None
            else max(0.0, (time.monotonic() - oldest_t) * 1e3)
        )
        if eng.metrics is not None:
            eng.metrics.gen_oldest_queued_age.labels("continuous").set(
                wait_ms / 1e3
            )
        paged = state is not None and getattr(state, "paged", False)
        shared = 0
        if paged and hasattr(state, "page_stats"):
            shared = state.page_stats()["shared"]
        now = time.monotonic()
        # cost ledger: the whole boundary's wall time lands on this tenant
        # (each scheduler thread is single-model); the prefill clock sum is
        # carved out, the remainder is decode+bookkeeping. tokens_out = one
        # prefill token per admission + the chunk tokens that reached live
        # rows (wasted overshoot excluded — waste is the ENGINE's cost).
        LEDGER.note_step(
            str(self.model_id), "continuous",
            prefill_s=prefill_s,
            decode_s=max(0.0, (now - step_t0) - prefill_s),
            tokens_in=tokens_in,
            # spec rounds pass the true emitted total (variable per-row
            # acceptance); plain chunks emit exactly chunk per live lane
            tokens_out=admitted + max(
                0, (active * chunk if emitted is None else emitted) - wasted
            ),
            queue_depth=depth,
        )
        RECORDER.record(
            str(self.model_id), "continuous",
            step_ms=(now - step_t0) * 1e3,
            chunk=chunk, active=active, admitted=admitted, retired=retired,
            pages_used=(
                state.arena_pages - len(state.free_pages) if paged else 0
            ),
            pages_free=len(state.free_pages) if paged else 0,
            wasted=wasted, queue_depth=depth, oldest_wait_ms=wait_ms,
            pages_shared=shared, prefix_hits=prefix_hits,
            drafted=drafted, accepted=accepted,
        )

    def _retire_pages(self, state, idx: int, req: _ContinuousReq) -> None:
        """Recycle a finishing row's pages and record its page-granularity
        waste: reserved capacity minus the tokens that actually occupied it
        (prompt + emitted; the internal-fragmentation cost of fixed pages
        plus the unconsumed max_new headroom)."""
        eng = self.engine
        if eng.metrics is not None:
            cap = state.lane_capacity(idx)
            used = req.prompt.shape[0] + len(req.tokens)
            eng.metrics.gen_kv_page_waste.observe(max(0, cap - min(used, cap)))
        if (
            req.conversation_id
            and eng.conversation_tier is not None
            and getattr(state, "paged", False)
            and hasattr(eng.runtime, "park_lane")
        ):
            # park BEFORE release: export needs the lane's page mapping.
            # History = prompt + all-but-last emitted token: the decode step
            # that emits token j writes the KV row for token j-1, so the
            # last emitted token's row was never written (mid-chunk EOS
            # leaves garbage beyond it). The next turn's prompt extends
            # exactly this sequence, so the match walk re-covers every row.
            try:
                if len(req.tokens) > 1:
                    history = np.concatenate(
                        [req.prompt, np.asarray(req.tokens[:-1], np.int32)]
                    )
                else:
                    history = req.prompt
                parked = eng.runtime.park_lane(state, idx, history)
                if parked is not None:
                    eng.conversation_tier.put(req.conversation_id, parked)
            except Exception:  # noqa: BLE001 - parking is best-effort
                log.warning(
                    "conversation park failed for %s", req.conversation_id,
                    exc_info=True,
                )
        state.release_pages(idx)
        d_st = getattr(state, "spec_draft", None)
        if d_st is not None:
            # the draft lane retires with its target: whole-page overshoot
            # from the last verify round hands back through the same
            # free-list, keeping the draft-side conservation census exact
            d_st.release_pages(idx)

    @staticmethod
    def _emit(req: _ContinuousReq, tok: int) -> None:
        """Append one emitted token and fire the row's stream callback (the
        SSE / gRPC-stream frame writers hang off it). A callback that raises
        is dropped after one failure — a dead client connection must not
        take the scheduler thread (and every other lane) down with it."""
        req.tokens.append(tok)
        cb = req.on_token
        if cb is not None:
            try:
                cb(tok)
            except Exception:  # noqa: BLE001 - client callback, not engine state
                req.on_token = None

    def _prefill_phase(
        self, rt, state, lanes, eos
    ) -> tuple[int, int, float, int]:
        """Advance every PREFILLING lane by exactly ONE fixed-size chunk
        (scheduler-thread only; called between admission and the decode
        half). The final chunk samples the row's first token under the seed
        drawn at admission — the same split-then-sample as a monolithic
        prefill — then activates the lane for the next boundary's decode
        chunk (or retires it on immediate EOS / max_new == 1). Returns
        (chunks_run, tokens_written, prefill_seconds, retired)."""
        eng = self.engine
        chunk_size = eng.prefill_chunk_tokens
        chunks = 0
        toks_in = 0
        prefill_s = 0.0
        retired = 0
        for idx, req in enumerate(lanes):
            if req is None or req.pf_pos is None:
                continue
            prompt = req.pf_prompt
            p = prompt.shape[0]
            t0 = time.monotonic()
            n = min(chunk_size, p - req.pf_pos)
            last = rt.slot_prefill_chunk(
                self.model_id, state, idx,
                prompt[req.pf_pos:req.pf_pos + n], req.pf_pos, chunk_size,
            )
            req.pf_pos += n
            dt = time.monotonic() - t0
            req.prefill_s += dt
            prefill_s += dt
            toks_in += n
            chunks += 1
            if eng.metrics is not None:
                eng.metrics.gen_prefill_chunks.inc()
            if req.pf_pos < p:
                continue
            tok = rt.sample_first_token(
                last, req.temperature, req.top_k, req.pf_seed
            )
            now = time.monotonic()
            if req.first_tok_t is None:
                req.first_tok_t = now
            req.prefill_tokens += p
            req.pf_pos = None
            req.pf_prompt = None
            remaining = req.max_new - len(req.tokens)
            self._emit(req, int(tok))
            if getattr(state, "prefix_index", None) is not None:
                # same publish the monolithic cold path does, just at the
                # last chunk: later same-prefix admissions map these pages
                rt.shared_prefix_publish(state, idx, prompt, last)
            if (eos is not None and int(tok) == eos) or remaining <= 1:
                lanes[idx] = None
                self._retire_pages(state, idx, req)
                req.finish_t = now
                req.done.set()
                retired += 1
                continue
            state.tok[idx] = int(tok)
            state.pos[idx] = p
            state.active[idx] = True
        return chunks, toks_in, prefill_s, retired

    def _pick_victim(
        self, lanes, req: _ContinuousReq
    ) -> int | None:
        """The preemption target for ``req``: the decoding lane with the
        numerically largest rank strictly above the arrival's (low loses to
        normal loses to high), youngest submit last — matching admission's
        (rank, seq) order in reverse. PREFILLING lanes are exempt (nothing
        decodable to park yet) and so are lanes out of preemption budget."""
        eng = self.engine
        best = None
        for li, lreq in enumerate(lanes):
            if lreq is None or lreq.pf_pos is not None:
                continue
            if lreq.rank <= req.rank:
                continue
            if lreq.preemptions >= eng.preempt_limit:
                continue
            if best is None or (lreq.rank, lreq.seq) > (
                lanes[best].rank, lanes[best].seq
            ):
                best = li
        return best

    def _preempt(self, rt, state, lanes, vidx: int) -> bool:
        """Park one decoding lane's KV through the conversation codec and
        requeue the row (priority preemption). The parked pages are COPIES:
        release_pages hands the originals back through the normal free
        list, so the conservation census never sees a discrepancy. Returns
        False when the lane can't be parked (dense state, codec mismatch) —
        the caller stops hunting victims then."""
        eng = self.engine
        victim = lanes[vidx]
        park_t0 = time.monotonic()
        try:
            # same validity rule as retirement parking: the decode step
            # that emits token j writes the KV row for token j-1, so the
            # last emitted token's row was never written
            if len(victim.tokens) > 1:
                history = np.concatenate(
                    [victim.prompt, np.asarray(victim.tokens[:-1], np.int32)]
                )
            else:
                history = victim.prompt
            parked = rt.park_lane(state, vidx, history)
        except Exception:  # noqa: BLE001 - lane left running on park failure
            log.warning(
                "preemption park failed for lane %d of %s",
                vidx, self.model_id, exc_info=True,
            )
            return False
        if parked is None:
            return False
        victim.preempt_parked = parked
        victim.preemptions += 1
        state.active[vidx] = False
        state.release_pages(vidx)
        d_st = getattr(state, "spec_draft", None)
        if d_st is not None:
            d_st.release_pages(vidx)
        lanes[vidx] = None
        with self.cv:
            self.pending.append(victim)
            if eng.metrics is not None:
                eng.metrics.batcher_queue_depth.labels("generate").inc()
        if eng.metrics is not None:
            eng.metrics.gen_preemptions.labels(victim.priority).inc()
        # flight-recorder phase note: every preemption decision leaves an
        # auditable per-victim stamp (park cost attributed like a phase)
        RECORDER.note_phases(
            str(self.model_id), "continuous",
            {"preempt_park": time.monotonic() - park_t0},
        )
        log.info(
            "preempted lane %d of %s (class=%s, %d tokens emitted, "
            "preemption %d/%d)",
            vidx, self.model_id, victim.priority, len(victim.tokens),
            victim.preemptions, eng.preempt_limit,
        )
        return True

    def _update_page_gauge(self, state) -> None:
        if state is not None and getattr(state, "paged", False):
            if hasattr(state, "page_stats"):
                # DISTINCT pages only: a prefix page mapped by N lanes
                # counts once, and index-only ("cached") pages are excluded
                # — they are reclaimable on demand, so counting them would
                # under-report admission headroom (NodeStatus routes on it)
                ps = state.page_stats()
                used, shared = ps["shared"] + ps["private"], ps["shared"]
            else:
                used = state.arena_pages - len(state.free_pages)
                shared = 0
            self.engine._set_pages(
                self.model_id, used, state.arena_pages, shared
            )


@lockchecked
class ContinuousGenerateEngine:
    """Iteration-level continuous batching for ``:generate`` — the vLLM-/
    DeepServe-style alternative to GenerateCoalescer, selected via
    ``serving.generate_engine=continuous``.

    Where the coalescer decides membership once at batch-formation time
    (a request arriving 50 ms after launch waits out the whole fixed-length
    scan, and early-EOS rows burn padded steps until the drain), this
    engine keeps a fixed-capacity slot array per model (static shapes — one
    compiled decode-chunk program regardless of which lanes are live) and
    makes both decisions at every chunk boundary: pending rows admit into
    free lanes (prompt prefilled via the prefix-cache-aware slot prefill),
    finished rows retire immediately.

    Scope mirrors the coalescer's exclusions: explicitly seeded requests
    (reproducible solo stream), non-transformer_lm families, malformed
    params, and LOCKSTEP mesh runtimes (``runtime.mesh_lockstep`` — a
    cross-process group's device-op stream must not depend on a host
    scheduler thread) all fall through to ``runtime.generate``. A
    single-process mesh runs here on its KV-head-sharded arena (ISSUE 20),
    greedy-parity-pinned against the single-device path by
    tests/test_mesh_parity.py.
    """

    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_scheds": "_lock",
        "_active": "_lock",
        "_pages": "_lock",
        "_closed": "_lock",
        "_seq": "_lock",
    }

    def __init__(
        self,
        runtime: BaseRuntime,
        slots: int = 8,
        chunk_tokens: int = 8,
        wait_timeout_s: float = 600.0,
        metrics=None,
        page_tokens: int | None = None,
        arena_pages: int | None = None,
        share_prefix_bytes: int | None = None,
        arena_dtype: str | None = None,
        paged_kernel: bool | None = None,
        spec_draft_model: str | None = None,
        spec_tokens: int | None = None,
        recovery: bool = True,
        max_recoveries: int = 2,
        conversation_kv_bytes: int | None = None,
        conversation_kv_disk_bytes: int | None = None,
        conversation_kv_dir: str | None = None,
        prefill_chunk_tokens: int | None = None,
    ) -> None:
        self.runtime = runtime
        self.slots = max(1, int(slots))
        self.chunk_tokens = max(1, int(chunk_tokens))
        self.wait_timeout_s = wait_timeout_s
        self.metrics = metrics
        # paged-KV knobs forwarded to slot_decode_state: None = defer to the
        # runtime's ServingConfig (kv_page_tokens / kv_arena_pages /
        # kv_share_prefix_bytes), 0 = explicit dense / sharing off, > 0 =
        # paged with this page size / arena size / prefix-index byte budget
        self.page_tokens = None if page_tokens is None else int(page_tokens)
        self.arena_pages = None if arena_pages is None else int(arena_pages)
        self.share_prefix_bytes = (
            None if share_prefix_bytes is None else int(share_prefix_bytes)
        )
        # same None-defers convention: kv_arena_dtype ("" = model dtype,
        # "int8" = quantized pages — byte-matched auto-size means MORE pages
        # for the same budget, so admission capacity grows with no batcher
        # change: reserve_pages just sees a longer free-list) and
        # kv_paged_kernel (fused Pallas decode vs gather+einsum reference)
        self.arena_dtype = None if arena_dtype is None else str(arena_dtype)
        self.paged_kernel = (
            None if paged_kernel is None else bool(paged_kernel)
        )
        # in-engine speculative decoding (ISSUE 16): None = defer to the
        # runtime's ServingConfig (serving.spec_draft_model /
        # serving.spec_tokens), "" = explicitly off.  The draft model is
        # named "name" (highest resident version) or "name@version"; each
        # scheduler attaches it to its slot state via slot_attach_draft and
        # replaces plain decode chunks with draft/verify rounds whenever the
        # health gate (_spec_admit) allows.
        self.spec_draft_model = (
            None if spec_draft_model is None else str(spec_draft_model)
        )
        self.spec_tokens = None if spec_tokens is None else int(spec_tokens)
        # transparent crash recovery (serving.generate_recovery): on an
        # engine-thread death the crashed scheduler's rows requeue into a
        # fresh scheduler thread instead of failing — admission re-prefills
        # a row's prompt + emitted tokens, so the client stream continues
        # where it broke. max_recoveries bounds the respawn budget PER ROW.
        self.recovery = bool(recovery)
        self.max_recoveries = max(0, int(max_recoveries))
        # conversation-grade KV lifecycle (ISSUE 18): a byte-budgeted host
        # tier (+ optional disk spill level) holding parked decode state
        # keyed by conversation id. None = defer to the runtime's
        # ServingConfig (serving.conversation_kv_bytes & friends), 0 =
        # explicitly off. The tier lives on the ENGINE, not the scheduler:
        # parked turns survive scheduler crashes and respawns.
        cfg = getattr(runtime, "cfg", None)
        ckv_bytes = (
            int(getattr(cfg, "conversation_kv_bytes", 0) or 0)
            if conversation_kv_bytes is None else int(conversation_kv_bytes)
        )
        ckv_disk = (
            int(getattr(cfg, "conversation_kv_disk_bytes", 0) or 0)
            if conversation_kv_disk_bytes is None
            else int(conversation_kv_disk_bytes)
        )
        ckv_dir = (
            str(getattr(cfg, "conversation_kv_dir", "/tmp/tpusc_conv_kv"))
            if conversation_kv_dir is None else str(conversation_kv_dir)
        )
        if ckv_bytes > 0:
            from tfservingcache_tpu.cache.conversation_kv import (
                ConversationKVTier,
            )
            self.conversation_tier = ConversationKVTier(
                ckv_bytes,
                disk_capacity_bytes=ckv_disk,
                disk_dir=ckv_dir,
                metrics=metrics,
            )
        else:
            self.conversation_tier = None
        # chunked prefill interleaving (ISSUE 19): None = defer to the
        # runtime's ServingConfig (serving.prefill_chunk_tokens), 0 =
        # explicitly off. Clamped UP to a pow2 so ONE compiled partial-
        # prefill program serves every chunk of every prompt (the final
        # chunk zero-pads into it).
        pf = (
            int(getattr(cfg, "prefill_chunk_tokens", 0) or 0)
            if prefill_chunk_tokens is None else int(prefill_chunk_tokens)
        )
        self.prefill_chunk_tokens = _next_bucket(pf) if pf > 0 else 0
        # priority preemption budget PER LANE: a row parked off its lane
        # this many times decodes to completion afterwards no matter what
        # class arrives — bounded starvation by construction
        self.preempt_limit = 2
        self._lock = threading.Lock()
        self._scheds: dict[ModelId, _ContinuousScheduler] = {}
        self._active: dict[ModelId, int] = {}
        # mid -> (used, total, shared); used counts DISTINCT pages and
        # excludes index-only cached pages (true admission headroom)
        self._pages: dict[ModelId, tuple[int, int, int]] = {}
        self._closed = False
        # engine-monotonic submit sequence — the FIFO half of admission's
        # (rank, seq) order; preserved across preemption/crash requeues
        self._seq = 0
        # observability (tests + bench)
        self.admitted = 0
        self.chunks = 0
        self.peak_active = 0  # high-water concurrent lanes (bench headline)

    def _set_active(self, model_id: ModelId, n: int) -> None:
        with self._lock:
            if n:
                self._active[model_id] = n
            else:
                self._active.pop(model_id, None)
            total = sum(self._active.values())
            if total > self.peak_active:
                self.peak_active = total
        if self.metrics is not None:
            # per-model series when model_labels is on (which model's lanes
            # are saturated), one all_models total otherwise
            label = self.metrics.model_label(model_id.name, model_id.version)
            value = n if self.metrics.model_labels else total
            self.metrics.gen_slots_active.labels(label).set(value)

    def _set_pages(self, model_id: ModelId, used: int, total: int,
                   shared: int = 0) -> None:
        with self._lock:
            if total:
                self._pages[model_id] = (used, total, shared)
            else:
                self._pages.pop(model_id, None)
            used_sum = sum(u for u, _, _ in self._pages.values())
            total_sum = sum(t for _, t, _ in self._pages.values())
            shared_sum = sum(s for _, _, s in self._pages.values())
        peak = RECORDER.observe_watermark("gen_kv_pages_used", float(used_sum))
        # cost ledger: this tenant's distinct-page level (feeds its
        # kv_page_seconds integral) and the cross-model arena occupancy
        # level (the conservation test's reference integral) — stamped at
        # the same boundary so Σ tenants tracks the arena exactly
        LEDGER.gauge_set(str(model_id), "kv_pages", used)
        LEDGER.note_arena(used_sum)
        if self.metrics is not None:
            self.metrics.gen_kv_pages_used.set(used_sum)
            self.metrics.gen_kv_pages_total.set(total_sum)
            self.metrics.gen_kv_pages_used_peak.set(peak)
            self.metrics.gen_kv_pages_shared.set(shared_sum)

    def _sched(self, model_id: ModelId) -> _ContinuousScheduler:
        with self._lock:
            if self._closed:
                raise RuntimeError_("continuous generate engine is closed")
            s = self._scheds.get(model_id)
            if s is not None and not s.thread.is_alive():
                # insurance: a scheduler whose thread died without managing
                # a respawn (recovery off, or a crash that raced close())
                # must not keep collecting rows into a corpse's queue
                self._scheds.pop(model_id, None)
                s = None
            if s is None:
                s = _ContinuousScheduler(self, model_id)
                self._scheds[model_id] = s
            return s

    def _respawn(
        self, old: _ContinuousScheduler, survivors: list[_ContinuousReq]
    ) -> "_ContinuousScheduler | None":
        """Crash recovery (called from ``old``'s dying thread): swap in a
        fresh scheduler for the model and requeue the surviving rows, FIFO
        order preserved. Returns None when the engine is closing or ``old``
        was already replaced — the caller then fails the rows instead of
        stranding them on a queue nobody drains."""
        with self._lock:
            if self._closed or self._scheds.get(old.model_id) is not old:
                return None
            fresh = _ContinuousScheduler(self, old.model_id)
            self._scheds[old.model_id] = fresh
        with old.cv:
            # a submit that raced the swap through a stale scheduler ref
            # may have landed rows on the corpse's queue: carry them over,
            # and stop the corpse so later stale submits raise cleanly
            old.stopped = True
            late = list(old.pending)
            old.pending.clear()
        if late and self.metrics is not None:
            # their original submit already counted them; fresh.submit
            # counts them again, so cancel one of the two
            self.metrics.batcher_queue_depth.labels("generate").dec(len(late))
        rows = survivors + late
        try:
            fresh.submit(rows)
        except RuntimeError_ as e:
            # closed between the swap and the submit
            fresh._fail(rows, e)
            return None
        log.warning(
            "continuous scheduler for %s respawned after crash: "
            "%d rows requeued (%d interrupted mid-decode)",
            old.model_id, len(rows),
            sum(1 for r in survivors if r.tokens),
        )
        return fresh

    def generate(
        self,
        model_id: ModelId,
        input_ids: np.ndarray,
        prompt_lengths: list[int] | None = None,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: int | None = None,
        return_stats: bool = False,
        conversation_id: str | None = None,
        priority: str = "normal",
        on_token: Callable[[int], None] | None = None,
    ) -> np.ndarray:
        """Drop-in for GenerateCoalescer.generate: (rows, max_new_tokens)
        int32. A row that hit EOS early is zero-padded after it (the solo
        path has no EOS concept and always fills max_new_tokens — identical
        when the model declares no eos_id). ``return_stats`` additionally
        returns per-row timing dicts (ttft_s, admission_wait_s, tokens,
        prefill_tokens, priority, preemptions) — the bench's streaming-TTFT
        surface.

        ``priority`` ("high" | "normal" | "low") orders admission by class
        then FIFO and arms preemption: a high-class arrival finding no free
        pages parks the lowest-class decoding lane. Ignored on the solo
        path (a solo dispatch has no queue to order).

        ``on_token`` streams each emitted token the moment the scheduler
        appends it (single-row requests only — multi-row token order is
        undefined across lanes, so the callback is dropped). On the solo
        path the full row is replayed through the callback after the
        dispatch returns, so stream framing works identically there.

        ``conversation_id`` opts the request into the conversation KV tier
        (ISSUE 18): on retirement the row's decode state parks under the id,
        and the next turn carrying the same id resumes with a suffix-only
        prefill. Multi-row calls get per-row ids (``"{id}#r{row}"``) so rows
        never alias each other's parked state. A no-op when the tier is
        disabled (conversation_kv_bytes = 0), or on the solo path."""
        pr = str(priority or "normal")
        rank = _PRIORITY_RANKS.get(pr)
        if rank is None:
            raise ValueError(
                f"unknown priority {priority!r} (expected high|normal|low)"
            )
        ids = np.asarray(input_ids, np.int32)
        family = getattr(self.runtime, "family_of", lambda _m: None)(model_id)
        # mesh_lockstep (ISSUE 20): only CROSS-PROCESS groups (or meshes
        # with serving.mesh_fast_path off) fall back to the solo/coalesce
        # path now — a single-process mesh runs the continuous paged engine
        # on its sharded arena
        solo = (
            seed is not None
            or getattr(
                self.runtime, "mesh_lockstep",
                getattr(self.runtime, "mesh", None) is not None,
            )
            or ids.ndim != 2
            or not ids.size
            or family != "transformer_lm"
        )
        lengths = None
        if not solo:
            rows, s = ids.shape
            if prompt_lengths is None:
                lengths = np.full((rows,), s, np.int32)
            else:
                lengths = np.asarray(prompt_lengths, np.int32)
                if (
                    lengths.shape != (rows,)
                    or (lengths < 1).any()
                    or (lengths > s).any()
                ):
                    solo = True  # runtime raises its own clean error
            if not solo and (
                max_new_tokens < 1
                or not np.isfinite(temperature)
                or temperature < 0.0
                or top_k < 0
            ):
                solo = True
        if solo:
            out = self.runtime.generate(
                model_id, ids, prompt_lengths=prompt_lengths,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k,
                seed=seed if seed is not None else secrets.randbits(31),
            )
            if on_token is not None and out.ndim == 2 and out.shape[0] == 1:
                # stream framing parity on the solo path: replay the row
                # through the callback (all at once — a solo dispatch has
                # no per-token boundary to hook)
                for t in np.asarray(out)[0, :max_new_tokens].tolist():
                    try:
                        on_token(int(t))
                    except Exception:  # noqa: BLE001 - client callback
                        break
            return (out, None) if return_stats else out

        cid = str(conversation_id) if conversation_id else None
        with self._lock:
            seq0 = self._seq
            self._seq += rows
        reqs = [
            _ContinuousReq(
                prompt=ids[r, : lengths[r]].copy(),
                max_new=int(max_new_tokens),
                temperature=float(temperature),
                top_k=int(top_k),
                conversation_id=(
                    None if cid is None
                    else (cid if rows == 1 else f"{cid}#r{r}")
                ),
                priority=pr,
                rank=rank,
                seq=seq0 + r,
                on_token=on_token if rows == 1 else None,
            )
            for r in range(rows)
        ]
        self._sched(model_id).submit(reqs)
        deadline = time.monotonic() + self.wait_timeout_s
        for r in reqs:
            if not r.done.wait(max(0.0, deadline - time.monotonic())):
                raise TimeoutError(
                    f"continuous generate for {model_id} timed out"
                )
        for r in reqs:
            if r.error is not None:
                raise r.error
        out = np.zeros((rows, max_new_tokens), np.int32)
        for i, r in enumerate(reqs):
            t = np.asarray(r.tokens[:max_new_tokens], np.int32)
            out[i, : t.shape[0]] = t
        # phase clocks (queue -> prefill -> decode -> respond), observed from
        # the CALLER's thread once every row is done: queue ends where the
        # scheduler starts the row's prefill, decode runs first token ->
        # finish, respond is the wait for batch-mates plus output assembly.
        # The worst row's attribution lands on the trace root — the request
        # was as slow as its slowest row.
        end_t = time.monotonic()
        ids_ctx = current_ids()
        worst: dict[str, float] = {}
        for r in reqs:
            admitted = r.admitted_t or r.enqueue_t
            finish = r.finish_t or admitted
            phases = {
                "queue": max(0.0, admitted - r.enqueue_t - r.prefill_s),
                "prefill": r.prefill_s,
                "decode": max(0.0, finish - (r.first_tok_t or admitted)),
                "respond": max(0.0, end_t - finish),
            }
            if self.metrics is not None:
                for ph, v in phases.items():
                    self.metrics.observe_phase(ph, "continuous", r.priority, v)
            for ph, v in phases.items():
                if v > worst.get(ph, -1.0):
                    worst[ph] = v
            RECORDER.note_phases(
                str(model_id), "continuous", phases,
                trace_id=ids_ctx[0] if ids_ctx else None,
            )
        # span annotation from the CALLER's thread (the scheduler thread has
        # no ambient trace — a span opened there would be an orphan root)
        TRACER.annotate(
            gen_engine="continuous",
            gen_admission_wait_ms=round(
                1e3 * max(
                    (r.admitted_t or r.enqueue_t) - r.enqueue_t for r in reqs
                ), 3,
            ),
            gen_prefix_hits=sum(1 for r in reqs if r.prefix_hit),
        )
        # priority + TTFT stamped on the ROOT (not the request span) so
        # /monitoring/traces and tools/slo_report.py --classes read the
        # same per-class attribution the class-labeled phase histogram
        # aggregates (ISSUE 20 satellite)
        TRACER.annotate_root(
            priority=pr,
            ttft_ms=round(
                1e3 * max(
                    (r.first_tok_t or r.enqueue_t) - r.enqueue_t for r in reqs
                ), 3,
            ),
            **{f"phase_{ph}_ms": round(v * 1e3, 3) for ph, v in worst.items()},
        )
        if return_stats:
            stats = [
                {
                    "ttft_s": (r.first_tok_t or r.enqueue_t) - r.enqueue_t,
                    "admission_wait_s": (r.admitted_t or r.enqueue_t)
                    - r.enqueue_t,
                    "tokens": len(r.tokens[:max_new_tokens]),
                    "prefill_tokens": r.prefill_tokens,
                    "priority": r.priority,
                    "preemptions": r.preemptions,
                }
                for r in reqs
            ]
            return out, stats
        return out

    def close(self) -> None:
        with self._lock:
            self._closed = True
            scheds = list(self._scheds.values())
            self._scheds.clear()
        for s in scheds:
            with s.cv:
                s.stopped = True
                s.cv.notify_all()
        for s in scheds:
            s.thread.join(timeout=5.0)
        if self.conversation_tier is not None:
            self.conversation_tier.close()

"""Scenario lab (ISSUE 17): composable workload specs, a fault injector,
and SLO scorecards over the existing serving machinery.

Three pieces, deliberately decoupled from production wiring:

* ``workload`` — a declarative DSL (tenant mix x zipf skew x arrival
  process x prompt-length mix x multi-turn depth) compiled to a seeded,
  replayable request schedule;
* ``faults`` — a process-global injector with pluggable hook sites in the
  engine, cache manager, peer-transfer receiver, and fleet status plane.
  Disarmed (the default) every hook is a passthrough; arming happens only
  through ``observability.lab_faults`` / the ``TPUSC_OBSERVABILITY_LAB_FAULTS``
  env override or an explicit ``arm()`` in tests and bench;
* ``scenario`` — runs one scenario x fault cell end-to-end and emits an
  SLO scorecard row (TTFT percentiles, tok/s, goodput, cold-miss rate,
  lost/recovered counts, page-conservation census, platform stamps).

This ``__init__`` intentionally imports nothing: production modules import
``tfservingcache_tpu.lab.faults`` for their hook sites, and that must not
drag numpy-heavy workload compilation into the server's import graph.
"""

"""Workload DSL: declarative scenario specs compiled to seeded schedules.

A :class:`WorkloadSpec` names the five axes the north-star cares about —
tenant mix x zipf skew x arrival process x prompt-length mix x multi-turn
depth — and :func:`compile_schedule` turns it into a deterministic list of
:class:`ScheduledRequest` (arrival offset, tenant, prompt tokens, budget).
The same (spec, seed) pair always compiles to the same schedule, so a
scorecard cell is replayable bit-for-bit: re-run the cell, get the same
request stream, diff only the system under test.

Arrival processes:

* ``poisson``     — exponential inter-arrivals at ``rate_rps`` (the classic
  open-loop load model; same idiom as bench.py's admission soak);
* ``burst``       — groups of ``burst_size`` simultaneous arrivals spaced
  ``burst_gap_s`` apart (coordinated clients, cron fan-out);
* ``flash_crowd`` — a poisson baseline with ``flash_share`` of all traffic
  compressed into a ``flash_width_s`` window at ``flash_at_s`` (λScale's
  motivating shape: everyone wants the same model NOW).

Multi-turn conversations (``turns`` > 1) chain requests whose prompts
extend the previous turn's prompt with a fresh suffix — page-aligned
shared prefixes, so the prefix cache and CoW machinery are on the hook,
not just cold prefill.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

__all__ = [
    "WorkloadSpec",
    "ScheduledRequest",
    "compile_schedule",
]

ARRIVALS = ("poisson", "burst", "flash_crowd")


@dataclass(frozen=True)
class WorkloadSpec:
    """One scenario, declaratively. ``requests`` counts TOTAL requests
    (conversations x turns); weights in ``tenant_mix``/``prompt_mix`` are
    relative, not normalized."""

    name: str
    tenants: tuple[str, ...] = ("lm",)
    # zipf skew over the tenant list (rank-ordered as given): weight of
    # tenant i is 1/(i+1)^zipf_s. 0 = uniform.
    zipf_s: float = 0.0
    arrival: str = "poisson"
    rate_rps: float = 16.0
    requests: int = 24
    burst_size: int = 6
    burst_gap_s: float = 0.4
    flash_at_s: float = 0.5
    flash_width_s: float = 0.05
    flash_share: float = 0.5
    prompt_lens: tuple[int, ...] = (6, 12, 24)
    prompt_mix: tuple[float, ...] = ()
    max_new: int = 12
    turns: int = 1
    turn_gap_s: float = 0.25
    # tokens appended per follow-up turn (the new "user message")
    turn_suffix_tokens: int = 6
    temperature: float = 0.0

    def __post_init__(self) -> None:
        if self.arrival not in ARRIVALS:
            raise ValueError(
                f"unknown arrival process {self.arrival!r}; one of {ARRIVALS}"
            )
        if not self.tenants:
            raise ValueError("spec needs at least one tenant")
        if self.prompt_mix and len(self.prompt_mix) != len(self.prompt_lens):
            raise ValueError("prompt_mix must match prompt_lens length")
        if self.requests < 1 or self.turns < 1:
            raise ValueError("requests and turns must be >= 1")


@dataclass(frozen=True)
class ScheduledRequest:
    """One compiled request: fire at ``at_s`` (offset from replay start)."""

    at_s: float
    tenant: str
    prompt: tuple[int, ...]
    max_new: int
    temperature: float
    conv: int          # conversation id (stable across its turns)
    turn: int          # 0-based turn index within the conversation
    index: int = field(default=0, compare=False)  # position in the schedule


def _tenant_weights(spec: WorkloadSpec) -> np.ndarray:
    n = len(spec.tenants)
    if spec.zipf_s <= 0.0 or n == 1:
        w = np.ones(n)
    else:
        w = 1.0 / np.power(np.arange(1, n + 1, dtype=np.float64), spec.zipf_s)
    return w / w.sum()


def _conv_starts(spec: WorkloadSpec, n: int, rng: np.random.Generator) -> np.ndarray:
    """Arrival offsets for the ``n`` conversation FIRST turns."""
    if spec.arrival == "poisson":
        return np.cumsum(rng.exponential(1.0 / spec.rate_rps, n))
    if spec.arrival == "burst":
        groups = np.arange(n) // max(1, spec.burst_size)
        return groups * spec.burst_gap_s
    # flash_crowd: baseline poisson trickle + a compressed spike
    n_flash = int(round(n * min(1.0, max(0.0, spec.flash_share))))
    base = np.cumsum(rng.exponential(1.0 / spec.rate_rps, n - n_flash))
    spike = spec.flash_at_s + rng.uniform(0.0, spec.flash_width_s, n_flash)
    return np.sort(np.concatenate([base, spike]))


def compile_schedule(
    spec: WorkloadSpec, seed: int, vocab: int = 256
) -> list[ScheduledRequest]:
    """Compile ``spec`` into a replayable schedule, sorted by arrival time.
    Token ids are drawn from [1, vocab) — 0 is reserved (pad in the toy LM
    family, same convention as bench.py's prompt generators)."""
    rng = np.random.default_rng([int(seed), spec.requests, len(spec.tenants)])
    vocab = max(2, int(vocab))
    n_conv = max(1, spec.requests // spec.turns)
    starts = _conv_starts(spec, n_conv, rng)
    weights = _tenant_weights(spec)
    mix = (
        np.asarray(spec.prompt_mix, np.float64)
        if spec.prompt_mix else np.ones(len(spec.prompt_lens))
    )
    mix = mix / mix.sum()

    out: list[ScheduledRequest] = []
    budget = spec.requests
    for conv in range(n_conv):
        tenant = spec.tenants[int(rng.choice(len(spec.tenants), p=weights))]
        base_len = int(spec.prompt_lens[int(rng.choice(len(spec.prompt_lens), p=mix))])
        prompt = tuple(int(t) for t in rng.integers(1, vocab, base_len))
        for turn in range(spec.turns):
            if budget <= 0:
                break
            budget -= 1
            if turn > 0:
                suffix = tuple(
                    int(t) for t in rng.integers(1, vocab, spec.turn_suffix_tokens)
                )
                prompt = prompt + suffix
            out.append(ScheduledRequest(
                at_s=float(starts[conv] + turn * spec.turn_gap_s),
                tenant=tenant,
                prompt=prompt,
                max_new=spec.max_new,
                temperature=spec.temperature,
                conv=conv,
                turn=turn,
            ))
    # leftover budget (requests not divisible by turns): extra single-turn
    # conversations riding the tail of the start sequence, never dropped
    # silently — a 25-request spec yields 25 requests
    extra = 0
    while budget > 0:
        budget -= 1
        extra += 1
        tenant = spec.tenants[int(rng.choice(len(spec.tenants), p=weights))]
        plen = int(spec.prompt_lens[int(rng.choice(len(spec.prompt_lens), p=mix))])
        out.append(ScheduledRequest(
            at_s=float(starts[-1] + extra * (1.0 / spec.rate_rps)),
            tenant=tenant,
            prompt=tuple(int(t) for t in rng.integers(1, vocab, plen)),
            max_new=spec.max_new,
            temperature=spec.temperature,
            conv=n_conv - 1 + extra,
            turn=0,
        ))
    out.sort(key=lambda r: (r.at_s, r.conv, r.turn))
    return [replace(r, index=i) for i, r in enumerate(out)]

"""Scenario-lab fault injector: pluggable chaos hooks in production paths.

Production code calls ``fire(site, ...)`` at four sites:

* ``engine_step``   — top of the continuous scheduler's chunk boundary
  (runtime/batcher.py). Kills (``kill_engine``) raise :class:`InjectedFault`
  mid-decode, exercising the requeue-and-re-prefill recovery path; freezes
  (``freeze_scheduler``) sleep the scheduler thread for ``duration_s`` so
  queued rows visibly age (``tpusc_gen_oldest_queued_age_seconds``).
* ``store_fetch``   — top of the cache manager's provider miss path
  (cache/manager.py ``_fetch``). ``stall_store`` sleeps there, simulating a
  hung object store under the cold-load deadline machinery.
* ``peer_chunk``    — every C-frame through the peer-transfer receiver
  (protocol/peer_transfer.py ``feed``). ``corrupt_peer_chunk`` flips a
  payload byte, so the receiver's hash check fails and the provider falls
  back to the store (``tpusc_peer_fetch_bytes_total{outcome="error"}``).
* ``status_ingest`` — fleet status ingestion (cluster/status.py
  ``FleetView.ingest``). ``drop_peer`` swallows the snapshot, so the peer's
  health score decays through the normal staleness machinery.

Disarmed (the default, and the only state production configs reach without
``observability.lab_faults``) every hook is ``return payload`` behind one
bool read — the parity test in tests/test_scenario_lab.py holds the
token-identity proof. Every firing increments
``tpusc_fault_injected_total{kind}`` (when a Metrics instance was armed
alongside the specs), tallies into the flight recorder
(``RECORDER.note_fault``), and writes one ``fault_injected:<kind>`` anomaly
dump through the existing per-reason/model cooldown dedup.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("lab.faults")

KILL_KINDS = ("kill_engine",)
SLEEP_KINDS = ("freeze_scheduler", "stall_store")
KINDS = (
    "kill_engine",
    "freeze_scheduler",
    "stall_store",
    "corrupt_peer_chunk",
    "drop_peer",
)
# which hook site each fault kind attaches to
SITE_OF = {
    "kill_engine": "engine_step",
    "freeze_scheduler": "engine_step",
    "stall_store": "store_fetch",
    "corrupt_peer_chunk": "peer_chunk",
    "drop_peer": "status_ingest",
}


class InjectedFault(RuntimeError):
    """Raised by an armed kill-class fault at its hook site. A plain
    RuntimeError subclass on purpose: the victim code path must handle it
    exactly like the organic failure it stands in for (an engine-thread
    crash), never special-case it."""


@dataclass
class FaultSpec:
    """One armed fault. ``after`` skips the first N matching visits (fire on
    visit N+1), ``count`` bounds total firings (0 = unlimited), ``model`` /
    ``peer`` filter the site context when set. ``visits``/``fired`` are
    runtime tallies owned by the injector lock."""

    kind: str
    after: int = 0
    count: int = 1
    duration_s: float = 0.05
    model: str | None = None
    peer: str | None = None
    visits: int = field(default=0, compare=False)
    fired: int = field(default=0, compare=False)

    def __post_init__(self) -> None:
        if self.kind not in SITE_OF:
            raise ValueError(
                f"unknown fault kind {self.kind!r}; one of {sorted(SITE_OF)}"
            )

    @property
    def site(self) -> str:
        return SITE_OF[self.kind]


class FaultInjector:
    """Process-global spec store. ``armed`` is a plain bool read on the
    per-hook fast path (GIL-atomic; flips only in arm/disarm); the spec
    list and tallies are lock-owned."""

    _tpusc_guarded = {"_specs": "_lock", "_metrics": "_lock"}

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._specs: list[FaultSpec] = []
        self._metrics: Any = None
        self.armed = False

    def arm(self, specs: list[FaultSpec], metrics: Any = None) -> None:
        """Arm ``specs`` (replacing any previous arming). ``metrics`` is the
        node's Metrics instance for the fault counter family — optional, so
        engine-only harnesses can arm without a registry."""
        with self._lock:
            self._specs = list(specs)
            self._metrics = metrics
        self.armed = True
        log.warning(
            "fault injector ARMED: %s",
            [f"{s.kind}(after={s.after},count={s.count})" for s in specs],
        )

    def disarm(self) -> None:
        self.armed = False
        with self._lock:
            self._specs = []
            self._metrics = None

    def snapshot(self) -> list[dict[str, Any]]:
        """Per-spec tallies (for scorecards and tests)."""
        with self._lock:
            return [
                {"kind": s.kind, "visits": s.visits, "fired": s.fired}
                for s in self._specs
            ]

    def fire(
        self,
        site: str,
        model: str | None = None,
        peer: str | None = None,
        payload: Any = None,
    ) -> Any:
        """Armed slow path (the module-level ``fire`` guards the fast path).
        Applies every matching spec in arming order; a kill raises after its
        bookkeeping so the firing is observable even though the site dies."""
        to_sleep = 0.0
        to_raise: InjectedFault | None = None
        fired_kinds: list[str] = []
        with self._lock:
            metrics = self._metrics
            for s in self._specs:
                if s.site != site:
                    continue
                if s.model is not None and s.model != model:
                    continue
                if s.peer is not None and s.peer != peer:
                    continue
                s.visits += 1
                if s.visits <= s.after or (s.count and s.fired >= s.count):
                    continue
                s.fired += 1
                fired_kinds.append(s.kind)
                if s.kind in SLEEP_KINDS:
                    to_sleep = max(to_sleep, s.duration_s)
                elif s.kind in KILL_KINDS:
                    to_raise = InjectedFault(
                        f"injected {s.kind} at {site}"
                        + (f" (model={model})" if model else "")
                    )
                elif s.kind == "corrupt_peer_chunk":
                    payload = _corrupt(payload)
                elif s.kind == "drop_peer":
                    payload = None
        for kind in fired_kinds:
            RECORDER.note_fault(kind)
            if metrics is not None:
                metrics.fault_injected.labels(kind).inc()
            # one dump per (reason, model) inside the recorder cooldown — a
            # 100-firing freeze storm is one spool file, not a hundred
            RECORDER.dump(
                f"fault_injected:{kind}", model=model,
                site=site, peer=peer,
            )
            log.warning("fault fired: %s at %s model=%s peer=%s",
                        kind, site, model, peer)
        if to_sleep > 0.0:
            time.sleep(to_sleep)
        if to_raise is not None:
            raise to_raise
        return payload


def _corrupt(payload: Any) -> Any:
    """Flip the last byte of a bytes-like payload (a peer-transfer frame):
    headers stay intact, so the frame parses and the corruption is caught by
    the receiver's per-chunk hash — the realistic wire-bitrot shape."""
    if payload is None or len(payload) == 0:
        return payload
    buf = bytearray(payload)
    buf[-1] ^= 0xFF
    return bytes(buf)


_INJECTOR = FaultInjector()


def fire(
    site: str,
    model: str | None = None,
    peer: str | None = None,
    payload: Any = None,
) -> Any:
    """Hook entry point for production call sites. Disarmed fast path is a
    single attribute read + return — provably no-op (parity test in
    tests/test_scenario_lab.py)."""
    if not _INJECTOR.armed:
        return payload
    return _INJECTOR.fire(site, model=model, peer=peer, payload=payload)


def arm(specs: list[FaultSpec], metrics: Any = None) -> None:
    _INJECTOR.arm(specs, metrics=metrics)


def disarm() -> None:
    _INJECTOR.disarm()


def armed() -> bool:
    return _INJECTOR.armed


def snapshot() -> list[dict[str, Any]]:
    return _INJECTOR.snapshot()


def arm_json(spec_json: str, metrics: Any = None) -> None:
    """Arm from the ``observability.lab_faults`` config string (reachable as
    the ``TPUSC_OBSERVABILITY_LAB_FAULTS`` env override): a JSON list of
    FaultSpec dicts, e.g.

        [{"kind": "freeze_scheduler", "after": 10, "duration_s": 0.25}]

    A malformed spec raises at startup — a chaos drill that silently armed
    nothing would report a meaninglessly green scorecard."""
    raw = json.loads(spec_json)
    if not isinstance(raw, list):
        raise ValueError("lab_faults must be a JSON list of fault specs")
    arm([FaultSpec(**d) for d in raw], metrics=metrics)

"""Scenario x fault cell runner: replay a compiled schedule, emit an SLO
scorecard row.

``run_cell`` is deliberately harness-agnostic: the caller (bench.py's
``scenario_lab`` section, or tests/test_scenario_lab.py) supplies a
``generate_fn(ScheduledRequest) -> dict`` closure over whatever stack it
built, plus optional Metrics / census hooks. The runner owns only the
open-loop replay (one thread per request, sleeping to its compiled arrival
offset), fault arming, and the scorecard math — so the same cell definition
runs against an engine-only stub stack in tests and the full
manager+runtime stack in bench.

Every scorecard row stamps ``kernel_active`` and ``platform`` (satellite
fix for BENCH_r09: its kernel arm silently ran interpret-mode on CPU and
the tok/s deltas were non-evidence — a matrix row without the stamp can no
longer exist).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable

from tfservingcache_tpu.lab import faults as lab_faults
from tfservingcache_tpu.lab.workload import ScheduledRequest, WorkloadSpec
from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("lab.scenario")

__all__ = [
    "default_scenarios",
    "default_faults",
    "run_cell",
    "SCORECARD_FIELDS",
]

# the scorecard schema, in render order (tools/slo_report.py and the
# OBSERVABILITY.md "Scenario lab" section mirror this list)
SCORECARD_FIELDS = (
    "scenario", "fault", "requests", "completed", "lost", "recovered",
    "p50_ttft_ms", "p95_ttft_ms", "p99_ttft_ms", "tok_s", "wall_s",
    "tokens_out", "goodput", "cold_miss_rate", "fault_injections",
    "preemptions", "conservation_ok", "kernel_active", "platform",
)


def default_scenarios(
    tenants: tuple[str, ...] = ("lm",), requests: int = 16, max_new: int = 10,
) -> list[WorkloadSpec]:
    """The standard 4-scenario row set (bench and the chaos suite share it
    so BENCH_r11 cells and regression cells are the same workloads)."""
    multi = tenants if len(tenants) > 1 else tenants * 2
    return [
        WorkloadSpec(
            name="steady_poisson", tenants=tenants[:1], arrival="poisson",
            rate_rps=24.0, requests=requests, max_new=max_new,
            prompt_lens=(6, 12, 24),
        ),
        WorkloadSpec(
            name="zipf_burst", tenants=multi, zipf_s=1.1, arrival="burst",
            burst_size=max(2, requests // 4), burst_gap_s=0.3,
            requests=requests, max_new=max_new, prompt_lens=(8, 16),
        ),
        WorkloadSpec(
            name="flash_crowd", tenants=multi, zipf_s=0.8,
            arrival="flash_crowd", rate_rps=12.0, flash_at_s=0.4,
            flash_width_s=0.05, flash_share=0.6, requests=requests,
            max_new=max_new, prompt_lens=(6, 12),
        ),
        WorkloadSpec(
            name="multi_turn", tenants=tenants[:1], arrival="poisson",
            rate_rps=16.0, requests=requests, max_new=max_new, turns=4,
            turn_gap_s=0.15, prompt_lens=(8,), turn_suffix_tokens=8,
        ),
    ]


def default_faults(duration_s: float = 0.4) -> list[lab_faults.FaultSpec | None]:
    """The standard fault column set: a no-fault baseline plus one spec per
    armed kind. ``after`` offsets put the firing mid-run, not at t=0 — a
    kill before any admission exercises nothing."""
    return [
        None,
        lab_faults.FaultSpec(kind="kill_engine", after=3, count=1),
        lab_faults.FaultSpec(
            kind="freeze_scheduler", after=2, count=1, duration_s=duration_s
        ),
        lab_faults.FaultSpec(
            kind="stall_store", after=0, count=1, duration_s=duration_s
        ),
        lab_faults.FaultSpec(kind="drop_peer", after=0, count=0),
    ]


def _family_sum(metrics: Any, family: str) -> float:
    """Sum a family's samples across all label sets (counters expose
    ``<family>_total`` samples; gauges expose the bare name)."""
    if metrics is None:
        return 0.0
    total = 0.0
    for mf in metrics.registry.collect():
        if mf.name != family:
            continue
        for s in mf.samples:
            if s.name in (family, family + "_total"):
                total += s.value
    return total


def _pct(sorted_ms: list[float], q: float) -> float:
    if not sorted_ms:
        return 0.0
    i = min(len(sorted_ms) - 1, int(round(q * (len(sorted_ms) - 1))))
    return sorted_ms[i]


def run_cell(
    schedule: list[ScheduledRequest],
    generate_fn: Callable[[ScheduledRequest], dict],
    *,
    scenario_name: str = "",
    fault: "lab_faults.FaultSpec | None" = None,
    metrics: Any = None,
    census_fn: Callable[[], bool] | None = None,
    kernel_active: bool = False,
    platform: str | None = None,
) -> dict[str, Any]:
    """Run one scenario x fault cell and return its scorecard row.

    ``generate_fn`` must return ``{"ok": bool, "ttft_s": float | None,
    "tokens": int, "error": str | None}`` per request and never raise (wrap
    and report — a lost request is a *measurement*, not a harness crash).
    ``census_fn`` returns the page-conservation verdict after the replay
    (None entry in the row when the stack has no paged state to census).
    """
    if platform is None:
        try:
            import jax

            platform = jax.default_backend()
        except Exception:  # noqa: BLE001 - stub stacks without jax
            platform = "unknown"

    base_recovered = _family_sum(metrics, "tpusc_requests_recovered")
    base_preempted = _family_sum(metrics, "tpusc_gen_preemptions")
    base_injected = _family_sum(metrics, "tpusc_fault_injected")
    base_lookups = _family_sum(metrics, "tfservingcache_cache")
    base_misses = _family_sum(metrics, "tfservingcache_cache_misses")
    base_faults = RECORDER.fault_counts()

    results: list[dict | None] = [None] * len(schedule)

    def _one(i: int, sr: ScheduledRequest, t0: float) -> None:
        delay = t0 + sr.at_s - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        try:
            results[i] = generate_fn(sr)
        except BaseException as e:  # noqa: BLE001 - a lost request is data
            results[i] = {"ok": False, "ttft_s": None, "tokens": 0,
                          "error": repr(e)}

    if fault is not None:
        # arm a FRESH copy: a FaultSpec's visits/fired tallies are runtime
        # state, and a spec list reused across a matrix must fire in every
        # cell, not just the first one that exhausts its count
        lab_faults.arm(
            [dataclasses.replace(fault, visits=0, fired=0)], metrics=metrics
        )
    try:
        t0 = time.monotonic()
        threads: list[threading.Thread] = []
        for i, sr in enumerate(schedule):
            t = threading.Thread(target=_one, args=(i, sr, t0), daemon=True)
            threads.append(t)
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.monotonic() - t0
    finally:
        if fault is not None:
            lab_faults.disarm()

    rows = [r if r is not None else
            {"ok": False, "ttft_s": None, "tokens": 0, "error": "no result"}
            for r in results]
    ok_rows = [r for r in rows if r.get("ok")]
    ttfts = sorted(
        r["ttft_s"] * 1e3 for r in ok_rows if r.get("ttft_s") is not None
    )
    tokens_out = sum(int(r.get("tokens", 0)) for r in ok_rows)
    lookups = _family_sum(metrics, "tfservingcache_cache") - base_lookups
    misses = _family_sum(metrics, "tfservingcache_cache_misses") - base_misses
    injected_now = RECORDER.fault_counts()
    injected = sum(injected_now.values()) - sum(base_faults.values())
    if metrics is not None:
        # prefer the counter when a registry is in play (it survives a
        # recorder shared across concurrent cells)
        injected = int(
            _family_sum(metrics, "tpusc_fault_injected") - base_injected
        ) or injected
    engine = RECORDER.engine_stats()
    row = {
        "scenario": scenario_name,
        "fault": fault.kind if fault is not None else "none",
        "requests": len(schedule),
        "completed": len(ok_rows),
        "lost": len(rows) - len(ok_rows),
        "recovered": int(
            _family_sum(metrics, "tpusc_requests_recovered") - base_recovered
        ),
        "p50_ttft_ms": round(_pct(ttfts, 0.50), 1),
        "p95_ttft_ms": round(_pct(ttfts, 0.95), 1),
        "p99_ttft_ms": round(_pct(ttfts, 0.99), 1),
        "tok_s": round(tokens_out / wall, 1) if wall > 0 else 0.0,
        "wall_s": round(wall, 2),
        "tokens_out": tokens_out,
        "goodput": round(float(engine.get("goodput", 1.0)), 4),
        "cold_miss_rate": round(misses / lookups, 4) if lookups else 0.0,
        "fault_injections": int(injected),
        "preemptions": int(
            _family_sum(metrics, "tpusc_gen_preemptions") - base_preempted
        ),
        "conservation_ok": census_fn() if census_fn is not None else None,
        "kernel_active": bool(kernel_active),
        "platform": platform,
    }
    errs = sorted({str(r.get("error")) for r in rows if not r.get("ok")})
    if errs:
        row["errors"] = errs[:4]
    log.info(
        "cell %s x %s: %d/%d ok, p95 ttft %.0f ms, %d recovered",
        row["scenario"], row["fault"], row["completed"], row["requests"],
        row["p95_ttft_ms"], row["recovered"],
    )
    return row

"""Ring attention: context parallelism for sequences too long for one chip.

First-class by design mandate (no reference counterpart — the reference
never touches tensors). Q/K/V are sharded along the sequence axis across a
mesh axis; each step computes attention of the local Q block against the
currently-held K/V block, then rotates K/V one hop around the ring with
``ppermute`` (ICI neighbor exchange), accumulating an online softmax exactly
like flash attention does across its K blocks. After P steps every Q block
has seen every K/V block while per-chip memory stays O(S/P).

Communication pattern: P-1 ppermute rounds of the K/V shards — bandwidth
equals one all-gather of K/V but overlapped with compute and never
materializing the full sequence on any chip.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfservingcache_tpu.parallel.mesh import compat_shard_map

NEG_INF = -1e30


def _block_attend(q, k, v, q_off, k_off, causal, acc, m, l):
    """One online-softmax update of (acc, m, l) with a K/V block at global
    offset ``k_off`` against Q at global offset ``q_off``.

    Operands stay in their INPUT dtype for the dots (bf16 runs at full MXU
    rate — upcasting first was the same half-rate mistake as the round-2
    flash kernel); scores/stats accumulate f32 via preferred_element_type,
    exactly the kernel's recipe (ops/attention.py)."""
    d = q.shape[-1]
    s = jax.lax.dot_general(
        q, k, (((3,), (3,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    ) / math.sqrt(d)                                      # (B, H, Sq, Sk) f32
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        q_pos = q_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = k_off + jax.lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    pv = jax.lax.dot_general(
        p.astype(v.dtype), v, (((3,), (2,)), ((0, 1), (0, 1))),
        preferred_element_type=jnp.float32,
    )
    return acc * alpha + pv, m_new, l_new


def _ring_shard_fn(q, k, v, *, axis: str, n_shards: int, causal: bool,  # static-bounded: causal, interpret -- boolean domains
                   impl: str = "xla", interpret: bool = False):
    """Per-shard body under shard_map: local (B, H, S/P, D) blocks. K/V ride
    the ring in their input dtype — rotating bf16 instead of upcast f32
    halves the ppermute bytes on ICI.

    ``impl="flash"`` runs each hop through the Pallas carry kernel
    (ops/attention.flash_attention_carry): the per-hop (Sq/P x Sk/P) f32
    score matrix — 64 MB per head-batch at 4k local — never touches HBM,
    only the O(S/P x D) carry does. ``impl="xla"`` keeps the einsum body
    (the CPU-harness path and the fallback for shapes the kernel rejects)."""
    idx = jax.lax.axis_index(axis)
    s_local = q.shape[2]
    acc = jnp.zeros(q.shape, jnp.float32)
    m = jnp.full(q.shape[:3] + (1,), NEG_INF, jnp.float32)
    l = jnp.zeros(q.shape[:3] + (1,), jnp.float32)
    q_off = idx * s_local

    k_cur, v_cur = k, v
    perm = [(j, (j + 1) % n_shards) for j in range(n_shards)]
    for step in range(n_shards):
        # after `step` rotations, this chip holds the block that started at
        # ring position (idx - step) mod P
        src = (idx - step) % n_shards
        k_off = src * s_local
        if impl == "flash":
            from tfservingcache_tpu.ops.attention import flash_attention_carry

            acc, m, l = flash_attention_carry(
                q, k_cur, v_cur, acc, m, l, k_off - q_off, causal=causal,
                interpret=interpret,
            )
        else:
            acc, m, l = _block_attend(
                q, k_cur, v_cur, q_off, k_off, causal, acc, m, l
            )
        if step + 1 < n_shards:
            k_cur = jax.lax.ppermute(k_cur, axis, perm)
            v_cur = jax.lax.ppermute(v_cur, axis, perm)
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def _pick_impl(impl: str, s_local: int, d: int) -> str:
    """"auto": Pallas carry kernel on TPU when the shard shape qualifies
    (128-multiple local seq, MXU-friendly head dim), einsum elsewhere."""
    if impl != "auto":
        return impl
    from tfservingcache_tpu.ops.attention import TPU_BACKENDS

    if (
        jax.default_backend() in TPU_BACKENDS
        and s_local % 128 == 0
        and d % 64 == 0
    ):
        return "flash"
    return "xla"


@functools.partial(
    jax.jit, static_argnames=("mesh", "axis", "causal", "impl", "interpret")
)
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "seq",
    causal: bool = True,
    impl: str = "auto",
    interpret: bool = False,
) -> jax.Array:
    """(B, H, S, D) attention with S sharded over ``mesh[axis]``. The full
    sequence never resides on one chip."""
    n_shards = mesh.shape[axis]
    if q.shape[2] % n_shards:
        raise ValueError(f"sequence {q.shape[2]} not divisible by {n_shards} ring shards")
    impl = _pick_impl(impl, q.shape[2] // n_shards, q.shape[3])
    spec = P(None, None, axis, None)
    fn = compat_shard_map(
        functools.partial(_ring_shard_fn, axis=axis, n_shards=n_shards,
                          causal=causal, impl=impl, interpret=interpret),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        # pallas_call out_shapes carry no varying-mesh-axes metadata, which
        # the flash body trips over; in/out specs above are explicit. The
        # einsum path keeps shard_map's validation (ADVICE r4).
        check_vma=(impl != "flash"),
    )
    return fn(q, k, v)

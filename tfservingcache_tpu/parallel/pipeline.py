"""Pipeline parallelism: GPipe-style microbatch schedule over a mesh axis.

No reference counterpart (SURVEY.md §2: the reference implements no
parallelism) — this exists so models deeper than one chip group's HBM can
span stages. TPU-first design: the schedule is a single jitted program under
``shard_map`` — each device holds one stage's weights (leading-dim sharded
over the ``stage`` axis), activations hop stage-to-stage with ``ppermute``
(nearest-neighbor ICI), and the whole T = M + P - 1 tick loop is a
``lax.fori_loop`` so XLA sees static control flow.

Bubble fraction is (P-1)/(M+P-1): callers pick n_microbatches >> n_stages to
amortize. Inter-stage activations must have one shape (the usual transformer
block contract).
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from tfservingcache_tpu.parallel.mesh import compat_shard_map


def stack_stage_params(stage_params: list[Any]) -> Any:
    """Stack per-stage pytrees into one pytree with leading dim n_stages
    (the dim ``pipeline_apply`` shards over the stage axis)."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *stage_params)


def _pipeline_shard_fn(
    params: Any,
    x: jax.Array,
    *,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    axis: str,
    n_stages: int,
    n_micro: int,
):
    """Per-device body: runs this device's stage for every tick."""
    idx = jax.lax.axis_index(axis)
    # shard_map hands each stage params with leading dim 1 — drop it
    local = jax.tree_util.tree_map(lambda a: a[0], params)
    mb_shape = x.shape[1:]

    fwd_perm = [(i, i + 1) for i in range(n_stages - 1)]
    n_ticks = n_micro + n_stages - 1

    def tick(t, carry):
        prev_y, out_buf = carry
        # activation from the previous stage (stage 0 receives zeros)
        recv = jax.lax.ppermute(prev_y, axis, fwd_perm) if n_stages > 1 else prev_y
        mb_ix = jnp.clip(t, 0, n_micro - 1)
        fresh = jax.lax.dynamic_index_in_dim(x, mb_ix, axis=0, keepdims=False)
        inp = jnp.where(idx == 0, fresh, recv)
        y = stage_fn(local, inp)
        # the last stage banks microbatch t-(P-1) once it emerges
        slot = t - (n_stages - 1)
        valid = jnp.logical_and(slot >= 0, idx == n_stages - 1)
        out_buf = jax.lax.cond(
            valid,
            lambda b: jax.lax.dynamic_update_index_in_dim(
                b, y, jnp.clip(slot, 0, n_micro - 1), axis=0
            ),
            lambda b: b,
            out_buf,
        )
        return y, out_buf

    init = (
        jnp.zeros(mb_shape, x.dtype),
        jnp.zeros((n_micro,) + mb_shape, x.dtype),
    )
    _, out_buf = jax.lax.fori_loop(0, n_ticks, tick, init)
    # only the last stage holds real outputs; psum over the stage axis
    # replicates them everywhere (all other stages contribute zeros)
    out_buf = jnp.where(idx == n_stages - 1, out_buf, jnp.zeros_like(out_buf))
    return jax.lax.psum(out_buf, axis)


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = "stage",
    n_microbatches: int | None = None,
) -> jax.Array:
    """Run ``x`` through ``n_stages`` chained applications of ``stage_fn``,
    pipelined over ``mesh[axis]``.

    ``stage_params``: pytree with leading dim n_stages (see
    ``stack_stage_params``), sharded one stage per mesh slot.
    ``x``: (batch, ...) — split into microbatches along dim 0.
    Returns exactly ``stage_fn(p[P-1], ... stage_fn(p[0], x))``.
    """
    n_stages = mesh.shape[axis]
    leading = {a.shape[0] for a in jax.tree_util.tree_leaves(stage_params)}
    if leading != {n_stages}:
        # a mismatch would otherwise be silently block-sharded (each device
        # getting >1 stage and running only the first) — wrong answer, no error
        raise ValueError(
            f"stage_params leading dim(s) {sorted(leading)} != {n_stages} mesh stages"
        )
    n_micro = n_stages if n_microbatches is None else n_microbatches
    if n_micro < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {n_micro}")
    if x.shape[0] % n_micro:
        raise ValueError(f"batch {x.shape[0]} not divisible into {n_micro} microbatches")
    mb = x.shape[0] // n_micro
    xm = x.reshape((n_micro, mb) + x.shape[1:])

    fn = compat_shard_map(
        functools.partial(
            _pipeline_shard_fn,
            stage_fn=stage_fn,
            axis=axis,
            n_stages=n_stages,
            n_micro=n_micro,
        ),
        mesh=mesh,
        in_specs=(P(axis), P()),   # params stage-sharded; input replicated
        out_specs=P(),
        check_vma=False,
    )
    out = fn(stage_params, xm)
    return out.reshape((n_micro * mb,) + out.shape[2:])

"""Partition-rule application: params pytree -> NamedShardings.

The TPU-native replacement for a hand-written distributed backend: families
declare path-regex -> PartitionSpec rules (e.g. megatron TP in
models/transformer_lm.py); XLA inserts the all-reduce/all-gather collectives
from the shardings. Rules reference mesh axis names; axes absent from the
actual mesh degrade to replication, so one rule set serves 1-chip, TP-only,
and DPxTP meshes.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for(path: str, rules: Mapping[str, Any], mesh: Mesh) -> PartitionSpec:
    for pattern, spec in rules.items():
        if re.fullmatch(pattern, path):
            # drop axes the mesh doesn't have (or that are size 1): the rule
            # set is written once for the largest topology
            cleaned = tuple(
                axis if (axis is None or mesh.shape.get(axis, 1) > 1) else None
                for axis in spec
            )
            return PartitionSpec(*cleaned)
    return PartitionSpec()  # replicate by default


def param_shardings(params: Any, rules: Mapping[str, Any], mesh: Mesh) -> Any:
    """Pytree of NamedShardings matching ``params``."""

    def one(path, leaf):
        del leaf
        return NamedSharding(mesh, spec_for(_path_str(path), rules, mesh))

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params: Any, rules: Mapping[str, Any], mesh: Mesh) -> Any:
    """device_put the pytree with rule-derived shardings (committed, so jit
    respects them and partitions the computation accordingly).

    On a mesh spanning processes (cross-host chip group), ``device_put`` of a
    host array cannot address remote devices; each process instead builds the
    global array from the shards it owns — every process calls this with the
    SAME host params (each loads the artifact from shared storage), so the
    assembled global array is consistent."""
    shardings = param_shardings(params, rules, mesh)
    if is_single_process(mesh):
        return jax.device_put(params, shardings)
    import numpy as np

    def to_global(x, s):
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, s, lambda idx: arr[idx])

    return jax.tree_util.tree_map(to_global, params, shardings)


def is_single_process(mesh: Mesh) -> bool:
    """True when every mesh device belongs to this process."""
    me = jax.process_index()
    return all(d.process_index == me for d in mesh.devices.flat)


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    if mesh.shape.get(axis, 1) > 1:
        return NamedSharding(mesh, PartitionSpec(axis))
    return NamedSharding(mesh, PartitionSpec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())

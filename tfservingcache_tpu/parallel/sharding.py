"""Partition-rule application: params pytree -> NamedShardings.

The TPU-native replacement for a hand-written distributed backend: families
declare path-regex -> PartitionSpec rules (e.g. megatron TP in
models/transformer_lm.py); XLA inserts the all-reduce/all-gather collectives
from the shardings. Rules reference mesh axis names; axes absent from the
actual mesh degrade to replication, so one rule set serves 1-chip, TP-only,
and DPxTP meshes.
"""

from __future__ import annotations

import re
from typing import Any, Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def spec_for(path: str, rules: Mapping[str, Any], mesh: Mesh) -> PartitionSpec:
    for pattern, spec in rules.items():
        if re.fullmatch(pattern, path):
            # drop axes the mesh doesn't have (or that are size 1): the rule
            # set is written once for the largest topology
            cleaned = tuple(
                axis if (axis is None or mesh.shape.get(axis, 1) > 1) else None
                for axis in spec
            )
            return PartitionSpec(*cleaned)
    return PartitionSpec()  # replicate by default


def param_shardings(params: Any, rules: Mapping[str, Any], mesh: Mesh) -> Any:
    """Pytree of NamedShardings matching ``params``."""

    def one(path, leaf):
        del leaf
        return NamedSharding(mesh, spec_for(_path_str(path), rules, mesh))

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params: Any, rules: Mapping[str, Any], mesh: Mesh) -> Any:
    """device_put the pytree with rule-derived shardings (committed, so jit
    respects them and partitions the computation accordingly).

    On a mesh spanning processes (cross-host chip group), ``device_put`` of a
    host array cannot address remote devices; each process instead builds the
    global array from the shards it owns — every process calls this with the
    SAME host params (each loads the artifact from shared storage), so the
    assembled global array is consistent."""
    shardings = param_shardings(params, rules, mesh)
    if is_single_process(mesh):
        return jax.device_put(params, shardings)
    import numpy as np

    def to_global(x, s):
        arr = np.asarray(x)
        return jax.make_array_from_callback(arr.shape, s, lambda idx: arr[idx])

    return jax.tree_util.tree_map(to_global, params, shardings)


def is_single_process(mesh: Mesh) -> bool:
    """True when every mesh device belongs to this process."""
    me = jax.process_index()
    return all(d.process_index == me for d in mesh.devices.flat)


# KV cache layouts place the KV-head axis at index 2 for BOTH the dense
# slot array (layers, slots, n_kv, max_seq, head_dim) and the paged arena
# (layers, n_pages, n_kv, page_tokens, head_dim) — one spec serves both.
KV_HEAD_DIM = 2


def kv_arena_spec(mesh: Mesh, n_kv: int, axis: str = "model") -> PartitionSpec:
    """PartitionSpec for a KV cache/arena: partitioned over the KV-head
    axis when the mesh has a >1 ``axis`` that divides ``n_kv`` (each shard
    holds ``n_kv/axis`` heads' pages, mirroring the megatron-TP split of
    wk/wv so a lane's K/V lands on the shard that computed it); replicated
    otherwise — indivisible head counts degrade rather than fail."""
    size = mesh.shape.get(axis, 1)
    if size > 1 and n_kv % size == 0:
        return PartitionSpec(None, None, axis, None, None)
    return PartitionSpec()


def kv_arena_shardings(mesh: Mesh, cache: Mapping[str, Any],
                       axis: str = "model") -> dict[str, NamedSharding]:
    """NamedShardings for an ``init_cache``/``init_paged_cache`` dict: the
    ``k``/``v`` payload partitioned per ``kv_arena_spec``, and the int8
    ``k_scale``/``v_scale`` buffers split over the SAME KV-head axis (their
    dim 2) whenever the payload is — a scale row is only ever read next to
    its page's head shard inside the decode jit, and committing the layout
    GSPMD would pick anyway keeps the arena-bytes accounting stable from
    allocation onward. Free-list/CoW/census bookkeeping stays host-side on
    page COUNTS, so no consumer ever needs a cross-shard gather."""
    spec = kv_arena_spec(mesh, int(cache["k"].shape[KV_HEAD_DIM]), axis)
    payload = NamedSharding(mesh, spec)
    scale = NamedSharding(
        mesh,
        PartitionSpec(None, None, axis) if axis in spec else PartitionSpec(),
    )
    return {
        name: payload if name in ("k", "v") else scale for name in cache
    }


def shard_kv_arena(cache: Mapping[str, Any], mesh: Mesh,
                   axis: str = "model") -> dict[str, Any]:
    """Commit a freshly allocated KV cache dict to its mesh shardings, so
    every generation jit that consumes it compiles a partitioned program
    (donation preserved: the committed layout round-trips through the
    donated-arena outputs)."""
    shardings = kv_arena_shardings(mesh, cache, axis)
    return {
        name: jax.device_put(arr, shardings[name])
        for name, arr in cache.items()
    }


def batch_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    if mesh.shape.get(axis, 1) > 1:
        return NamedSharding(mesh, PartitionSpec(axis))
    return NamedSharding(mesh, PartitionSpec())


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())

"""Device mesh construction + chip-group assignment.

New design territory (SURVEY.md §2 parallelism inventory: the reference has
none): models larger than one chip are served by a *chip group* — a sub-mesh
of the pod slice — and the consistent-hash ring assigns models to groups
instead of single chips. Within a group, XLA collectives ride ICI; the
request/routing plane between hosts stays gRPC over DCN (SURVEY.md §5
distributed-backend note).
"""

from __future__ import annotations

import numpy as np
from jax.sharding import Mesh


def make_mesh(axes: dict[str, int], devices=None) -> Mesh:
    """Mesh from {axis: size}; total must divide available devices.

    Axis order follows dict order; put the fastest-varying (tensor/model)
    axis last so it maps to adjacent devices — adjacent = shortest ICI hops
    on a TPU slice.
    """
    import jax

    if devices is None:
        devices = jax.devices()
    total = int(np.prod(list(axes.values())))
    if total > len(devices):
        raise ValueError(f"mesh {axes} needs {total} devices, have {len(devices)}")
    arr = np.array(devices[:total]).reshape(tuple(axes.values()))
    return Mesh(arr, tuple(axes))


def chip_groups(devices, group_size: int) -> list[list]:
    """Partition devices into contiguous groups of ``group_size`` (contiguous
    = ICI-adjacent on a slice). The ring's members become group ids."""
    if group_size < 1:
        raise ValueError("group_size must be >= 1")
    if len(devices) % group_size:
        raise ValueError(f"{len(devices)} devices not divisible into groups of {group_size}")
    return [list(devices[i : i + group_size]) for i in range(0, len(devices), group_size)]


def group_mesh(devices, group_size: int, group_index: int, axis: str = "model") -> Mesh:
    groups = chip_groups(devices, group_size)
    return Mesh(np.array(groups[group_index]), (axis,))


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
    """``jax.shard_map`` across the jax versions this repo meets: the
    top-level alias (with its ``check_vma`` knob) postdates 0.4.x, where
    the API lives at ``jax.experimental.shard_map.shard_map`` and the
    same knob is spelled ``check_rep``."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map

    return shard_map(f, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=check_vma)

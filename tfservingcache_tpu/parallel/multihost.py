"""Cross-host chip groups: one model sharded over chips owned by SEVERAL
processes (SURVEY.md §7 hard part (e) — the reference's ring semantics,
cluster.go:116-130, generalized to groups with no single-process owner).

Design. JAX multi-controller SPMD requires every process in a group to run
the SAME program in the SAME order — but serving is request-driven and only
one process receives each RPC. So:

  - the group's LEADER (the process owning the group's first device) is its
    ring member: it binds the group's REST/gRPC ports and answers requests;
  - follower processes run a tiny HTTP *work service*; before executing any
    collective op (load+warmup, predict, generate, unload), the leader
    broadcasts the op + its full inputs to every follower, which replays it
    against its own manager/runtime — all processes then enter the same
    jitted program and XLA's collectives ride ICI/DCN;
  - the broadcast is FIRE-THEN-COMPUTE: the leader must start its own
    computation while followers run theirs (joining the HTTP responses first
    would deadlock the collective), so responses are collected after;
  - a per-group lock on the leader serializes ops, which is what guarantees
    every process sees the same op order. Followers execute work items under
    their own per-group lock.

The data plane between hosts stays HTTP/gRPC over DCN exactly as SURVEY §5
prescribes for the routing layer; only tensors INSIDE the jitted program
move over XLA collectives.
"""

from __future__ import annotations

import io
import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Mapping

import numpy as np

from tfservingcache_tpu.runtime.base import GroupUnhealthyError
from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime
from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("multihost")

WORK_PATH = "/tpusc/groupwork"
# unhealthy-group re-formation probe cadence (leader pings followers, then
# resets the whole group to an empty lockstep state)
REFORM_PROBE_PERIOD_S = 5.0
PING_TIMEOUT_S = 2.0


class FollowerUnreachable(RuntimeError):
    """Transport-level follower failure: connection refused/reset or work
    timeout — the process is dead or wedged, as opposed to a live follower
    answering 500 (an application error scoped to one request)."""


def encode_work(meta: dict, arrays: Mapping[str, np.ndarray] | None = None) -> bytes:
    """npz envelope: JSON meta + named tensors (no pickle — work requests
    cross a trust boundary between processes)."""
    buf = io.BytesIO()
    payload = {f"t_{k}": np.asarray(v) for k, v in (arrays or {}).items()}
    payload["meta"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    np.savez(buf, **payload)
    return buf.getvalue()


def decode_work(body: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        arrays = {k[2:]: z[k] for k in z.files if k.startswith("t_")}
    return meta, arrays


class GroupWorkHandler:
    """Follower side: executes broadcast collective ops for the cross-host
    groups this process participates in (but does not lead)."""

    def __init__(self) -> None:
        # group index -> (manager, runtime)
        self._groups: dict[int, tuple[Any, TPUModelRuntime]] = {}
        self._locks: dict[int, threading.Lock] = {}
        self._pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="tpusc-gw")

    def register(self, group_index: int, manager, runtime: TPUModelRuntime) -> None:
        self._groups[group_index] = (manager, runtime)
        self._locks[group_index] = threading.Lock()

    @property
    def group_indexes(self) -> list[int]:
        return sorted(self._groups)

    def _execute(self, meta: dict, arrays: dict[str, np.ndarray],
                 t_arrival: float | None = None) -> None:
        gi = int(meta["group"])
        manager, runtime = self._groups[gi]
        op = meta["op"]
        # program-affecting config must match across the group: a mismatch
        # (e.g. prefix_cache_bytes on the leader, off here) would run
        # DIFFERENT XLA programs into one collective. Checked on every
        # envelope — including ping, so a misconfigured group's re-formation
        # stays blocked with a clear error instead of churning
        # teardown/reform forever (one permanent misconfiguration = one
        # permanent, explained, out-of-ring group).
        cfg = meta.get("cfg")
        if cfg is not None:
            mine = getattr(runtime, "_prefix_cache", None) is not None
            if bool(cfg.get("prefix_cache")) != mine:
                raise RuntimeError(
                    f"group {gi} config mismatch: leader prefix_cache="
                    f"{bool(cfg.get('prefix_cache'))}, this process={mine} — "
                    "serving.prefix_cache_bytes must match on every process "
                    "of a cross-host group"
                )
        if op == "ping":
            # reform probe: alive AND able to take the group lock soon — a
            # follower wedged mid-op answers "busy", so the leader keeps the
            # group down instead of re-forming against a stuck process
            lock = self._locks[gi]
            if not lock.acquire(timeout=float(meta.get("lock_timeout_s", 0.5))):
                raise TimeoutError("group lock busy (possibly wedged mid-op)")
            lock.release()
            return
        if op == "reset":
            with self._locks[gi]:
                runtime.reset_group_state()
            log.info("group %d state reset for re-formation", gi)
            return
        mid = ModelId(meta["model"], int(meta["version"]))
        with self._locks[gi]:  # same-order guarantee as the leader's lock
            # the leader ships its remaining request budget; a PREFETCH that
            # already spent it queued behind the group lock is one the leader
            # has abandoned (504) — fail it fast instead of hammering the
            # provider for a request nobody is waiting on. ONLY the host-side
            # joinable phase may be dropped: for collective ops (ensure/
            # predict/generate/unload) the leader has already entered its
            # half of the program by the time this runs, so a skipped
            # follower would wedge the group's collective forever (the
            # process is healthy — jax.distributed would never flag it)
            budget = meta.get("budget_s")
            if (
                op == "prefetch"
                and budget is not None
                and t_arrival is not None
                and time.monotonic() - t_arrival > float(budget)
            ):
                raise TimeoutError(
                    f"work item {op} for {mid} expired before execution "
                    f"(queued {time.monotonic() - t_arrival:.1f}s > "
                    f"budget {float(budget):.1f}s)"
                )
            if op == "prefetch":
                manager.prefetch(mid)  # host-side IO only, no collectives
            elif op == "ensure":
                manager.ensure_servable(mid)
            elif op == "predict":
                manager.ensure_servable(mid)
                runtime.predict(mid, arrays, meta.get("output_filter") or None)
            elif op == "generate":
                manager.ensure_servable(mid)
                draft_mid = (
                    ModelId(meta["draft_model"], int(meta["draft_version"]))
                    if meta.get("draft_model")
                    else None
                )
                if draft_mid is not None:
                    manager.ensure_servable(draft_mid)
                pr = meta.get("prefix_rows")
                runtime.generate(
                    mid,
                    arrays["input_ids"],
                    prompt_lengths=arrays["prompt_lengths"].tolist(),
                    max_new_tokens=int(meta["max_new_tokens"]),
                    temperature=float(meta["temperature"]),
                    top_k=int(meta["top_k"]),
                    seed=int(meta["seed"]),  # MUST match the leader's draw
                    draft_model_id=draft_mid,
                    spec_tokens=int(meta.get("spec_tokens", 4)),
                    # the leader's prefix-cache decision: this process must
                    # run the same program (None = decide locally, pre-r5
                    # leaders)
                    prefix_rows=None if pr is None else int(pr),
                )
            elif op == "unload":
                runtime.unload(mid)
            else:
                raise ValueError(f"unknown group work op {op!r}")

    async def handle(self, request):
        """aiohttp handler for POST /tpusc/groupwork."""
        import asyncio

        from aiohttp import web

        t_arrival = time.monotonic()
        body = await request.read()
        try:
            meta, arrays = decode_work(body)
            await asyncio.get_running_loop().run_in_executor(
                self._pool, self._execute, meta, arrays, t_arrival
            )
        except Exception as e:  # noqa: BLE001 - errors go back to the leader
            log.exception("group work failed")
            return web.json_response(
                {"ok": False, "error": f"{type(e).__name__}: {e}"}, status=500
            )
        return web.json_response({"ok": True})

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


class GroupWorkServer:
    """The follower process's work endpoint (one per process, shared by all
    its follower groups)."""

    def __init__(self, handler: GroupWorkHandler) -> None:
        self.handler = handler
        self._runner = None
        self.port = 0

    async def start(self, port: int, host: str = "0.0.0.0") -> int:
        from aiohttp import web

        app = web.Application(client_max_size=1 << 30)
        app.router.add_post(WORK_PATH, self.handler.handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        self.handler.close()


class MultiHostGroupRuntime(TPUModelRuntime):
    """Leader-side runtime for a group spanning processes: every collective
    op broadcasts to the followers FIRST (async), then runs locally, then
    joins the follower acknowledgements. The per-group lock makes the op
    stream identical on all processes."""

    def __init__(
        self,
        *args,
        followers: list[str],
        group_index: int = 0,
        work_timeout_s: float = 600.0,
        **kwargs,
    ) -> None:
        super().__init__(*args, **kwargs)
        self._followers = list(followers)
        self._group_index = group_index
        # per-op follower bound: the client-facing deadline
        # (serving.load_timeout_s) when configured, capped by work_timeout_s
        # — NOT a flat 600 s. A leader that has already answered 504 must not
        # leave followers decoding for minutes with the group lock pinned
        # (VERDICT r3 weak #5 / next #7). work_timeout_s remains the
        # backstop when no request deadline is configured.
        self._work_timeout_s = work_timeout_s
        load_t = getattr(self.cfg, "load_timeout_s", None)
        self._op_timeout_s = min(work_timeout_s, load_t) if load_t else work_timeout_s
        self._group_lock = threading.RLock()
        self._bcast_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self._followers)),
            thread_name_prefix="tpusc-bcast",
        )
        # failure containment (VERDICT r5 #5): a transport-dead follower
        # flips the group unhealthy — requests fail fast (503), the ring
        # heartbeat drops this group (check() below), and the reform thread
        # probes until every follower answers, then resets the whole group
        # to an empty lockstep state and rejoins
        self._unhealthy_reason: str | None = None
        self._health_lock = threading.Lock()
        self._reform_thread: threading.Thread | None = None
        self._closing = threading.Event()
        # bumped on every successful re-formation: stale failure signals
        # from the pre-teardown era (a slow timeout resolving after the
        # group already re-formed) must not re-tear-down the new group
        self._epoch = 0
        # the LEADER owns the group's draft-acceptance gate: its admit
        # decision rides the envelope (a gated request simply ships no
        # draft), so followers never need gate state of their own
        self._spec_gate_active = True
        if self.metrics is not None:
            self.metrics.group_healthy.labels(str(group_index)).set(1)

    # -- broadcast plumbing -------------------------------------------------
    def _post(self, addr: str, body: bytes,
              timeout_s: float | None = None) -> None:
        req = urllib.request.Request(
            f"http://{addr}{WORK_PATH}", data=body,
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(
                req, timeout=timeout_s or self._op_timeout_s
            ) as resp:
                out = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            # the follower's 500 carries the actual cause in its JSON body —
            # surface it, not just "HTTP Error 500". An HTTP status means the
            # process is ALIVE: this is an application error, not group death.
            try:
                detail = json.loads(e.read().decode()).get("error", str(e))
            except Exception:  # noqa: BLE001
                detail = str(e)
            raise RuntimeError(f"follower {addr}: {detail}") from None
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            # connection refused/reset or work timeout: dead or wedged
            raise FollowerUnreachable(f"follower {addr}: {e}") from None
        if not out.get("ok"):
            raise RuntimeError(f"follower {addr}: {out.get('error')}")

    def _broadcast(self, meta: dict, arrays: Mapping[str, np.ndarray] | None = None,
                   collective: bool = False):
        # budget_s lets the follower drop items that expire while queued
        # behind its group lock (the leader has long since 504'd them);
        # cfg is the program-affecting fingerprint every follower validates
        meta = dict(
            meta, group=self._group_index, budget_s=self._op_timeout_s,
            cfg={"prefix_cache": self._prefix_cache is not None},
        )
        body = encode_work(meta, arrays)
        futures = [
            self._bcast_pool.submit(self._post, addr, body)
            for addr in self._followers
        ]
        if collective:
            # transport death during a collective phase: mark the group down
            # the moment the future resolves — the leader's local half may be
            # wedged inside the collective and never reach _join. ONLY
            # transport errors here: an application-level 500 from a LIVE
            # follower is classified at _run_collective's join (symmetric
            # validation failures must not let one malformed request tear
            # the group down). The epoch tag stops a slow pre-teardown
            # failure from re-tearing-down an already re-formed group.
            epoch = self._epoch

            def _watch(f):
                # close() cancels queued futures; .exception() on those
                # raises CancelledError inside the callback
                if f.cancelled():
                    return
                if isinstance(f.exception(), FollowerUnreachable):
                    self._mark_unhealthy(
                        f"follower died during a collective: {f.exception()}",
                        epoch=epoch,
                    )

            for f in futures:
                f.add_done_callback(_watch)
        return futures

    def _acquire_group_lock(self) -> None:
        """Bounded acquire: a request queued behind a wedged op must notice
        the group went unhealthy and 503 out instead of waiting forever."""
        while not self._group_lock.acquire(timeout=0.5):
            self._require_healthy()

    def _join(self, futures) -> None:
        errs = []
        for f in futures:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        if errs:
            msg = f"group followers failed: {'; '.join(str(e) for e in errs)}"
            if any(isinstance(e, FollowerUnreachable) for e in errs):
                # a dead/wedged follower poisons the whole group's lockstep
                # guarantee — contain it (fail fast + leave the ring) rather
                # than let every request queue into the wedge. The TRIGGERING
                # request gets the same retriable 503 its successors will:
                # replicas/other groups can absorb it right now
                self._mark_unhealthy(msg)
                raise GroupUnhealthyError(
                    f"cross-host group {self._group_index} lost a follower "
                    f"({msg}); retry against a replica"
                )
            raise RuntimeError(msg)

    # -- failure containment / re-formation ---------------------------------
    def _mark_unhealthy(self, reason: str, epoch: int | None = None) -> None:
        with self._health_lock:
            if epoch is not None and epoch != self._epoch:
                return  # signal from before a completed re-formation: stale
            if self._unhealthy_reason is not None or self._closing.is_set():
                return
            self._unhealthy_reason = reason
            log.error(
                "cross-host group %d torn down: %s — failing requests fast, "
                "leaving the ring, probing for re-formation every %.0fs",
                self._group_index, reason, REFORM_PROBE_PERIOD_S,
            )
            if self.metrics is not None:
                self.metrics.group_reforms.labels(
                    str(self._group_index), "torn_down"
                ).inc()
                self.metrics.group_healthy.labels(
                    str(self._group_index)
                ).set(0)
            self._reform_thread = threading.Thread(
                target=self._reform_loop, name="tpusc-reform", daemon=True
            )
            self._reform_thread.start()

    def _require_healthy(self) -> None:
        reason = self._unhealthy_reason
        if reason is not None:
            raise GroupUnhealthyError(
                f"cross-host group {self._group_index} is re-forming after a "
                f"follower failure ({reason}); retry against a replica"
            )

    def _reform_loop(self) -> None:
        """Probe followers until all answer, then reset every process's group
        state (empty resident set — parity is re-derived by cold loads, the
        reference's remap semantics, SURVEY §3.4) and rejoin the ring."""
        while not self._closing.wait(REFORM_PROBE_PERIOD_S):
            try:
                ping = encode_work({
                    "op": "ping", "group": self._group_index,
                    "lock_timeout_s": 0.5,
                    # mismatched config blocks re-formation HERE, with the
                    # handler's clear error in the "still down" log, instead
                    # of churning teardown/reform on every request
                    "cfg": {"prefix_cache": self._prefix_cache is not None},
                })
                for addr in self._followers:
                    self._post(addr, ping, timeout_s=PING_TIMEOUT_S)
            except Exception as e:  # noqa: BLE001 - keep probing
                log.info(
                    "group %d still down (%s)", self._group_index, e
                )
                continue
            # every follower is alive with a free group lock: re-form. The
            # local half may still hold the group lock if it is wedged inside
            # a collective — bound the acquire and keep the group down rather
            # than reset state under a live op (only a process restart clears
            # a truly wedged XLA collective; same recovery story as the
            # reference's dead node, supervisor-owned).
            if not self._group_lock.acquire(timeout=PING_TIMEOUT_S):
                log.warning(
                    "group %d followers recovered but the leader half is "
                    "still wedged; restart required", self._group_index,
                )
                continue
            try:
                self._join(self._broadcast({"op": "reset"}))
                self.reset_group_state()
            except Exception as e:  # noqa: BLE001 - a failed reset retries
                log.warning("group %d re-formation failed: %s",
                            self._group_index, e)
                continue
            finally:
                self._group_lock.release()
            with self._health_lock:
                self._unhealthy_reason = None
                self._epoch += 1  # invalidate stale pre-teardown signals
                # gauge flips INSIDE the lock: a teardown racing this window
                # must not be overwritten back to healthy afterwards
                if self.metrics is not None:
                    self.metrics.group_reforms.labels(
                        str(self._group_index), "reformed"
                    ).inc()
                    self.metrics.group_healthy.labels(
                        str(self._group_index)
                    ).set(1)
            log.info(
                "cross-host group %d re-formed (empty state) and rejoined "
                "the ring", self._group_index,
            )
            return

    def check(self) -> None:
        """Ring/health probe: an unhealthy group FAILS its heartbeat so
        discovery drops exactly this group's membership and replicas absorb
        its keys (router.py pairs each group ident with its manager's
        is_healthy — the group-level analogue of reference cluster.go
        dead-node remap)."""
        self._require_healthy()
        super().check()

    def _run_collective(self, meta, arrays, fn):
        """Fire the broadcast, run the local half of the collective, then
        surface any follower error. The local compute MUST start without
        waiting for follower HTTP responses — they only arrive after the
        followers finish the same collective.

        Failure model: host-side fallible work (artifact fetch) is pushed
        into the joinable prefetch phase (ensure_loaded below), so a
        follower error DURING a collective means divergent device state; the
        jax.distributed coordination service then detects the dead/failed
        task and fails the whole group's processes for a supervisor restart
        — there is no in-band recovery from a half-entered collective."""
        self._require_healthy()  # fail fast, never queue into a dead group
        self._acquire_group_lock()
        try:
            self._require_healthy()  # the group may have died while queued
            # meta may be a callable: decisions that must be made atomically
            # with the op stream (e.g. the prefix-cache hit decision) are
            # computed here, under the group lock, just before the broadcast
            futures = self._broadcast(
                meta() if callable(meta) else meta, arrays, collective=True
            )
            try:
                result = fn()
            except BaseException as leader_err:
                # the leader's half ALSO failed: a symmetric failure (every
                # process rejected the same bad request before device work)
                # is an ordinary request error, not group death — transport
                # deaths still mark via _join/_watch
                try:
                    self._join(futures)
                except GroupUnhealthyError:
                    raise  # a dead follower trumps: retriable 503
                except RuntimeError as fe:
                    # symmetric app errors must not mask the leader's TYPED
                    # exception (RuntimeError_ maps to 400; a builtin
                    # RuntimeError would 500 a plain bad request)
                    log.debug("followers failed the same op: %s", fe)
                raise leader_err
            try:
                self._join(futures)
            except RuntimeError as e:
                # the leader completed the op but a LIVE follower failed it:
                # the processes' states have diverged (one ran the op, one
                # didn't) — the lockstep guarantee is gone, re-form. (The
                # transport-death case raised GroupUnhealthyError from _join
                # already — RuntimeError_ is not a builtin RuntimeError, so
                # it passes through untouched.)
                self._mark_unhealthy(
                    "follower failed a collective op the leader completed "
                    "(states diverged)"
                )
                raise GroupUnhealthyError(
                    f"cross-host group {self._group_index} diverged on a "
                    f"collective op ({e}); re-forming — retry against a "
                    "replica"
                ) from e
            return result
        finally:
            self._group_lock.release()

    # -- collective ops -----------------------------------------------------
    def ensure_loaded(self, model) -> None:
        if self.is_loaded(model.identifier):
            return
        mid = model.identifier
        self._require_healthy()
        self._acquire_group_lock()
        try:
            # phase 1 (joinable, host-side only): every process fetches the
            # artifact to its local disk; any provider/IO failure surfaces
            # HERE, before a single process enters the warmup collective
            self._join(self._broadcast(
                {"op": "prefetch", "model": mid.name, "version": mid.version}
            ))
            # phase 2 (collective): load + shard + warmup in lockstep
            self._run_collective(
                {"op": "ensure", "model": mid.name, "version": mid.version},
                None,
                lambda: super(MultiHostGroupRuntime, self).ensure_loaded(model),
            )
        finally:
            self._group_lock.release()

    def predict(self, model_id, inputs, output_filter=None):
        return self._run_collective(
            {
                "op": "predict", "model": model_id.name,
                "version": model_id.version, "output_filter": output_filter,
            },
            inputs,
            lambda: super(MultiHostGroupRuntime, self).predict(
                model_id, inputs, output_filter
            ),
        )

    def generate(self, model_id, input_ids, prompt_lengths=None,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0, draft_model_id=None,
                 spec_tokens: int = 4, prefix_rows=None):
        ids = np.asarray(input_ids, np.int32)
        lengths = (
            np.full((ids.shape[0],), ids.shape[1], np.int32)
            if ids.ndim == 2 and prompt_lengths is None
            else np.asarray(prompt_lengths if prompt_lengths is not None else [], np.int32)
        )
        # leader-decides prefix caching (VERDICT r5 #7): the hit decision is
        # made HERE, under the group lock (meta is a callable — see
        # _run_collective), and shipped in the envelope so every process
        # provably runs the same program. A follower whose cache cannot
        # honor it raises before any device op (lockstep divergence -> the
        # containment path tears the group down for a reset).
        decision = {"rows": -1, "use_draft": draft_model_id is not None}

        def meta() -> dict:
            # the group's draft-acceptance gate (leader-decides, VERDICT r5
            # #6's group extension): a gated request ships NO draft, so
            # every process runs the identical plain program
            if draft_model_id is not None:
                decision["use_draft"] = self._spec_admit(
                    model_id, draft_model_id
                )
            use = decision["use_draft"]
            # ALWAYS ship an explicit decision: peeked rows (>= 0, run the
            # prefix machinery) or -1 (run the cache-less plain path). A
            # follower must never "decide locally" — with mixed
            # prefix_cache_bytes configs that silently enters a different
            # program than the leader's (miss-path gen carries
            # return_cache; plain gen does not). Draft requests use the
            # SAME decision: the speculative path is prefix-aware (the
            # target prefills from the cached rows).
            decision["rows"] = -1
            if (
                self._prefix_cache is not None
                and ids.ndim == 2
                and ids.shape[0] == 1
                # malformed prompt_lengths must reach generate's own
                # validation (clean 400), not crash the peek with IndexError
                and lengths.shape == (1,)
                and 1 <= int(lengths[0]) <= ids.shape[1]
            ):
                decision["rows"] = self._prefix_cache.peek(
                    model_id, ids[0, : int(lengths[0])]
                )
            return {
                "op": "generate", "model": model_id.name,
                "version": model_id.version, "max_new_tokens": max_new_tokens,
                "temperature": temperature, "top_k": top_k, "seed": seed,
                # followers must replay the SAME speculative program: the
                # draft's forwards are collectives too on a sharded group
                "draft_model": draft_model_id.name if (draft_model_id and use) else "",
                "draft_version": draft_model_id.version if (draft_model_id and use) else 0,
                "spec_tokens": spec_tokens,
                "prefix_rows": decision["rows"],
            }

        return self._run_collective(
            meta,
            {"input_ids": ids, "prompt_lengths": lengths},
            lambda: super(MultiHostGroupRuntime, self).generate(
                model_id, ids, prompt_lengths=list(lengths),
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, seed=seed,
                draft_model_id=draft_model_id if decision["use_draft"] else None,
                spec_tokens=spec_tokens, prefix_rows=decision["rows"],
                spec_admitted=True if decision["use_draft"] and draft_model_id else None,
            ),
        )

    def unload(self, model_id) -> None:
        # unload holds no collectives, but followers must mirror it so the
        # group's LRU states stay in lockstep (divergent eviction would make
        # a later follower re-load run its warmup collective solo) — same
        # fire/compute/join + divergence classification as any collective op
        self._run_collective(
            {"op": "unload", "model": model_id.name,
             "version": model_id.version},
            None,
            lambda: super(MultiHostGroupRuntime, self).unload(model_id),
        )

    def close(self) -> None:
        self._closing.set()
        if self.metrics is not None:
            # a closed group no longer serves: the gauge must not keep
            # reporting healthy on a still-running metrics endpoint
            self.metrics.group_healthy.labels(str(self._group_index)).set(0)
        self._bcast_pool.shutdown(wait=False, cancel_futures=True)
        super().close()

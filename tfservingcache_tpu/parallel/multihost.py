"""Cross-host chip groups: one model sharded over chips owned by SEVERAL
processes (SURVEY.md §7 hard part (e) — the reference's ring semantics,
cluster.go:116-130, generalized to groups with no single-process owner).

Design. JAX multi-controller SPMD requires every process in a group to run
the SAME program in the SAME order — but serving is request-driven and only
one process receives each RPC. So:

  - the group's LEADER (the process owning the group's first device) is its
    ring member: it binds the group's REST/gRPC ports and answers requests;
  - follower processes run a tiny HTTP *work service*; before executing any
    collective op (load+warmup, predict, generate, unload), the leader
    broadcasts the op + its full inputs to every follower, which replays it
    against its own manager/runtime — all processes then enter the same
    jitted program and XLA's collectives ride ICI/DCN;
  - the broadcast is FIRE-THEN-COMPUTE: the leader must start its own
    computation while followers run theirs (joining the HTTP responses first
    would deadlock the collective), so responses are collected after;
  - a per-group lock on the leader serializes ops, which is what guarantees
    every process sees the same op order. Followers execute work items under
    their own per-group lock.

The data plane between hosts stays HTTP/gRPC over DCN exactly as SURVEY §5
prescribes for the routing layer; only tensors INSIDE the jitted program
move over XLA collectives.
"""

from __future__ import annotations

import io
import json
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Mapping

import numpy as np

from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime
from tfservingcache_tpu.types import ModelId
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("multihost")

WORK_PATH = "/tpusc/groupwork"


def encode_work(meta: dict, arrays: Mapping[str, np.ndarray] | None = None) -> bytes:
    """npz envelope: JSON meta + named tensors (no pickle — work requests
    cross a trust boundary between processes)."""
    buf = io.BytesIO()
    payload = {f"t_{k}": np.asarray(v) for k, v in (arrays or {}).items()}
    payload["meta"] = np.frombuffer(json.dumps(meta).encode(), np.uint8)
    np.savez(buf, **payload)
    return buf.getvalue()


def decode_work(body: bytes) -> tuple[dict, dict[str, np.ndarray]]:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        meta = json.loads(bytes(z["meta"].tobytes()).decode())
        arrays = {k[2:]: z[k] for k in z.files if k.startswith("t_")}
    return meta, arrays


class GroupWorkHandler:
    """Follower side: executes broadcast collective ops for the cross-host
    groups this process participates in (but does not lead)."""

    def __init__(self) -> None:
        # group index -> (manager, runtime)
        self._groups: dict[int, tuple[Any, TPUModelRuntime]] = {}
        self._locks: dict[int, threading.Lock] = {}
        self._pool = ThreadPoolExecutor(max_workers=8, thread_name_prefix="tpusc-gw")

    def register(self, group_index: int, manager, runtime: TPUModelRuntime) -> None:
        self._groups[group_index] = (manager, runtime)
        self._locks[group_index] = threading.Lock()

    @property
    def group_indexes(self) -> list[int]:
        return sorted(self._groups)

    def _execute(self, meta: dict, arrays: dict[str, np.ndarray],
                 t_arrival: float | None = None) -> None:
        gi = int(meta["group"])
        manager, runtime = self._groups[gi]
        mid = ModelId(meta["model"], int(meta["version"]))
        op = meta["op"]
        with self._locks[gi]:  # same-order guarantee as the leader's lock
            # the leader ships its remaining request budget; a PREFETCH that
            # already spent it queued behind the group lock is one the leader
            # has abandoned (504) — fail it fast instead of hammering the
            # provider for a request nobody is waiting on. ONLY the host-side
            # joinable phase may be dropped: for collective ops (ensure/
            # predict/generate/unload) the leader has already entered its
            # half of the program by the time this runs, so a skipped
            # follower would wedge the group's collective forever (the
            # process is healthy — jax.distributed would never flag it)
            budget = meta.get("budget_s")
            if (
                op == "prefetch"
                and budget is not None
                and t_arrival is not None
                and time.monotonic() - t_arrival > float(budget)
            ):
                raise TimeoutError(
                    f"work item {op} for {mid} expired before execution "
                    f"(queued {time.monotonic() - t_arrival:.1f}s > "
                    f"budget {float(budget):.1f}s)"
                )
            if op == "prefetch":
                manager.prefetch(mid)  # host-side IO only, no collectives
            elif op == "ensure":
                manager.ensure_servable(mid)
            elif op == "predict":
                manager.ensure_servable(mid)
                runtime.predict(mid, arrays, meta.get("output_filter") or None)
            elif op == "generate":
                manager.ensure_servable(mid)
                draft_mid = (
                    ModelId(meta["draft_model"], int(meta["draft_version"]))
                    if meta.get("draft_model")
                    else None
                )
                if draft_mid is not None:
                    manager.ensure_servable(draft_mid)
                runtime.generate(
                    mid,
                    arrays["input_ids"],
                    prompt_lengths=arrays["prompt_lengths"].tolist(),
                    max_new_tokens=int(meta["max_new_tokens"]),
                    temperature=float(meta["temperature"]),
                    top_k=int(meta["top_k"]),
                    seed=int(meta["seed"]),  # MUST match the leader's draw
                    draft_model_id=draft_mid,
                    spec_tokens=int(meta.get("spec_tokens", 4)),
                )
            elif op == "unload":
                runtime.unload(mid)
            else:
                raise ValueError(f"unknown group work op {op!r}")

    async def handle(self, request):
        """aiohttp handler for POST /tpusc/groupwork."""
        import asyncio

        from aiohttp import web

        t_arrival = time.monotonic()
        body = await request.read()
        try:
            meta, arrays = decode_work(body)
            await asyncio.get_running_loop().run_in_executor(
                self._pool, self._execute, meta, arrays, t_arrival
            )
        except Exception as e:  # noqa: BLE001 - errors go back to the leader
            log.exception("group work failed")
            return web.json_response(
                {"ok": False, "error": f"{type(e).__name__}: {e}"}, status=500
            )
        return web.json_response({"ok": True})

    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)


class GroupWorkServer:
    """The follower process's work endpoint (one per process, shared by all
    its follower groups)."""

    def __init__(self, handler: GroupWorkHandler) -> None:
        self.handler = handler
        self._runner = None
        self.port = 0

    async def start(self, port: int, host: str = "0.0.0.0") -> int:
        from aiohttp import web

        app = web.Application(client_max_size=1 << 30)
        app.router.add_post(WORK_PATH, self.handler.handle)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, host, port)
        await site.start()
        self.port = site._server.sockets[0].getsockname()[1]
        return self.port

    async def close(self) -> None:
        if self._runner is not None:
            await self._runner.cleanup()
        self.handler.close()


class MultiHostGroupRuntime(TPUModelRuntime):
    """Leader-side runtime for a group spanning processes: every collective
    op broadcasts to the followers FIRST (async), then runs locally, then
    joins the follower acknowledgements. The per-group lock makes the op
    stream identical on all processes."""

    def __init__(
        self,
        *args,
        followers: list[str],
        group_index: int = 0,
        work_timeout_s: float = 600.0,
        **kwargs,
    ) -> None:
        super().__init__(*args, **kwargs)
        self._followers = list(followers)
        self._group_index = group_index
        # per-op follower bound: the client-facing deadline
        # (serving.load_timeout_s) when configured, capped by work_timeout_s
        # — NOT a flat 600 s. A leader that has already answered 504 must not
        # leave followers decoding for minutes with the group lock pinned
        # (VERDICT r3 weak #5 / next #7). work_timeout_s remains the
        # backstop when no request deadline is configured.
        self._work_timeout_s = work_timeout_s
        load_t = getattr(self.cfg, "load_timeout_s", None)
        self._op_timeout_s = min(work_timeout_s, load_t) if load_t else work_timeout_s
        self._group_lock = threading.RLock()
        self._bcast_pool = ThreadPoolExecutor(
            max_workers=max(4, 2 * len(self._followers)),
            thread_name_prefix="tpusc-bcast",
        )

    # -- broadcast plumbing -------------------------------------------------
    def _post(self, addr: str, body: bytes) -> None:
        req = urllib.request.Request(
            f"http://{addr}{WORK_PATH}", data=body,
            headers={"Content-Type": "application/octet-stream"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self._op_timeout_s) as resp:
                out = json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            # the follower's 500 carries the actual cause in its JSON body —
            # surface it, not just "HTTP Error 500"
            try:
                detail = json.loads(e.read().decode()).get("error", str(e))
            except Exception:  # noqa: BLE001
                detail = str(e)
            raise RuntimeError(f"follower {addr}: {detail}") from None
        if not out.get("ok"):
            raise RuntimeError(f"follower {addr}: {out.get('error')}")

    def _broadcast(self, meta: dict, arrays: Mapping[str, np.ndarray] | None = None):
        # budget_s lets the follower drop items that expire while queued
        # behind its group lock (the leader has long since 504'd them)
        meta = dict(meta, group=self._group_index, budget_s=self._op_timeout_s)
        body = encode_work(meta, arrays)
        return [
            self._bcast_pool.submit(self._post, addr, body)
            for addr in self._followers
        ]

    @staticmethod
    def _join(futures) -> None:
        errs = []
        for f in futures:
            try:
                f.result()
            except Exception as e:  # noqa: BLE001
                errs.append(e)
        if errs:
            raise RuntimeError(
                f"group followers failed: {'; '.join(str(e) for e in errs)}"
            )

    def _run_collective(self, meta, arrays, fn):
        """Fire the broadcast, run the local half of the collective, then
        surface any follower error. The local compute MUST start without
        waiting for follower HTTP responses — they only arrive after the
        followers finish the same collective.

        Failure model: host-side fallible work (artifact fetch) is pushed
        into the joinable prefetch phase (ensure_loaded below), so a
        follower error DURING a collective means divergent device state; the
        jax.distributed coordination service then detects the dead/failed
        task and fails the whole group's processes for a supervisor restart
        — there is no in-band recovery from a half-entered collective."""
        with self._group_lock:
            futures = self._broadcast(meta, arrays)
            try:
                result = fn()
            except BaseException:
                self._join(futures)  # follower errors usually explain ours
                raise
            self._join(futures)
            return result

    # -- collective ops -----------------------------------------------------
    def ensure_loaded(self, model) -> None:
        if self.is_loaded(model.identifier):
            return
        mid = model.identifier
        with self._group_lock:
            # phase 1 (joinable, host-side only): every process fetches the
            # artifact to its local disk; any provider/IO failure surfaces
            # HERE, before a single process enters the warmup collective
            self._join(self._broadcast(
                {"op": "prefetch", "model": mid.name, "version": mid.version}
            ))
            # phase 2 (collective): load + shard + warmup in lockstep
            self._run_collective(
                {"op": "ensure", "model": mid.name, "version": mid.version},
                None,
                lambda: super(MultiHostGroupRuntime, self).ensure_loaded(model),
            )

    def predict(self, model_id, inputs, output_filter=None):
        return self._run_collective(
            {
                "op": "predict", "model": model_id.name,
                "version": model_id.version, "output_filter": output_filter,
            },
            inputs,
            lambda: super(MultiHostGroupRuntime, self).predict(
                model_id, inputs, output_filter
            ),
        )

    def generate(self, model_id, input_ids, prompt_lengths=None,
                 max_new_tokens: int = 32, temperature: float = 0.0,
                 top_k: int = 0, seed: int = 0, draft_model_id=None,
                 spec_tokens: int = 4):
        ids = np.asarray(input_ids, np.int32)
        lengths = (
            np.full((ids.shape[0],), ids.shape[1], np.int32)
            if ids.ndim == 2 and prompt_lengths is None
            else np.asarray(prompt_lengths if prompt_lengths is not None else [], np.int32)
        )
        return self._run_collective(
            {
                "op": "generate", "model": model_id.name,
                "version": model_id.version, "max_new_tokens": max_new_tokens,
                "temperature": temperature, "top_k": top_k, "seed": seed,
                # followers must replay the SAME speculative program: the
                # draft's forwards are collectives too on a sharded group
                "draft_model": draft_model_id.name if draft_model_id else "",
                "draft_version": draft_model_id.version if draft_model_id else 0,
                "spec_tokens": spec_tokens,
            },
            {"input_ids": ids, "prompt_lengths": lengths},
            lambda: super(MultiHostGroupRuntime, self).generate(
                model_id, ids, prompt_lengths=list(lengths),
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, seed=seed, draft_model_id=draft_model_id,
                spec_tokens=spec_tokens,
            ),
        )

    def unload(self, model_id) -> None:
        # unload holds no collectives, but followers must mirror it so the
        # group's LRU states stay in lockstep (divergent eviction would make
        # a later follower re-load run its warmup collective solo)
        with self._group_lock:
            futures = self._broadcast(
                {"op": "unload", "model": model_id.name, "version": model_id.version}
            )
            super().unload(model_id)
            self._join(futures)

    def close(self) -> None:
        self._bcast_pool.shutdown(wait=False, cancel_futures=True)
        super().close()

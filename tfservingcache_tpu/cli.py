"""``tpuserve`` CLI — entry point wiring (reference cmd/taskhandler/main.go:20-43).

Grows with the build: ``serve`` starts the cache node (and the proxy/router
when discovery is configured), ``export`` writes model artifacts.
"""

from __future__ import annotations

import argparse
import sys

from tfservingcache_tpu.config import load_config
from tfservingcache_tpu.utils.logging import get_logger, setup_logging

log = get_logger("cli")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuserve", description=__doc__)
    parser.add_argument("--config", default=None, help="path to config.yaml")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("serve", help="run a cache node (+ proxy when discovery is configured)")
    exp = sub.add_parser("export", help="export a model artifact to a provider dir")
    exp.add_argument("model", help="model family name (see tfservingcache_tpu.models.registry)")
    exp.add_argument("dest", help="destination dir (<base>/<name>/<version> is created)")
    exp.add_argument("--name", default=None)
    exp.add_argument("--version", type=int, default=1)
    exp.add_argument(
        "--quantize", choices=["int8"], default=None,
        help="store large float weights as int8 + per-channel scales "
             "(device dequant at load; halves the cold-path transfer)",
    )
    rep = sub.add_parser(
        "repack",
        help="rewrite an artifact in the current format (tpusc.v1 msgpack -> "
        "tpusc.v2 packed bin; applies the family's storage dtype)",
    )
    rep.add_argument("src", help="existing artifact dir (<...>/<name>/<version>)")
    rep.add_argument("dest", help="output artifact dir")
    args = parser.parse_args(argv)

    cfg = load_config(args.config)
    setup_logging(cfg.logging.level, cfg.logging.fmt)
    if cfg.serving.platform:
        # before any backend init (serve AND export both touch jax): a
        # JAX_PLATFORMS env var alone does not beat an installed PJRT
        # plugin's registration — only the config update reliably selects
        import jax

        jax.config.update("jax_platforms", cfg.serving.platform)

    if args.cmd == "serve":
        from tfservingcache_tpu.server import run_server

        run_server(cfg)
        return 0
    if args.cmd == "export":
        from tfservingcache_tpu.models.registry import export_artifact

        path = export_artifact(args.model, args.dest, name=args.name,
                               version=args.version, quantize=args.quantize)
        print(path)
        return 0
    if args.cmd == "repack":
        import json as _json
        import os as _os

        from tfservingcache_tpu.models.registry import load_artifact, save_artifact

        # carry the source's quantize marker AND bytes through: raw_quant
        # returns QuantLeaf views that save_artifact writes verbatim —
        # dequantize-then-requantize would shift scales and compound error
        # on every repack
        try:
            with open(_os.path.join(args.src, "model.json")) as f:
                src_quant = _json.load(f).get("quantize")
        except (OSError, ValueError):
            src_quant = None
        model, params = load_artifact(args.src, raw_quant=True)
        print(save_artifact(args.dest, model, params, quantize=src_quant))
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())

"""``tpuserve`` CLI — entry point wiring (reference cmd/taskhandler/main.go:20-43).

Grows with the build: ``serve`` starts the cache node (and the proxy/router
when discovery is configured), ``export`` writes model artifacts.
"""

from __future__ import annotations

import argparse
import sys

from tfservingcache_tpu.config import load_config
from tfservingcache_tpu.utils.logging import get_logger, setup_logging

log = get_logger("cli")


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(prog="tpuserve", description=__doc__)
    parser.add_argument("--config", default=None, help="path to config.yaml")
    sub = parser.add_subparsers(dest="cmd", required=True)
    sub.add_parser("serve", help="run a cache node (+ proxy when discovery is configured)")
    exp = sub.add_parser("export", help="export a model artifact to a provider dir")
    exp.add_argument("model", help="model family name (see tfservingcache_tpu.models.registry)")
    exp.add_argument("dest", help="destination dir (<base>/<name>/<version> is created)")
    exp.add_argument("--name", default=None)
    exp.add_argument("--version", type=int, default=1)
    exp.add_argument(
        "--quantize", choices=["int8"], default=None,
        help="store large float weights as int8 + per-channel scales "
             "(device dequant at load; halves the cold-path transfer)",
    )
    exp.add_argument(
        "--config-json", default=None, metavar="JSON",
        help="family config overrides as a JSON object, e.g. "
             '\'{"d_model": 512, "n_layers": 8}\' (merged over the '
             "family's defaults)",
    )
    exp.add_argument("--seed", type=int, default=0,
                     help="parameter init seed")
    rep = sub.add_parser(
        "repack",
        help="rewrite an artifact in the current format (tpusc.v1 msgpack -> "
        "tpusc.v2 packed bin; applies the family's storage dtype)",
    )
    rep.add_argument("src", help="existing artifact dir (<...>/<name>/<version>)")
    rep.add_argument("dest", help="output artifact dir")
    wrm = sub.add_parser(
        "warm",
        help="pre-populate the persistent XLA compile cache "
        "(serving.compile_cache_dir) with an artifact's serving programs — "
        "bake into the deploy image so a node's FIRST cold load is a "
        "compile-cache hit (SURVEY §7: load-bearing for the <=2s target)",
    )
    wrm.add_argument("artifact", help="artifact dir (<...>/<name>/<version>)")
    wrm.add_argument(
        "--batches", default="1,2,4,8",
        help="comma-separated predict batch buckets to compile",
    )
    wrm.add_argument(
        "--lm-seq", type=int, default=128,
        help="prompt length for LM-family predict/generate programs",
    )
    wrm.add_argument(
        "--generate-tokens", type=int, default=32,
        help="decode program length for LM families (0 skips generate)",
    )
    args = parser.parse_args(argv)

    cfg = load_config(args.config)
    setup_logging(cfg.logging.level, cfg.logging.fmt)
    if cfg.serving.platform:
        # before any backend init (serve AND export both touch jax): a
        # JAX_PLATFORMS env var alone does not beat an installed PJRT
        # plugin's registration — only the config update reliably selects
        import jax

        jax.config.update("jax_platforms", cfg.serving.platform)

    if args.cmd == "serve":
        from tfservingcache_tpu.server import run_server

        run_server(cfg)
        return 0
    if args.cmd == "export":
        import json as _json

        from tfservingcache_tpu.models.registry import export_artifact

        config = None
        if args.config_json is not None:
            # empty string falls through json.loads and fails loudly like
            # every other malformed value (a silently-ignored unset $CFG
            # would export defaults the user didn't ask for)
            try:
                config = _json.loads(args.config_json)
                if not isinstance(config, dict):
                    raise ValueError("must be a JSON object")
            except ValueError as e:
                log.error("invalid --config-json: %s", e)
                return 2
        path = export_artifact(args.model, args.dest, name=args.name,
                               version=args.version, seed=args.seed,
                               config=config, quantize=args.quantize)
        print(path)
        return 0
    if args.cmd == "repack":
        import json as _json
        import os as _os

        from tfservingcache_tpu.models.registry import load_artifact, save_artifact

        # carry the source's quantize marker AND bytes through: raw_quant
        # returns QuantLeaf views that save_artifact writes verbatim —
        # dequantize-then-requantize would shift scales and compound error
        # on every repack
        try:
            with open(_os.path.join(args.src, "model.json")) as f:
                src_quant = _json.load(f).get("quantize")
        except (OSError, ValueError):
            src_quant = None
        model, params = load_artifact(args.src, raw_quant=True)
        print(save_artifact(args.dest, model, params, quantize=src_quant))
        return 0
    if args.cmd == "warm":
        return _warm(cfg, args)
    return 2


def _warm(cfg, args) -> int:
    """Compile an artifact's serving programs through the REAL runtime (the
    persisted cache keys must match what `serve` will look up) and leave
    them in the persistent XLA compile cache."""
    import os
    import time

    import numpy as np

    from tfservingcache_tpu.cache.disk_cache import dir_size_bytes
    from tfservingcache_tpu.runtime.model_runtime import TPUModelRuntime
    from tfservingcache_tpu.types import Model, ModelId

    if not cfg.serving.compile_cache_dir:
        log.error(
            "serving.compile_cache_dir is not set: there is no persistent "
            "cache to warm (set it in config.yaml or TPUSC_SERVING_"
            "COMPILE_CACHE_DIR)"
        )
        return 2
    art = os.path.abspath(args.artifact)
    version_s = os.path.basename(art)
    name = os.path.basename(os.path.dirname(art))
    mid = ModelId(name or "model", int(version_s) if version_s.isdigit() else 1)
    rt = TPUModelRuntime(cfg.serving)
    compiled = []
    t0 = time.perf_counter()
    try:
        rt.ensure_loaded(Model(identifier=mid, path=art,
                               size_on_disk=dir_size_bytes(art)))
        in_spec, _, _ = rt.signature(mid)
        family = rt.family_of(mid)
        loaded = rt._resident.get(mid, touch=False)
        max_seq = int(loaded.model_def.config.get("max_seq", 0) or 0)
        seq = args.lm_seq
        gen_tokens = args.generate_tokens
        if max_seq:
            # clamp to what the model can serve: a default 128/32 against a
            # small max_seq must warm the usable shapes, not crash mid-warm
            seq = min(seq, max(1, max_seq // 2))
            gen_tokens = min(gen_tokens, max_seq - seq)
            if (seq, gen_tokens) != (args.lm_seq, args.generate_tokens):
                log.info("clamped to seq=%d, generate_tokens=%d (max_seq %d)",
                         seq, gen_tokens, max_seq)
        for b in sorted({int(x) for x in args.batches.split(",") if x.strip()}):
            inputs = {}
            for nm, spec in in_spec.items():
                # the FIRST dynamic dim of each input is the batch axis,
                # later dynamic dims (LM/bert seq, t5 src/tgt) get --lm-seq
                # — unlike the runtime's load-time _concrete_shape (all
                # dims=1), warm must compile the shapes traffic asks for
                shape, dyn = [], 0
                for d in spec.norm_shape():
                    if isinstance(d, str):
                        shape.append(b if dyn == 0 else seq)
                        dyn += 1
                    else:
                        shape.append(d)
                inputs[nm] = np.zeros(tuple(shape), spec.np_dtype())
            rt.predict(mid, inputs)
            compiled.append(f"predict b={b}")
        if family in ("transformer_lm", "moe_lm") and gen_tokens > 0:
            ids = np.zeros((1, seq), np.int32)
            rt.generate(mid, ids, max_new_tokens=gen_tokens)
            compiled.append(f"generate b=1 new={gen_tokens}")
    finally:
        rt.close()
    dt = time.perf_counter() - t0
    print(
        f"warmed {mid} ({family}): {', '.join(compiled)} in {dt:.1f}s -> "
        f"{cfg.serving.compile_cache_dir}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())

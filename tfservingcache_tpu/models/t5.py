"""T5-style encoder-decoder family (BASELINE.json config #5 lists T5-XL as a
multi-chip shard target alongside Llama). RMSNorm, relative-position bias
buckets, GeGLU feed-forward, tied embeddings — bf16 matmuls, fp32 softmax.

Serving signature: (input_ids, decoder_input_ids) -> decoder logits, the
predict shape for translation/summarization-style fine-tunes.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register

DEFAULT_CONFIG = {
    "vocab_size": 32128,
    "d_model": 512,
    "n_layers": 6,
    "n_heads": 8,
    "d_ff": 1024,
    "rel_buckets": 32,
    "rel_max_dist": 128,
    "dtype": "bfloat16",
}

TINY_CONFIG = {
    "vocab_size": 256,
    "d_model": 64,
    "n_layers": 2,
    "n_heads": 4,
    "d_ff": 128,
    "rel_buckets": 8,
    "rel_max_dist": 32,
    "dtype": "bfloat16",
}


def _rmsnorm(x, gain, eps=1e-6):
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * gain.astype(x.dtype)


def _rel_bucket(rel_pos, bidirectional, num_buckets, max_dist):
    """T5 relative-position bucketing (log-spaced beyond num_buckets//2)."""
    ret = jnp.zeros_like(rel_pos)
    n = -rel_pos
    if bidirectional:
        num_buckets //= 2
        ret = ret + jnp.where(n < 0, num_buckets, 0)
        n = jnp.abs(n)
    else:
        n = jnp.maximum(n, 0)
    max_exact = num_buckets // 2
    is_small = n < max_exact
    large = max_exact + (
        jnp.log(jnp.maximum(n, 1).astype(jnp.float32) / max_exact)
        / math.log(max_dist / max_exact)
        * (num_buckets - max_exact)
    ).astype(jnp.int32)
    large = jnp.minimum(large, num_buckets - 1)
    return ret + jnp.where(is_small, n, large)


def _attn(p, q_in, kv_in, bias, cfg, extra_mask=None):
    b, sq, d = q_in.shape
    sk = kv_in.shape[1]
    h = cfg["n_heads"]
    hd = d // h
    dtype = q_in.dtype
    q = (q_in @ p["wq"]).reshape(b, sq, h, hd).transpose(0, 2, 1, 3)
    k = (kv_in @ p["wk"]).reshape(b, sk, h, hd).transpose(0, 2, 1, 3)
    v = (kv_in @ p["wv"]).reshape(b, sk, h, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    )  # f32 accumulation, not a bf16-accumulated cast
    if bias is not None:
        scores = scores + bias
    if extra_mask is not None:
        scores = jnp.where(extra_mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v).transpose(0, 2, 1, 3).reshape(b, sq, d)
    return ctx @ p["wo"]


def _geglu(p, x):
    return (jax.nn.gelu(x @ p["w0"], approximate=True) * (x @ p["w1"])) @ p["w2"]


def _rel_bias(table, sq, sk, bidirectional, cfg):
    pos_q = jnp.arange(sq)[:, None]
    pos_k = jnp.arange(sk)[None, :]
    buckets = _rel_bucket(
        pos_k - pos_q, bidirectional, cfg["rel_buckets"], cfg["rel_max_dist"]
    )
    return table[buckets].transpose(2, 0, 1)[None].astype(jnp.float32)  # (1,h,sq,sk)


def _forward(params, input_ids, decoder_input_ids, cfg):
    dtype = jnp.dtype(cfg["dtype"])
    cast = lambda tree: jax.tree_util.tree_map(lambda w: w.astype(dtype), tree)

    # Token 0 is the pad token (T5 convention): padded src positions are
    # masked out of encoder self-attention and cross-attention so the
    # runtime's bucket padding cannot change valid-position logits.
    src_valid = (input_ids != 0)[:, None, None, :]  # (b,1,1,s_src)

    # encoder
    x = params["embed"][input_ids].astype(dtype)
    enc_bias = _rel_bias(params["enc_rel"], x.shape[1], x.shape[1], True, cfg)
    for layer in params["enc_layers"]:
        lp = cast(layer)
        x = x + _attn(
            lp["attn"], _rmsnorm(x, layer["ln1"]), _rmsnorm(x, layer["ln1"]),
            enc_bias, cfg, extra_mask=src_valid,
        )
        x = x + _geglu(lp["mlp"], _rmsnorm(x, layer["ln2"]))
    enc_out = _rmsnorm(x, params["enc_ln"])

    # decoder
    y = params["embed"][decoder_input_ids].astype(dtype)
    sq = y.shape[1]
    dec_bias = _rel_bias(params["dec_rel"], sq, sq, False, cfg)
    causal = jnp.tril(jnp.ones((sq, sq), bool))[None, None]
    for layer in params["dec_layers"]:
        lp = cast(layer)
        y = y + _attn(
            lp["self_attn"], _rmsnorm(y, layer["ln1"]), _rmsnorm(y, layer["ln1"]),
            dec_bias, cfg, extra_mask=causal,
        )
        y = y + _attn(
            lp["cross_attn"], _rmsnorm(y, layer["ln2"]), enc_out, None, cfg,
            extra_mask=src_valid,
        )
        y = y + _geglu(lp["mlp"], _rmsnorm(y, layer["ln3"]))
    y = _rmsnorm(y, params["dec_ln"])
    # tied embedding head, T5 1/sqrt(d) scaling
    return ((y / math.sqrt(cfg["d_model"])) @ params["embed"].astype(dtype).T).astype(
        jnp.float32
    )


@register("t5", DEFAULT_CONFIG)
def build(config: dict) -> ModelDef:
    cfg = config

    def apply(params, inputs):
        logits = _forward(
            params,
            inputs["input_ids"].astype(jnp.int32),
            inputs["decoder_input_ids"].astype(jnp.int32),
            cfg,
        )
        return {"logits": logits}

    def init(rng):
        d, ff, v, h = cfg["d_model"], cfg["d_ff"], cfg["vocab_size"], cfg["n_heads"]
        keys = jax.random.split(rng, 2 * cfg["n_layers"] + 3)

        def dense(key, fan_in, shape):
            return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

        def attn_p(key):
            ks = jax.random.split(key, 4)
            return {
                "wq": dense(ks[0], d, (d, d)),
                "wk": dense(ks[1], d, (d, d)),
                "wv": dense(ks[2], d, (d, d)),
                "wo": dense(ks[3], d, (d, d)),
            }

        def mlp_p(key):
            ks = jax.random.split(key, 3)
            return {
                "w0": dense(ks[0], d, (d, ff)),
                "w1": dense(ks[1], d, (d, ff)),
                "w2": dense(ks[2], ff, (ff, d)),
            }

        enc_layers = []
        for i in range(cfg["n_layers"]):
            ks = jax.random.split(keys[i], 2)
            enc_layers.append(
                {"attn": attn_p(ks[0]), "mlp": mlp_p(ks[1]),
                 "ln1": jnp.ones((d,)), "ln2": jnp.ones((d,))}
            )
        dec_layers = []
        for i in range(cfg["n_layers"]):
            ks = jax.random.split(keys[cfg["n_layers"] + i], 3)
            dec_layers.append(
                {
                    "self_attn": attn_p(ks[0]),
                    "cross_attn": attn_p(ks[1]),
                    "mlp": mlp_p(ks[2]),
                    "ln1": jnp.ones((d,)), "ln2": jnp.ones((d,)), "ln3": jnp.ones((d,)),
                }
            )
        return {
            "embed": dense(keys[-3], d, (v, d)),
            "enc_rel": dense(keys[-2], 1, (cfg["rel_buckets"], h)),
            "dec_rel": dense(keys[-1], 1, (cfg["rel_buckets"], h)),
            "enc_layers": enc_layers,
            "dec_layers": dec_layers,
            "enc_ln": jnp.ones((d,)),
            "dec_ln": jnp.ones((d,)),
        }

    def loss(params, inputs, targets):
        logits = _forward(
            params,
            inputs["input_ids"].astype(jnp.int32),
            inputs["decoder_input_ids"].astype(jnp.int32),
            cfg,
        )
        labels = targets["labels"].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    partition_rules = {
        r"embed": (None, "model"),
        r"(enc|dec)_layers/\d+/(self_|cross_)?attn/w[qkv]": (None, "model"),
        r"(enc|dec)_layers/\d+/(self_|cross_)?attn/wo": ("model", None),
        r"(enc|dec)_layers/\d+/mlp/w[01]": (None, "model"),
        r"(enc|dec)_layers/\d+/mlp/w2": ("model", None),
    }

    return ModelDef(
        family="t5",
        config=cfg,
        apply=apply,
        init=init,
        input_spec={
            "input_ids": TensorSpec("int32", ("batch", "src")),
            "decoder_input_ids": TensorSpec("int32", ("batch", "tgt")),
        },
        output_spec={"logits": TensorSpec("float32", ("batch", "tgt", cfg["vocab_size"]))},
        partition_rules=partition_rules,
        loss=loss,
        # apply casts to cfg dtype; bf16 artifacts halve the cold transfer
        store_param_dtype=cfg["dtype"],
    )

"""BERT encoder family (BASELINE.json config #3: 100 per-tenant fine-tunes
served from object storage). Bidirectional transformer encoder with a
pooled classification head — the shape of a per-tenant fine-tune fleet:
every tenant shares the arch (one XLA executable via the registry build
cache) and differs only in weights.

bf16 matmuls on the MXU, fp32 softmax/LN. Attention is mask-additive jnp
(BERT sequences are <=512; the flash kernel's win is long-sequence memory,
not this regime).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register

DEFAULT_CONFIG = {
    "vocab_size": 30522,
    "hidden": 768,
    "n_layers": 12,
    "n_heads": 12,
    "d_ff": 3072,
    "max_seq": 512,
    "type_vocab": 2,
    "num_labels": 2,
    "dtype": "bfloat16",
}

TINY_CONFIG = {
    "vocab_size": 512,
    "hidden": 64,
    "n_layers": 2,
    "n_heads": 4,
    "d_ff": 128,
    "max_seq": 64,
    "type_vocab": 2,
    "num_labels": 3,
    "dtype": "bfloat16",
}


def _layernorm(x, gain, bias, eps=1e-12):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * gain + bias).astype(x.dtype)


def _encoder_layer(p, x, mask_bias, cfg):
    b, s, d = x.shape
    h = cfg["n_heads"]
    hd = d // h
    dtype = x.dtype

    q = (x @ p["wq"] + p["bq"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    k = (x @ p["wk"] + p["bk"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    v = (x @ p["wv"] + p["bv"]).reshape(b, s, h, hd).transpose(0, 2, 1, 3)
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / math.sqrt(hd)  # f32 ACCUMULATION, not a bf16-accumulated cast
    scores = scores + mask_bias  # (b,1,1,s) additive -inf on padding
    probs = jax.nn.softmax(scores, axis=-1).astype(dtype)
    ctx = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    ctx = ctx.transpose(0, 2, 1, 3).reshape(b, s, d)
    x = _layernorm(x + (ctx @ p["wo"] + p["bo"]), p["ln1_g"], p["ln1_b"])
    ff = jax.nn.gelu(x @ p["w1"] + p["b1"], approximate=True)
    x = _layernorm(x + (ff @ p["w2"] + p["b2"]), p["ln2_g"], p["ln2_b"])
    return x


def _forward(params, input_ids, attention_mask, cfg):
    dtype = jnp.dtype(cfg["dtype"])
    s = input_ids.shape[1]
    if s > cfg["max_seq"]:
        # trace-time check: beyond the table, pos_emb gathers silently clamp
        # and return confident garbage
        raise ValueError(f"sequence length {s} exceeds max_seq {cfg['max_seq']}")
    x = (
        params["word_emb"][input_ids]
        + params["pos_emb"][jnp.arange(s)][None]
        + params["type_emb"][jnp.zeros_like(input_ids)]
    ).astype(dtype)
    x = _layernorm(x, params["emb_ln_g"], params["emb_ln_b"])
    mask_bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, -1e30).astype(
        jnp.float32
    )
    for layer in params["layers"]:
        lp = jax.tree_util.tree_map(lambda w: w.astype(dtype), layer)
        lp["ln1_g"], lp["ln1_b"] = layer["ln1_g"], layer["ln1_b"]
        lp["ln2_g"], lp["ln2_b"] = layer["ln2_g"], layer["ln2_b"]
        x = _encoder_layer(lp, x, mask_bias, cfg)
    pooled = jnp.tanh(x[:, 0, :] @ params["pool_w"].astype(dtype) + params["pool_b"])
    logits = (pooled @ params["cls_w"].astype(dtype) + params["cls_b"]).astype(jnp.float32)
    return logits, pooled.astype(jnp.float32)


@register("bert", DEFAULT_CONFIG)
def build(config: dict) -> ModelDef:
    cfg = config

    def apply(params, inputs):
        logits, pooled = _forward(
            params,
            inputs["input_ids"].astype(jnp.int32),
            inputs["attention_mask"].astype(jnp.int32),
            cfg,
        )
        return {"logits": logits, "pooled_output": pooled}

    def init(rng):
        d, ff, v = cfg["hidden"], cfg["d_ff"], cfg["vocab_size"]
        keys = jax.random.split(rng, cfg["n_layers"] + 2)

        def dense(key, fan_in, shape):
            return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

        layers = []
        for i in range(cfg["n_layers"]):
            ks = jax.random.split(keys[i], 6)
            layers.append(
                {
                    "wq": dense(ks[0], d, (d, d)), "bq": jnp.zeros((d,)),
                    "wk": dense(ks[1], d, (d, d)), "bk": jnp.zeros((d,)),
                    "wv": dense(ks[2], d, (d, d)), "bv": jnp.zeros((d,)),
                    "wo": dense(ks[3], d, (d, d)), "bo": jnp.zeros((d,)),
                    "w1": dense(ks[4], d, (d, ff)), "b1": jnp.zeros((ff,)),
                    "w2": dense(ks[5], ff, (ff, d)), "b2": jnp.zeros((d,)),
                    "ln1_g": jnp.ones((d,)), "ln1_b": jnp.zeros((d,)),
                    "ln2_g": jnp.ones((d,)), "ln2_b": jnp.zeros((d,)),
                }
            )
        k_emb, k_head = keys[-2], keys[-1]
        ke = jax.random.split(k_emb, 3)
        kh = jax.random.split(k_head, 2)
        return {
            "word_emb": dense(ke[0], d, (v, d)),
            "pos_emb": dense(ke[1], d, (cfg["max_seq"], d)),
            "type_emb": dense(ke[2], d, (cfg["type_vocab"], d)),
            "emb_ln_g": jnp.ones((d,)), "emb_ln_b": jnp.zeros((d,)),
            "layers": layers,
            "pool_w": dense(kh[0], d, (d, d)), "pool_b": jnp.zeros((d,)),
            "cls_w": dense(kh[1], d, (d, cfg["num_labels"])),
            "cls_b": jnp.zeros((cfg["num_labels"],)),
        }

    def loss(params, inputs, targets):
        logits, _ = _forward(
            params,
            inputs["input_ids"].astype(jnp.int32),
            inputs["attention_mask"].astype(jnp.int32),
            cfg,
        )
        labels = jax.nn.one_hot(targets["label"], cfg["num_labels"])
        return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))

    partition_rules = {
        r"layers/\d+/w[qkv]": (None, "model"),
        r"layers/\d+/wo": ("model", None),
        r"layers/\d+/w1": (None, "model"),
        r"layers/\d+/w2": ("model", None),
        r"word_emb": (None, "model"),
    }

    return ModelDef(
        family="bert",
        config=cfg,
        apply=apply,
        init=init,
        input_spec={
            "input_ids": TensorSpec("int32", ("batch", "seq")),
            "attention_mask": TensorSpec("int32", ("batch", "seq")),
        },
        output_spec={
            "logits": TensorSpec("float32", (-1, cfg["num_labels"])),
            "pooled_output": TensorSpec("float32", (-1, cfg["hidden"])),
        },
        partition_rules=partition_rules,
        # the absolute pos_emb table bounds servable sequence length; the
        # runtime clamps its padding bucket here so a 300-token request under
        # max_seq=384 pads to 384, not 512 (which _forward would reject)
        axis_caps={"seq": cfg["max_seq"]},
        loss=loss,
    )

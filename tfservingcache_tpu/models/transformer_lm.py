"""transformer_lm — the flagship decoder-LM family (Llama/T5-XL-class,
BASELINE.json config #5: models that span >1 TPU chip, served by chip
groups the ring assigns).

TPU-first design:
  - bf16 matmuls (MXU), fp32 softmax/norm accumulation;
  - Pallas flash attention on TPU (ops/attention.py), jnp fallback on CPU;
  - pure-functional params pytree with explicit tensor-parallel partition
    rules (megatron-style: attention/MLP sharded over the "model" mesh axis,
    collectives inserted by XLA from the shardings — no hand-written NCCL,
    SURVEY.md §2 distributed-backend inventory);
  - weights stored in the serving dtype (bf16) in the artifact — the cold
    path is host->HBM bandwidth-bound, so artifact bytes are the latency.

Config presets cover smoke tests through llama-7b-class shapes.
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register
from tfservingcache_tpu.ops.attention import attention

DEFAULT_CONFIG: dict[str, Any] = {
    "vocab_size": 2048,
    "d_model": 256,
    "n_layers": 4,
    "n_heads": 8,
    "n_kv_heads": 4,       # GQA
    "d_ff": 1024,
    "max_seq": 1024,
    "rope_theta": 10000.0,
    "dtype": "bfloat16",
    # "auto" = flash kernel on TPU / jnp elsewhere. "ring" = context
    # parallelism: the sequence axis is sharded over the serving chip group
    # and K/V blocks rotate by ppermute (parallel/ring_attention.py) — for
    # long-context models whose attention working set exceeds one chip.
    "attention": "auto",
}

# llama-2-7b-class shape for multi-chip serving/benching
LLAMA7B_CONFIG: dict[str, Any] = {
    "vocab_size": 32000,
    "d_model": 4096,
    "n_layers": 32,
    "n_heads": 32,
    "n_kv_heads": 32,
    "d_ff": 11008,
    "max_seq": 4096,
    "rope_theta": 10000.0,
    "dtype": "bfloat16",
}


def _rmsnorm(x: jax.Array, gain: jax.Array, eps: float = 1e-5) -> jax.Array:
    x32 = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * scale).astype(x.dtype) * gain.astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding over (B, H, S, D)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)      # (d/2,)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]     # (S, d/2)
    cos = jnp.cos(angles)[None, None]                                    # (1,1,S,d/2)
    sin = jnp.sin(angles)[None, None]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.reshape(x.shape).astype(x.dtype)


def _attention_block(params: dict, x: jax.Array, cfg: dict, mesh=None) -> jax.Array:  # static-bounded: mesh -- one Mesh object per runtime lifetime
    b, s, d_model = x.shape
    n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
    head_dim = d_model // n_heads
    q = (x @ params["wq"]).reshape(b, s, n_heads, head_dim).transpose(0, 2, 1, 3)
    k = (x @ params["wk"]).reshape(b, s, n_kv, head_dim).transpose(0, 2, 1, 3)
    v = (x @ params["wv"]).reshape(b, s, n_kv, head_dim).transpose(0, 2, 1, 3)
    positions = jnp.arange(s)
    q = _rope(q, positions, cfg["rope_theta"])
    k = _rope(k, positions, cfg["rope_theta"])
    if (
        mesh is not None
        and cfg.get("attention") == "ring"
        and s % mesh.shape.get("model", 1) == 0
        and mesh.shape.get("model", 1) > 1
    ):
        # context parallelism: sequence sharded over the group's chips, K/V
        # rotating by ppermute — sequences too short for the ring (bucket <
        # group size) fall through to regular attention below
        from tfservingcache_tpu.parallel.ring_attention import ring_attention

        out = ring_attention(q, k, v, mesh, axis="model", causal=True)
    else:
        # GQA handled inside attention (grouped K/V, never materialized via
        # repeat — that would negate GQA's HBM saving at llama-7b scale)
        out = attention(q, k, v, causal=True)                           # (b,h,s,hd)
    out = out.transpose(0, 2, 1, 3).reshape(b, s, d_model)
    return out @ params["wo"]


def _mlp_block(params: dict, x: jax.Array) -> jax.Array:
    gate = jax.nn.silu(x @ params["w1"])
    up = x @ params["w3"]
    return (gate * up) @ params["w2"]


def _forward(params: dict, input_ids: jax.Array, cfg: dict, mesh=None) -> jax.Array:
    dtype = jnp.dtype(cfg["dtype"])
    x = params["embed"][input_ids].astype(dtype)                        # (b,s,d)
    for layer in params["layers"]:
        x = x + _attention_block(
            jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["attn"]),
            _rmsnorm(x, layer["ln1"]),
            cfg,
            mesh,
        )
        x = x + _mlp_block(
            jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["mlp"]),
            _rmsnorm(x, layer["ln2"]),
        )
    x = _rmsnorm(x, params["ln_f"])
    # logits in f32 for a stable softmax/argmax downstream
    return (x @ params["embed"].astype(dtype).T).astype(jnp.float32)


@register("transformer_lm", DEFAULT_CONFIG)
def build(config: dict) -> ModelDef:
    cfg = config
    ring = cfg.get("attention") == "ring"
    if ring and cfg["n_heads"] != cfg["n_kv_heads"]:
        raise ValueError(
            "attention='ring' requires n_heads == n_kv_heads (the ring "
            "rotates full K/V blocks; grouped-KV ring is not implemented)"
        )

    def make_apply(mesh=None):
        def apply(params, inputs):
            # logits only: the runtime pads the sequence axis to shape
            # buckets, and causal masking keeps valid positions exact — but
            # any "last token" reduction would land on padding, so sampling
            # stays client-side (or in the generate helper, which tracks
            # true lengths).
            logits = _forward(
                params, inputs["input_ids"].astype(jnp.int32), cfg, mesh
            )
            return {"logits": logits}

        return apply

    apply = make_apply(None)

    def init(rng):
        d, v, ff = cfg["d_model"], cfg["vocab_size"], cfg["d_ff"]
        n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
        head_dim = d // n_heads
        keys = jax.random.split(rng, cfg["n_layers"] + 1)

        def dense(key, fan_in, shape):
            return (jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in))

        layers = []
        for i in range(cfg["n_layers"]):
            ks = jax.random.split(keys[i], 7)
            layers.append(
                {
                    "attn": {
                        "wq": dense(ks[0], d, (d, n_heads * head_dim)),
                        "wk": dense(ks[1], d, (d, n_kv * head_dim)),
                        "wv": dense(ks[2], d, (d, n_kv * head_dim)),
                        "wo": dense(ks[3], n_heads * head_dim, (n_heads * head_dim, d)),
                    },
                    "mlp": {
                        "w1": dense(ks[4], d, (d, ff)),
                        "w2": dense(ks[5], ff, (ff, d)),
                        "w3": dense(ks[6], d, (d, ff)),
                    },
                    "ln1": jnp.ones((d,), jnp.float32),
                    "ln2": jnp.ones((d,), jnp.float32),
                }
            )
        return {
            "embed": dense(keys[-1], d, (v, d)),
            "layers": layers,
            "ln_f": jnp.ones((d,), jnp.float32),
        }

    def loss(params, inputs, targets):
        logits = _forward(params, inputs["input_ids"].astype(jnp.int32), cfg)
        labels = targets["labels"].astype(jnp.int32)
        # next-token cross entropy, ignoring the final position
        logp = jax.nn.log_softmax(logits[:, :-1, :], axis=-1)
        tgt = labels[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(nll)

    if ring:
        # context parallelism owns the group's mesh axis for the SEQUENCE;
        # weights replicate (rule matches everything -> PartitionSpec())
        partition_rules = {r".*": ()}
    else:
        # Megatron-style tensor parallelism over the "model" mesh axis:
        # column-parallel QKV/W1/W3, row-parallel WO/W2 (XLA inserts the
        # all-reduces).
        partition_rules = {
            "embed": (None, "model"),
            r"layers/\d+/attn/w[qkv]": (None, "model"),
            r"layers/\d+/attn/wo": ("model", None),
            r"layers/\d+/mlp/w[13]": (None, "model"),
            r"layers/\d+/mlp/w2": ("model", None),
            r".*ln.*": (None,),
        }

    def last_token_logits(outputs, dyn_sizes):
        """Device-side slice at the last REAL position (runtime pads seq to a
        bucket, so -1 would land on padding). Ships (B, V) to host instead of
        (B, S, V) — the LM warm-path fix. Rows share one true length; ragged
        prompts belong to :generate, which tracks per-row lengths."""
        logits = outputs["logits"]
        s = dyn_sizes.get("seq", logits.shape[1])
        b = dyn_sizes.get("batch", logits.shape[0])
        return logits[:b, s - 1, :]

    return ModelDef(
        family="transformer_lm",
        config=cfg,
        apply=apply,
        init=init,
        input_spec={"input_ids": TensorSpec("int32", ("batch", "seq"))},
        output_spec={"logits": TensorSpec("float32", ("batch", "seq", cfg["vocab_size"]))},
        partition_rules=partition_rules,
        loss=loss,
        derived_outputs={
            "last_token_logits": (
                last_token_logits,
                TensorSpec("float32", ("batch", cfg["vocab_size"])),
            )
        },
        # out-of-box predict ships the (B, V) next-token logits; the full
        # (B, S, V) tensor is opt-in via output_filter=["logits"] (at seq 128
        # vocab 4096 that's 8 MB of f32 per request — the round-2 0.5 qps)
        default_outputs=["last_token_logits"],
        # apply casts weights to cfg dtype anyway; storing them f32 doubled
        # the cold-path transfer (round-2 cold p50 3.14 s was ~80% device_put)
        store_param_dtype=cfg["dtype"],
        # ring mode needs the serving group's mesh inside the computation
        bind_mesh=make_apply if ring else None,
    )

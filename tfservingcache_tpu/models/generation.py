"""KV-cached autoregressive generation for the decoder-LM families
(transformer_lm, moe_lm — both share the attention/cache layout; the FFN
half is pluggable: dense silu-gate MLP vs routed expert block).

No reference counterpart (the reference proxies opaque Predict calls —
SURVEY.md §5); generation is where a TPU-native LM server must not re-run
the full sequence per token. Design:

  - prefill: one full forward over the prompt that also WRITES each layer's
    K/V into a preallocated (B, n_kv, max_len, head_dim) cache — the prompt
    is processed at MXU-friendly width once;
  - decode: a ``lax.scan`` over new tokens, each step attending one query
    position against the cache — static shapes, one compiled program for
    the whole generation, no per-token Python dispatch;
  - sampling: greedy or temperature/top-k, PRNG threaded through the scan.

The whole generate (prefill + scan + sampling) is a single jittable
function: compile once per (batch, prompt-bucket, max_new_tokens) and reuse.
"""

from __future__ import annotations

import functools
import math
import warnings
from typing import Any

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.transformer_lm import _rmsnorm

# The slot-decode jits donate their K/V buffers (in-place update on TPU);
# CPU/interpreter backends cannot honor donation and warn on EVERY dispatch
# — steady-state noise at chunk cadence on the test harness, carrying no
# action. The donation itself stays: it is the difference between rewriting
# and reallocating a (layers, S, n_kv, max_seq, hd) array per chunk on HBM.
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable"
)


def init_cache(cfg: dict, batch: int, max_len: int, mesh=None) -> dict:
    """Preallocated per-layer K/V buffers. bf16 storage halves HBM traffic;
    attention still accumulates in f32. ``mesh`` commits the buffers to
    KV-head shardings (parallel/sharding.kv_arena_shardings) so the slot
    jits compile partitioned programs from day one."""
    n_kv = cfg["n_kv_heads"]
    head_dim = cfg["d_model"] // cfg["n_heads"]
    dtype = jnp.dtype(cfg["dtype"])
    cache = {
        "k": jnp.zeros((cfg["n_layers"], batch, n_kv, max_len, head_dim), dtype),
        "v": jnp.zeros((cfg["n_layers"], batch, n_kv, max_len, head_dim), dtype),
    }
    if mesh is not None:
        from tfservingcache_tpu.parallel.sharding import shard_kv_arena

        cache = shard_kv_arena(cache, mesh)
    return cache


def _sample(logits, rng, temperature, top_k):
    """logits (B, V) -> token ids (B,).

    ``temperature`` and ``top_k`` are TRACED scalars, not compile-time
    constants: both arrive straight from the unauthenticated ``:generate``
    request body, and a static argname would mint (and cache forever) a fresh
    XLA compile of the whole prefill+scan program per novel value — a
    compile-DoS vector. One compiled program now serves every sampling
    config: temperature<=0 selects greedy, top_k<=0 (or >= vocab) disables
    top-k filtering, all via in-graph selects.
    """
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = jnp.clip(jnp.asarray(top_k, jnp.int32), 0, v)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kth = sorted_desc[:, jnp.clip(k - 1, 0, v - 1)][:, None]
    thresh = jnp.where((k > 0) & (k < v), kth, -jnp.inf)
    filt = jnp.where(logits < thresh, -1e30, logits)
    temp = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    sampled = jax.random.categorical(rng, filt / temp, axis=-1).astype(jnp.int32)
    return jnp.where(jnp.asarray(temperature, jnp.float32) <= 0.0, greedy, sampled)


def _decode_scan(params, cache, first_tok, start_pos, rng, temperature,
                 top_k, cfg, family, max_new_tokens: int):
    """The shared sampling scan: ``first_tok`` sits at ``start_pos`` (not
    yet in cache); emits max_new_tokens including it. The rng split
    structure is FIXED (one split per step) so the plain and from-cache
    paths draw identical streams for the same seed."""

    def step(carry, _):
        cache, tok, pos, rng = carry
        logits, cache = _forward_cached_dyn(
            params, tok[:, None], cache, pos, cfg, family
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits[:, 0], sub, temperature, top_k)
        return (cache, nxt, pos + 1, rng), tok

    (cache, _, _, _), toks = jax.lax.scan(
        step, (cache, first_tok, start_pos, rng), None, length=max_new_tokens
    )
    return jnp.transpose(toks, (1, 0)), cache  # (B, max_new_tokens)


@functools.partial(
    jax.jit,
    static_argnames=("cfg_key", "max_new_tokens", "family", "return_cache"),
)
def _generate_jit(
    params,
    input_ids,
    prompt_len,
    rng,
    temperature,
    top_k,
    *,
    cfg_key,
    max_new_tokens: int,
    family: str = "transformer_lm",
    return_cache: bool = False,
):
    cfg = dict(cfg_key)
    b, s_max = input_ids.shape
    max_len = s_max + max_new_tokens
    cache = init_cache(cfg, b, max_len)

    # prefill the (right-padded) prompt block — the start_pos = 0 case of the
    # per-example forward; padding positions write junk K/V but the per-step
    # mask keeps them invisible until overwritten
    logits, cache = _forward_cached_dyn(
        params, input_ids, cache, jnp.zeros((b,), jnp.int32), cfg, family
    )
    # last REAL prompt token's logits seed the first sampled token
    last = jnp.take_along_axis(logits, (prompt_len - 1)[:, None, None], axis=1)[:, 0]
    rng, sub = jax.random.split(rng)
    tok = _sample(last, sub, temperature, top_k)

    toks, cache = _decode_scan(
        params, cache, tok, prompt_len, rng, temperature, top_k, cfg, family,
        max_new_tokens,
    )
    if return_cache:
        return toks, cache["k"], cache["v"]
    return toks


@functools.partial(
    jax.jit,
    static_argnames=("cfg_key", "max_new_tokens", "family", "return_cache"),
)
def _generate_from_cache_jit(
    params,
    suffix_ids,          # (1, S_suffix_pad) — prompt tokens AFTER the prefix
    suffix_len,          # (1,) true suffix length
    cached_k,            # (layers, 1, n_kv, Lpad, head_dim)
    cached_v,
    cached_len,          # (1,) valid prefix rows (the rest is masked junk)
    rng,
    temperature,
    top_k,
    *,
    cfg_key,
    max_new_tokens: int,
    family: str = "transformer_lm",
    return_cache: bool = False,
):
    """Continue from a cached prompt-prefix KV: copy the prefix rows in,
    prefill ONLY the suffix, then the shared decode scan. Junk rows beyond
    ``cached_len`` (entry padding / stale tail) are overwritten by the
    suffix prefill and the per-step writes before any query can see them —
    the same argument that makes plain prefill's pad rows safe."""
    cfg = dict(cfg_key)
    b, s_pad = suffix_ids.shape
    l_pad = cached_k.shape[3]
    max_len = l_pad + s_pad + max_new_tokens
    cache = init_cache(cfg, b, max_len)
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], cached_k.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], cached_v.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
        ),
    }
    start = cached_len.astype(jnp.int32)                  # (1,)
    logits, cache = _forward_cached_dyn(
        params, suffix_ids, cache, start, cfg, family
    )
    last = jnp.take_along_axis(
        logits, (suffix_len - 1)[:, None, None], axis=1
    )[:, 0]
    rng, sub = jax.random.split(rng)
    tok = _sample(last, sub, temperature, top_k)

    toks, cache = _decode_scan(
        params, cache, tok, start + suffix_len, rng, temperature, top_k,
        cfg, family, max_new_tokens,
    )
    if return_cache:
        return toks, cache["k"], cache["v"]
    return toks


def _sample_per_row(logits, rng, temperature, top_k):
    """Per-row sampling params: logits (S, V), temperature (S,) f32,
    top_k (S,) i32 -> token ids (S,). The continuous engine packs unrelated
    requests into one slot array, so each lane carries its own sampling
    config; the values stay TRACED for the same compile-DoS reason as
    ``_sample``. One categorical draw covers all rows (matches the batched
    stream structure)."""
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = jnp.clip(top_k.astype(jnp.int32), 0, v)
    sorted_desc = jnp.sort(logits, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(
        sorted_desc, jnp.clip(k - 1, 0, v - 1)[:, None], axis=-1
    )
    thresh = jnp.where(((k > 0) & (k < v))[:, None], kth, -jnp.inf)
    filt = jnp.where(logits < thresh, -1e30, logits)
    temp = jnp.maximum(temperature.astype(jnp.float32), 1e-6)[:, None]
    sampled = jax.random.categorical(rng, filt / temp, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


@functools.partial(jax.jit, static_argnames=("cfg_key", "family"))
def _slot_prefill_jit(
    params,
    input_ids,           # (1, S_pad) right-padded prompt
    prompt_len,          # (1,)
    rng,
    temperature,         # scalar f32
    top_k,               # scalar i32
    *,
    cfg_key,
    family: str = "transformer_lm",
):
    """Prefill ONE prompt into a fresh (1, S_pad)-row cache and sample the
    request's first token — the admission half of the continuous engine.
    Returns (first_tok (1,), k, v, last_logits (1, V) f32); the first
    token's own K/V is NOT yet in the cache (it sits at pos=prompt_len,
    written by the first decode-chunk step — the same convention as
    ``_decode_scan``'s first_tok). The last-position logits ride along so
    the shared-prefix index can cache them: an exact re-admission of the
    same prompt samples its first token from these under its own seed and
    skips prefill compute entirely."""
    cfg = dict(cfg_key)
    b, s_max = input_ids.shape
    cache = init_cache(cfg, b, s_max)
    logits, cache = _forward_cached_dyn(
        params, input_ids, cache, jnp.zeros((b,), jnp.int32), cfg, family
    )
    last = jnp.take_along_axis(logits, (prompt_len - 1)[:, None, None], axis=1)[:, 0]
    _, sub = jax.random.split(rng)
    tok = _sample(last, sub, temperature, top_k)
    return tok, cache["k"], cache["v"], last


@functools.partial(jax.jit, static_argnames=("cfg_key", "family"))
def _slot_prefill_from_cache_jit(
    params,
    suffix_ids,          # (1, S_suffix_pad)
    suffix_len,          # (1,)
    cached_k,            # (layers, 1, n_kv, Lpad, head_dim)
    cached_v,
    cached_len,          # (1,)
    rng,
    temperature,
    top_k,
    *,
    cfg_key,
    family: str = "transformer_lm",
):
    """Admission prefill continuing from a prefix-cache hit: copy the prefix
    rows, prefill only the suffix, sample the first token. Same junk-row
    safety argument as ``_generate_from_cache_jit``. Returns the
    last-position logits too (same contract as ``_slot_prefill_jit``)."""
    cfg = dict(cfg_key)
    b, s_pad = suffix_ids.shape
    l_pad = cached_k.shape[3]
    cache = init_cache(cfg, b, l_pad + s_pad)
    cache = {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], cached_k.astype(cache["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], cached_v.astype(cache["v"].dtype), (0, 0, 0, 0, 0)
        ),
    }
    start = cached_len.astype(jnp.int32)
    logits, cache = _forward_cached_dyn(
        params, suffix_ids, cache, start, cfg, family
    )
    last = jnp.take_along_axis(
        logits, (suffix_len - 1)[:, None, None], axis=1
    )[:, 0]
    _, sub = jax.random.split(rng)
    tok = _sample(last, sub, temperature, top_k)
    return tok, cache["k"], cache["v"], last


@jax.jit
def _sample_logits_jit(last, rng, temperature, top_k):
    """Sample one first token from CACHED last-position logits — the
    shared-prefix index's exact-hit path, replacing the whole prefill
    dispatch. The split-then-sample sequence is byte-identical to
    ``_slot_prefill_jit``'s tail, so an exact hit and a cold prefill of
    the same prompt produce the same token under the same seed."""
    _, sub = jax.random.split(rng)
    return _sample(last, sub, temperature, top_k)


@functools.partial(jax.jit, donate_argnums=(0, 1))
def _slot_insert_jit(slot_k, slot_v, pk, pv, idx):
    """Copy one admitted request's prefill K/V (layers, 1, n_kv, P_pad, hd)
    into slot row ``idx`` of the slot array (layers, S, n_kv, max_seq, hd).
    ``idx`` is traced, so one compile serves every slot; donation makes the
    copy in-place instead of reallocating the (large) slot array. Rows
    beyond P_pad keep a previous occupant's stale K/V — never visible: a
    query at pos p sees only rows <= p, and the decode step writes row p
    before attending (the same write-before-read argument as prefill
    padding)."""
    idx = idx.astype(jnp.int32)
    k = jax.lax.dynamic_update_slice(
        slot_k, pk.astype(slot_k.dtype), (0, idx, 0, 0, 0)
    )
    v = jax.lax.dynamic_update_slice(
        slot_v, pv.astype(slot_v.dtype), (0, idx, 0, 0, 0)
    )
    return k, v


@functools.partial(
    jax.jit,
    static_argnames=("cfg_key", "family", "chunk"),
    donate_argnums=(1, 2),
)
def _decode_chunk_jit(
    params,
    slot_k,              # (layers, S, n_kv, max_seq, head_dim) — donated
    slot_v,
    tok,                 # (S,) last sampled token per slot
    pos,                 # (S,) i32 write position per slot
    active,              # (S,) bool — frozen for the whole chunk
    rngs,                # (chunk, 2) uint32 — one PRNG key per step
    temperature,         # (S,) f32 per-slot
    top_k,               # (S,) i32 per-slot
    *,
    cfg_key,
    family: str = "transformer_lm",
    chunk: int,
):
    """Advance every ACTIVE slot by ``chunk`` decode steps in one compiled
    program — the continuous engine's only steady-state dispatch. Inactive
    lanes ride along: their token/pos are frozen (``where(active, ...)``)
    so each step just rewrites the same K/V at the frozen pos — junk for
    never-admitted slots, a no-op rewrite for retired ones — and the host
    ignores their emitted tokens. Admission/retirement happen on the host
    BETWEEN chunks; a row finishing mid-chunk keeps decoding from its own
    EOS until the chunk ends (the < chunk overshoot the wasted-steps
    counter measures)."""
    cfg = dict(cfg_key)

    def step(carry, rng):
        k, v, tok, pos = carry
        logits, cache = _forward_cached_dyn(
            params, tok[:, None], {"k": k, "v": v}, pos, cfg, family
        )
        nxt = _sample_per_row(logits[:, 0], rng, temperature, top_k)
        nxt = jnp.where(active, nxt, tok)
        pos = pos + active.astype(jnp.int32)
        return (cache["k"], cache["v"], nxt, pos), nxt

    (slot_k, slot_v, tok, pos), toks = jax.lax.scan(
        step, (slot_k, slot_v, tok, pos), rngs, length=chunk
    )
    return slot_k, slot_v, tok, pos, jnp.transpose(toks, (1, 0))  # (S, chunk)


def init_paged_cache(cfg: dict, n_pages: int, page_tokens: int,
                     arena_dtype: str = "", mesh=None) -> dict:
    """Preallocated paged KV arena shared by every lane of one model's
    continuous-decode state: fixed-size pages instead of per-lane
    ``max_seq`` rows, so HBM is sized by tokens in flight, not worst case.
    Page 0 is the TRASH page — never handed out by the free-list; retired
    and never-admitted lanes' block tables point at it so their frozen
    rewrites land somewhere no live lane ever gathers.

    ``arena_dtype="int8"`` (serving.kv_arena_dtype) stores the pages
    quantized with per-(page, head, token) f32 scales riding in a parallel
    ``k_scale``/``v_scale`` buffer — one scale per written KV row, so an
    append never requantizes resident rows (a true per-page scale would
    force a read-modify-write of the whole page on every decode step).
    Payload bytes halve vs bf16 (head_dim int8 + 4 scale bytes per row vs
    2*head_dim), which is where the extra admitted slots come from.

    ``mesh`` (ISSUE 20) commits the arena to KV-head shardings — each
    shard holds ``(layers, n_pages, n_kv/axis, page_tokens, hd)`` — with
    the int8 scale buffers sharded over the same KV-head axis (their dim
    2), matching the layout GSPMD picks for the decode programs so the
    arena-bytes accounting is stable from allocation onward. Block tables
    and the free-list stay
    host-side, so reserve/CoW/publish/census run unchanged on the sharded
    arena; every jit that donates the arena round-trips the committed
    layout, keeping donation effective."""
    n_kv = cfg["n_kv_heads"]
    head_dim = cfg["d_model"] // cfg["n_heads"]
    dtype = jnp.dtype(cfg["dtype"])
    shape = (cfg["n_layers"], n_pages, n_kv, page_tokens, head_dim)
    if arena_dtype == "int8":
        sshape = shape[:-1]
        cache = {
            "k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32),
        }
    else:
        if arena_dtype:
            dtype = jnp.dtype(arena_dtype)
        cache = {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}
    if mesh is not None:
        from tfservingcache_tpu.parallel.sharding import shard_kv_arena

        cache = shard_kv_arena(cache, mesh)
    return cache


def _quantize_kv_rows(x):
    """Symmetric absmax int8 over the head_dim axis: ``x (..., hd)`` ->
    (int8 values, f32 scales ``(...)``) with ``x ≈ values * scales[..., None]``.
    Per-row scales keep quantization LOCAL to the written row — the
    incremental-append property the arena's write paths depend on."""
    xf = x.astype(jnp.float32)
    scale = jnp.maximum(jnp.max(jnp.abs(xf), axis=-1), 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale


def _paged_forward_step(params, tok, cache, tables, pos, cfg, family,
                        page_tokens: int, kernel: bool = False):
    """One decode step (s_len=1 per lane) against the paged arena — the
    block-table counterpart of ``_forward_cached_dyn``. Each lane writes its
    new K/V at ``tables[lane, pos // page_tokens]`` offset ``pos %
    page_tokens`` (clipped to the last table slot: overshoot past a lane's
    reservation hits a zeroed table entry, i.e. the trash page), then
    attends over its pages via ``paged_attention`` — the fused Pallas
    kernel when ``kernel`` and the backend/shape gate admit it, else the
    gather+einsum reference whose GQA/mask pipeline matches the dense path
    operation-for-operation, so greedy decode is token-for-token identical.

    An int8 arena (``cache["k_scale"]`` present) quantizes each lane's new
    row at write time — per-row scales, so resident rows are never
    requantized — and attention dequantizes on the read side."""
    from tfservingcache_tpu.ops.attention import paged_attention

    dtype = jnp.dtype(cfg["dtype"])
    s_lanes = tok.shape[0]
    n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
    head_dim = cfg["d_model"] // n_heads
    pps = tables.shape[1]
    positions = pos[:, None]                                     # (S, 1)
    page = jnp.take_along_axis(
        tables, jnp.clip(pos // page_tokens, 0, pps - 1)[:, None], axis=1
    )[:, 0]                                                      # (S,)
    # past-the-table writes go to the trash page EXPLICITLY: the clip above
    # would otherwise hand back the lane's own last slot, which is a live
    # reserved page when the lane's budget fills the whole table (a draft
    # scan near max_seq under spec headroom capping can get here)
    page = jnp.where(pos // page_tokens >= pps, 0, page)
    off = pos % page_tokens
    quantized = "k_scale" in cache

    x = params["embed"][tok[:, None]].astype(dtype)              # (S, 1, d)
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for li, layer in enumerate(params["layers"]):
        attn = jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["attn"])
        h = _rmsnorm(x, layer["ln1"])
        q = (h @ attn["wq"]).reshape(s_lanes, 1, n_heads, head_dim).transpose(0, 2, 1, 3)
        k = (h @ attn["wk"]).reshape(s_lanes, 1, n_kv, head_dim).transpose(0, 2, 1, 3)
        v = (h @ attn["wv"]).reshape(s_lanes, 1, n_kv, head_dim).transpose(0, 2, 1, 3)
        q = _rope_per_example(q, positions, cfg["rope_theta"])
        k = _rope_per_example(k, positions, cfg["rope_theta"])

        # scatter each lane's single new row into its current page; lanes
        # parked on the trash page may collide — last-writer-wins junk that
        # no live lane's block table can reach
        k_row, v_row = k[:, :, 0, :], v[:, :, 0, :]              # (S, n_kv, hd)
        ks_arena = vs_arena = None
        if quantized:
            k_row, k_s = _quantize_kv_rows(k_row)
            v_row, v_s = _quantize_kv_rows(v_row)
            ks_arena = cache["k_scale"][li].at[page, :, off].set(k_s)
            vs_arena = cache["v_scale"][li].at[page, :, off].set(v_s)
            new_ks.append(ks_arena)
            new_vs.append(vs_arena)
        k_arena = cache["k"][li].at[page, :, off, :].set(
            k_row.astype(cache["k"].dtype)
        )
        v_arena = cache["v"][li].at[page, :, off, :].set(
            v_row.astype(cache["v"].dtype)
        )
        new_k.append(k_arena)
        new_v.append(v_arena)

        out = paged_attention(q, k_arena, v_arena, tables, pos, page_tokens,
                              k_scale=ks_arena, v_scale=vs_arena,
                              kernel=kernel)
        out = out.reshape(s_lanes, n_heads, 1, head_dim).astype(x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(s_lanes, 1, cfg["d_model"])
        x = x + out @ attn["wo"]
        x = x + _ffn_block(layer, x, cfg, family, dtype)
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].astype(dtype).T).astype(jnp.float32)
    new_cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quantized:
        new_cache["k_scale"] = jnp.stack(new_ks)
        new_cache["v_scale"] = jnp.stack(new_vs)
    return logits, new_cache


def _paged_verify_step(params, toks, cache, tables, pos, cfg, family,
                       page_tokens: int, kernel: bool = False):
    """One multi-position forward (s_len=T per lane) against the paged
    arena — the verify pass of in-engine speculative decoding. Lane ``s``'s
    T tokens ``toks[s]`` sit at positions ``pos[s]..pos[s]+T-1``; each
    writes its K/V row at ``tables[lane, p // page_tokens]`` offset
    ``p % page_tokens`` (clipped to the last table slot — overshoot past
    the reservation lands on the trash page, exactly like the decode
    step), then all T queries attend in ONE ``paged_attention_verify``
    call with per-position causal masks. With T == 1 the math degenerates
    to ``_paged_forward_step`` operation-for-operation, which is what
    keeps spec-on greedy decode token-for-token identical to spec-off.

    An int8 arena quantizes each of the T new rows at write time with the
    same per-row absmax discipline — rejected draft rows are quantization
    junk above the accepted prefix, masked until overwritten."""
    from tfservingcache_tpu.ops.attention import paged_attention_verify

    dtype = jnp.dtype(cfg["dtype"])
    s_lanes, t_q = toks.shape
    n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
    head_dim = cfg["d_model"] // n_heads
    pps = tables.shape[1]
    positions = pos[:, None] + jnp.arange(t_q)[None, :]          # (S, T)
    pages = jnp.take_along_axis(
        tables, jnp.clip(positions // page_tokens, 0, pps - 1), axis=1
    )                                                            # (S, T)
    # past-the-table positions redirect to the trash page explicitly — the
    # clip alone would alias them onto the lane's own LAST slot, stomping
    # visible history when the reservation fills the whole table
    pages = jnp.where(positions // page_tokens >= pps, 0, pages)
    off = positions % page_tokens
    quantized = "k_scale" in cache

    x = params["embed"][toks].astype(dtype)                      # (S, T, d)
    new_k, new_v, new_ks, new_vs = [], [], [], []
    for li, layer in enumerate(params["layers"]):
        attn = jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["attn"])
        h = _rmsnorm(x, layer["ln1"])
        q = (h @ attn["wq"]).reshape(s_lanes, t_q, n_heads, head_dim).transpose(0, 2, 1, 3)
        k = (h @ attn["wk"]).reshape(s_lanes, t_q, n_kv, head_dim).transpose(0, 2, 1, 3)
        v = (h @ attn["wv"]).reshape(s_lanes, t_q, n_kv, head_dim).transpose(0, 2, 1, 3)
        q = _rope_per_example(q, positions, cfg["rope_theta"])
        k = _rope_per_example(k, positions, cfg["rope_theta"])

        # scatter the T new rows per lane: advanced indices (S, T) at arena
        # dims 0 and 2 straddle the head slice, so the updated block is
        # (S, T, n_kv, hd) — the natural layout of the projection
        k_rows = k.transpose(0, 2, 1, 3)                         # (S, T, n_kv, hd)
        v_rows = v.transpose(0, 2, 1, 3)
        ks_arena = vs_arena = None
        if quantized:
            k_rows, k_s = _quantize_kv_rows(k_rows)
            v_rows, v_s = _quantize_kv_rows(v_rows)
            ks_arena = cache["k_scale"][li].at[pages, :, off].set(k_s)
            vs_arena = cache["v_scale"][li].at[pages, :, off].set(v_s)
            new_ks.append(ks_arena)
            new_vs.append(vs_arena)
        k_arena = cache["k"][li].at[pages, :, off, :].set(
            k_rows.astype(cache["k"].dtype)
        )
        v_arena = cache["v"][li].at[pages, :, off, :].set(
            v_rows.astype(cache["v"].dtype)
        )
        new_k.append(k_arena)
        new_v.append(v_arena)

        out = paged_attention_verify(
            q, k_arena, v_arena, tables, pos, page_tokens,
            k_scale=ks_arena, v_scale=vs_arena, kernel=kernel,
        )
        out = out.reshape(s_lanes, n_heads, t_q, head_dim).astype(x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(s_lanes, t_q, cfg["d_model"])
        x = x + out @ attn["wo"]
        x = x + _ffn_block(layer, x, cfg, family, dtype)
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].astype(dtype).T).astype(jnp.float32)
    new_cache = {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}
    if quantized:
        new_cache["k_scale"] = jnp.stack(new_ks)
        new_cache["v_scale"] = jnp.stack(new_vs)
    return logits, new_cache


@functools.partial(
    jax.jit,
    static_argnames=("cfg_key", "family", "page_tokens", "kernel"),
    donate_argnums=(1, 2, 3),
)
def _paged_prefill_chunk_jit(params, arena_k, arena_v, scales, table_row,
                             toks, start, real_len, *, cfg_key,
                             family="transformer_lm", page_tokens,
                             kernel=False):
    """One fixed-size prefill chunk written straight into a lane's reserved
    pages (chunked-prefill interleaving, serving.prefill_chunk_tokens).
    ``toks`` is (1, C) with C STATIC — the engine clamps the knob up to a
    pow2 and zero-pads the final chunk, so ONE compiled program serves
    every chunk of every prompt. ``start`` (1,) i32 is the absolute
    position of toks[:, 0]; ``real_len`` (1,) i32 counts the non-pad
    tokens in this chunk. Reuses the spec-decode verify step: K/V rows
    land at start..start+C-1 through the lane's block-table row (the
    trash-page redirect inside absorbs pad rows that run past the
    reservation), and per-position causal masks give each real query
    exact attention over every previously written chunk. Pad rows INSIDE
    the reservation hold junk at positions >= the prompt end — the same
    write-before-read argument as the dense insert makes them invisible:
    decode writes row p before any query attends to it. Returns the
    updated arena plus the last REAL token's logits (f32), which the
    final chunk feeds through the split-then-sample helper for a first
    token bit-identical in discipline to the monolithic prefill."""
    cfg = dict(cfg_key)
    cache = {"k": arena_k, "v": arena_v}
    if scales is not None:
        cache["k_scale"] = scales["k"]
        cache["v_scale"] = scales["v"]
    logits, cache = _paged_verify_step(
        params, toks, cache, table_row, start, cfg, family, page_tokens,
        kernel=kernel,
    )
    idx = jnp.clip(real_len - 1, 0, toks.shape[1] - 1)
    last = jnp.take_along_axis(logits, idx[:, None, None], axis=1)[:, 0]
    out_scales = (
        {"k": cache["k_scale"], "v": cache["v_scale"]}
        if "k_scale" in cache else None
    )
    return cache["k"], cache["v"], out_scales, last


@functools.partial(
    jax.jit, donate_argnums=(0, 1, 2), static_argnames=("page_tokens",)
)
def _paged_insert_jit(arena_k, arena_v, scales, pk, pv, table_row, base, *,
                      page_tokens):
    """Scatter one admitted request's prefill K/V (layers, 1, n_kv, P_pad,
    hd) into its reserved pages: logical row ``r`` goes to page
    ``table_row[r // page_tokens]`` offset ``r % page_tokens``. ``table_row``
    is the lane's FULL (pages_per_slot,) block-table row — entries beyond
    the reservation are 0, so prefill-pad rows past the reserved budget
    (P_pad is a pow2 bucket and can overshoot it) land in the trash page.
    Junk pad rows inside the reservation are never visible for the same
    write-before-read reason as the dense insert. ``base`` (traced i32) is
    the shared-prefix boundary: rows < base belong to pages another lane /
    the prefix index owns READ-ONLY, so their scatter is redirected to the
    trash page — prefill stops at the shared boundary and only private
    pages are written. base=0 is the plain unshared insert. One compile
    per P_pad bucket, same bound as the prefill itself (base is data, not
    a signature). ``scales`` is the int8 arena's {"k", "v"} per-row scale
    buffers (donated; None for a dense-dtype arena): prefill rows are
    quantized here with the same per-row absmax discipline as the decode
    write, so a page is bit-identical whether filled by prefill or steps."""
    p_pad = pk.shape[3]
    pps = table_row.shape[0]
    rows = jnp.arange(p_pad)
    pages = table_row[jnp.clip(rows // page_tokens, 0, pps - 1)]  # (P_pad,)
    pages = jnp.where(rows >= base.astype(jnp.int32), pages, 0)
    offs = rows % page_tokens
    # (layers, 1, n_kv, P_pad, hd) -> (P_pad, layers, n_kv, hd): the two
    # advanced indices below are non-adjacent, so their broadcast dim moves
    # to the front of the updated slice
    kv = pk[:, 0].transpose(2, 0, 1, 3)
    vv = pv[:, 0].transpose(2, 0, 1, 3)
    if scales is not None:
        kv, k_s = _quantize_kv_rows(kv)
        vv, v_s = _quantize_kv_rows(vv)
        scales = {
            "k": scales["k"].at[:, pages, :, offs].set(k_s),
            "v": scales["v"].at[:, pages, :, offs].set(v_s),
        }
    arena_k = arena_k.at[:, pages, :, offs, :].set(kv.astype(arena_k.dtype))
    arena_v = arena_v.at[:, pages, :, offs, :].set(vv.astype(arena_v.dtype))
    return arena_k, arena_v, scales


@jax.jit
def _paged_gather_prefix_jit(arena_k, arena_v, scales, pages):
    """Gather ``n`` full shared-prefix pages into the dense
    (layers, 1, n_kv, n*page_tokens, hd) layout `_slot_prefill_from_cache_jit`
    expects as its cached prefix. Read-only on the arena (no donation — the
    shared pages stay live for every other referencing lane). One compile
    per distinct page count, bounded by pages_per_slot. An int8 arena
    (``scales`` not None) is dequantized here: the suffix prefill runs on
    dense f32 rows either way."""
    # arena: (layers, n_pages, n_kv, page_tokens, hd); pages: (n,) i32
    k = arena_k[:, pages]                       # (L, n, n_kv, pt, hd)
    v = arena_v[:, pages]
    if scales is not None:
        k = k.astype(jnp.float32) * scales["k"][:, pages][..., None]
        v = v.astype(jnp.float32) * scales["v"][:, pages][..., None]
    layers, n, n_kv, pt, hd = k.shape
    k = k.swapaxes(1, 2).reshape(layers, n_kv, n * pt, hd)[:, None]
    v = v.swapaxes(1, 2).reshape(layers, n_kv, n * pt, hd)[:, None]
    return k, v


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _page_copy_jit(arena_k, arena_v, scales, src, dst):
    """Copy one arena page ``src`` -> ``dst`` in place (donated buffers, no
    arena-sized copy). This is the copy-on-write fast path: the host swaps
    the lane's block-table entry to ``dst`` afterwards and decrefs ``src``.
    ``src``/``dst`` are traced scalars, so every CoW event reuses the single
    compiled program — the decode-chunk program count is untouched. An int8
    arena's per-row scales (``scales`` {"k","v"}, donated) travel with the
    page bytes — a CoW'd or published page stays bit-identical."""
    arena_k = arena_k.at[:, dst].set(arena_k[:, src])
    arena_v = arena_v.at[:, dst].set(arena_v[:, src])
    if scales is not None:
        scales = {
            "k": scales["k"].at[:, dst].set(scales["k"][:, src]),
            "v": scales["v"].at[:, dst].set(scales["v"][:, src]),
        }
    return arena_k, arena_v, scales


@jax.jit
def _pages_export_jit(arena_k, arena_v, scales, pages):
    """Gather ``n`` arena pages' RAW rows for conversation parking
    (cache/conversation_kv.py): page-layout (layers, n, n_kv, page_tokens,
    hd) in the arena dtype, plus the int8 arena's per-row scales
    (layers, n, n_kv, page_tokens) when present. Read-only on the arena —
    parking copies, it never steals — and deliberately NOT dequantized:
    the parked bytes must re-import bit-identical, and int8 + scales is
    half the host/disk footprint of dense rows. One compile per distinct
    page count, bounded by pages_per_slot."""
    k = arena_k[:, pages]
    v = arena_v[:, pages]
    if scales is None:
        return k, v, None
    return k, v, {"k": scales["k"][:, pages], "v": scales["v"][:, pages]}


@functools.partial(jax.jit, donate_argnums=(0, 1, 2))
def _pages_import_jit(arena_k, arena_v, scales, pages, pk, pv, pscales):
    """Scatter parked page payloads (the `_pages_export_jit` layout) back
    into freshly reserved arena pages — the resume half of the park cycle.
    Donated arena buffers, batched over all pages in one dispatch; the
    payload is already in the arena dtype so the set is a verbatim byte
    move and a park/resume round-trip leaves every page bit-identical to a
    lane that never retired. One compile per page count, same bound as the
    export."""
    arena_k = arena_k.at[:, pages].set(pk.astype(arena_k.dtype))
    arena_v = arena_v.at[:, pages].set(pv.astype(arena_v.dtype))
    if scales is not None:
        scales = {
            "k": scales["k"].at[:, pages].set(pscales["k"]),
            "v": scales["v"].at[:, pages].set(pscales["v"]),
        }
    return arena_k, arena_v, scales


@functools.partial(
    jax.jit,
    static_argnames=("cfg_key", "family", "chunk", "page_tokens", "kernel"),
    donate_argnums=(1, 2, 3),
)
def _paged_decode_chunk_jit(
    params,
    arena_k,             # (layers, n_pages, n_kv, page_tokens, hd) — donated
    arena_v,
    scales,              # {"k","v"} int8 per-row scale buffers | None — donated
    tables,              # (S, pages_per_slot) i32 block tables
    tok,                 # (S,) last sampled token per lane
    pos,                 # (S,) i32 write position per lane
    active,              # (S,) bool — frozen for the whole chunk
    rngs,                # (chunk, 2) uint32 — one PRNG key per step
    temperature,         # (S,) f32 per-lane
    top_k,               # (S,) i32 per-lane
    *,
    cfg_key,
    family: str = "transformer_lm",
    chunk: int,
    page_tokens: int,
    kernel: bool = False,
):
    """Paged counterpart of ``_decode_chunk_jit``: same scan, same frozen
    inactive-lane convention, but K/V live in the shared page arena and
    each lane reads through its block table. ``tables`` is traced (a tiny
    (S, pages_per_slot) i32 H2D copy per chunk), so recycling pages never
    mints a new program; compiled-program count stays one per chunk size
    (x2 for the ``kernel`` boolean — the serving.kv_paged_kernel gate)."""
    cfg = dict(cfg_key)
    quantized = scales is not None

    def step(carry, rng):
        cache, tok, pos = carry
        logits, cache = _paged_forward_step(
            params, tok, cache, tables, pos, cfg, family,
            page_tokens, kernel=kernel,
        )
        nxt = _sample_per_row(logits[:, 0], rng, temperature, top_k)
        nxt = jnp.where(active, nxt, tok)
        pos = pos + active.astype(jnp.int32)
        return (cache, nxt, pos), nxt

    cache = {"k": arena_k, "v": arena_v}
    if quantized:
        cache["k_scale"] = scales["k"]
        cache["v_scale"] = scales["v"]
    (cache, tok, pos), toks = jax.lax.scan(
        step, (cache, tok, pos), rngs, length=chunk
    )
    scales = (
        {"k": cache["k_scale"], "v": cache["v_scale"]} if quantized else None
    )
    return (cache["k"], cache["v"], scales, tok, pos,
            jnp.transpose(toks, (1, 0)))  # (S, chunk)


def _ffn_block(layer: dict, x, cfg: dict, family: str, dtype):
    """The family-specific second half of a decoder layer (input is the
    residual stream BEFORE its norm; returns the residual delta)."""
    h = _rmsnorm(x, layer["ln2"])
    if family == "moe_lm":
        from tfservingcache_tpu.models.moe_lm import _moe_block

        moe = {
            "router": layer["moe"]["router"],  # routing stays f32
            "w1": layer["moe"]["w1"].astype(dtype),
            "w2": layer["moe"]["w2"].astype(dtype),
        }
        y, _aux = _moe_block(moe, h, cfg)  # aux loss is a training-only signal
        return y
    mlp = jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["mlp"])
    return (jax.nn.silu(h @ mlp["w1"]) * (h @ mlp["w3"])) @ mlp["w2"]


def _forward_cached_dyn(params, input_ids, cache, start_pos, cfg,
                        family: str = "transformer_lm"):
    """Like _forward_cached but with PER-EXAMPLE start positions (B,) —
    needed because prompts in one batch have different true lengths."""
    dtype = jnp.dtype(cfg["dtype"])
    b, s_len = input_ids.shape
    n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
    head_dim = cfg["d_model"] // n_heads
    positions = start_pos[:, None] + jnp.arange(s_len)[None, :]   # (B, S)

    x = params["embed"][input_ids].astype(dtype)
    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        attn = jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["attn"])
        h = _rmsnorm(x, layer["ln1"])
        q = (h @ attn["wq"]).reshape(b, s_len, n_heads, head_dim).transpose(0, 2, 1, 3)
        k = (h @ attn["wk"]).reshape(b, s_len, n_kv, head_dim).transpose(0, 2, 1, 3)
        v = (h @ attn["wv"]).reshape(b, s_len, n_kv, head_dim).transpose(0, 2, 1, 3)
        q = _rope_per_example(q, positions, cfg["rope_theta"])
        k = _rope_per_example(k, positions, cfg["rope_theta"])

        # scatter each example's K/V row into its own cache offset
        def upd(cache_l, kv):
            def one(c, kv_b, p):
                return jax.lax.dynamic_update_slice(c, kv_b, (0, p, 0))
            return jax.vmap(one)(cache_l, kv, start_pos)

        k_cache = upd(cache["k"][li], k.astype(cache["k"].dtype))
        v_cache = upd(cache["v"][li], v.astype(cache["v"].dtype))
        new_k.append(k_cache)
        new_v.append(v_cache)

        # per-example visibility: key pos <= query pos. GQA grouped-K/V form:
        # query heads fold into (kv_head, group) so the cache is read as-is,
        # never repeated up to n_heads (the repeat would materialize
        # group x cache bytes every step at exactly the scale GQA exists for)
        d = q.shape[-1]
        group = n_heads // n_kv
        # dots read the caches in their stored dtype: upcasting K/V to f32
        # here doubled the HBM bytes of the cache read EVERY decode step —
        # the read that dominates decode. Scores/softmax still accumulate
        # f32 via preferred_element_type (the flash-kernel recipe).
        qg = q.reshape(b, n_kv, group, s_len, d)
        s = jnp.einsum(
            "bkgqd,bkld->bkgql", qg, k_cache,
            preferred_element_type=jnp.float32,
        )
        s = s / math.sqrt(d)
        k_pos = jnp.arange(k_cache.shape[2])
        mask = k_pos[None, None, :] <= positions[:, :, None]      # (B, S, max_len)
        s = jnp.where(mask[:, None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum(
            "bkgql,bkld->bkgqd", p.astype(v_cache.dtype), v_cache,
            preferred_element_type=jnp.float32,
        )
        out = out.reshape(b, n_heads, s_len, d).astype(x.dtype)
        out = out.transpose(0, 2, 1, 3).reshape(b, s_len, cfg["d_model"])
        x = x + out @ attn["wo"]
        x = x + _ffn_block(layer, x, cfg, family, dtype)
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].astype(dtype).T).astype(jnp.float32)
    return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}


def _rope_per_example(x, positions, theta):
    """Rotary embedding with per-example positions (B, S) over (B, H, S, D)."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs[None, None, :]  # (B,S,d/2)
    cos = jnp.cos(angles)[:, None]                                            # (B,1,S,d/2)
    sin = jnp.sin(angles)[:, None]
    x1, x2 = x[..., 0::2], x[..., 1::2]
    rot = jnp.stack([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return rot.reshape(x.shape).astype(x.dtype)


def generate(  # static-bounded: cfg_key, max_new_tokens, return_cache -- cfg_key is per-model config; runtime callers pass pow2-bucketed max_new_tokens (next_bucket); return_cache is boolean
    model_def: Any,
    params: Any,
    input_ids,
    prompt_lengths=None,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    rng=None,
    return_cache: bool = False,
) -> jax.Array:
    """Generate ``max_new_tokens`` per row of ``input_ids`` (B, S prompt,
    right-padded to a common S; ``prompt_lengths`` gives true lengths).

    Decoder-LM families sharing the transformer_lm attention/cache layout
    are supported (transformer_lm, moe_lm). Returns (B, max_new_tokens)
    int32 token ids; with ``return_cache`` also the final KV arrays (the
    prefix cache stores them for reuse).
    """
    if model_def.family not in ("transformer_lm", "moe_lm"):
        raise ValueError(
            f"generation supports transformer_lm/moe_lm, not {model_def.family!r}"
        )
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s = input_ids.shape
    if prompt_lengths is None:
        prompt_lengths = jnp.full((b,), s, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    cfg = model_def.config
    if s + max_new_tokens > cfg["max_seq"]:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds max_seq {cfg['max_seq']}"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)
    cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
    return _generate_jit(
        params,
        input_ids,
        prompt_lengths,
        rng,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        cfg_key=cfg_key,
        max_new_tokens=max_new_tokens,
        family=model_def.family,
        return_cache=return_cache,
    )


def generate_from_cache(  # static-bounded: cfg_key, max_new_tokens, return_cache -- cfg_key is per-model config; runtime callers pass pow2-bucketed max_new_tokens (next_bucket); return_cache is boolean
    model_def: Any,
    params: Any,
    suffix_ids,
    suffix_len: int,
    cached_k,
    cached_v,
    cached_len: int,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    rng=None,
    return_cache: bool = False,
):
    """Continue a (B=1) generate from a cached prompt-prefix KV (the prefix
    cache's fast path — runtime/prefix_cache.py). ``suffix_ids`` (1, S') are
    the prompt tokens after the cached prefix, padded; ``cached_len`` is the
    number of valid rows in the padded ``cached_k/v``."""
    import jax

    if rng is None:
        rng = jax.random.PRNGKey(0)
    cfg = model_def.config
    cfg_key = tuple(sorted((k, v) for k, v in cfg.items()))
    return _generate_from_cache_jit(
        params,
        jnp.asarray(suffix_ids, jnp.int32),
        jnp.asarray([suffix_len], jnp.int32),
        cached_k,
        cached_v,
        jnp.asarray([cached_len], jnp.int32),
        rng,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(top_k, jnp.int32),
        cfg_key=cfg_key,
        max_new_tokens=max_new_tokens,
        family=model_def.family,
        return_cache=return_cache,
    )

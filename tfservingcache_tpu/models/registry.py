"""Model family registry + the TPUSavedModel artifact format.

The reference serves opaque TF SavedModels through an external
tensorflow_model_server; models here are native JAX modules, stored as a
versioned artifact directory (same ``<base>/<name>/<version>/`` layout the
protocol and providers assume — reference diskmodelprovider.go:20-44):

    <name>/<version>/
      model.json       — {"format": "tpusc.v2", "family": ..., "config": ...,
                          "params": {"file": "params.bin", "manifest": [...]}}
      params.bin       — raw little-endian leaf bytes, grouped by dtype,
                         16-byte-aligned offsets per the manifest

v2 rationale (cold path = the product): one sequential read, zero-copy
views straight into the packed host->HBM transfer
(runtime.packed_device_put) — no msgpack parse, and a multi-GB llama-class
artifact can stream. ``tpusc.v1`` (flax msgpack) artifacts remain readable.

``family`` selects a builder registered here; the builder returns a
``ModelDef`` whose ``apply`` is a pure jittable function — everything the
runtime compiles and pins to TPU HBM.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping

import numpy as np

ARTIFACT_FORMAT = "tpusc.v2"
ARTIFACT_FORMAT_V1 = "tpusc.v1"
MODEL_JSON = "model.json"
PARAMS_FILE = "params.msgpack"     # v1 (read-compat)
PARAMS_BIN = "params.bin"          # v2


@dataclass(frozen=True)
class TensorSpec:
    """Shape entries are ints (static) or axis-name strings (dynamic): the
    same name must agree across all inputs of one request and buckets
    independently of other names ("batch" + "seq" for LMs, "src"/"tgt" for
    encoder-decoders). -1 is accepted as an alias for "batch"."""

    dtype: str
    shape: tuple[int | str, ...]

    def norm_shape(self) -> tuple[int | str, ...]:
        return tuple("batch" if d == -1 else d for d in self.shape)

    def dynamic_axes(self) -> list[tuple[int, str]]:
        return [(i, d) for i, d in enumerate(self.norm_shape()) if isinstance(d, str)]

    def np_dtype(self) -> np.dtype:
        import ml_dtypes  # registered extended dtypes (bfloat16)

        del ml_dtypes
        return np.dtype(self.dtype)


@dataclass
class ModelDef:
    """A built, servable model family instance.

    ``apply(params, inputs) -> outputs`` is a pure function over a params
    pytree and a dict of arrays — the unit of XLA compilation.
    """

    family: str
    config: dict[str, Any]
    apply: Callable[[Any, Mapping[str, Any]], dict[str, Any]]
    init: Callable[[Any], Any]                      # rng -> params pytree
    input_spec: dict[str, TensorSpec]
    output_spec: dict[str, TensorSpec]
    method_name: str = "tensorflow/serving/predict"
    # canonical (family, config) identity assigned by build(); the runtime
    # keys shared executables by this
    cache_key: str = ""
    # mesh-axis partition rules for multi-chip serving, e.g.
    # {("dense", "kernel"): (None, "model")}; consumed by parallel.sharding
    partition_rules: dict[str, Any] = field(default_factory=dict)
    # hard upper bound per named dynamic axis (e.g. {"seq": max_seq} for
    # absolute-position-table models): the runtime clamps its power-of-two
    # padding bucket to the cap and rejects true sizes beyond it
    axis_caps: dict[str, int] = field(default_factory=dict)
    # loss(params, inputs, targets) for families that support training steps
    loss: Callable[..., Any] | None = None
    # optional derived outputs computed OUTSIDE the jitted apply, on device,
    # from (device_outputs, dyn_sizes): name -> (fn, spec). Lets a client
    # request e.g. "last_token_logits" so predict ships a (B, V) slice
    # instead of the full (B, S, V) logits to host (VERDICT.md weak #4).
    # Only materialized when named in the request's output_filter.
    derived_outputs: dict[str, tuple[Callable[..., Any], TensorSpec]] = field(
        default_factory=dict
    )
    # outputs served when a request names none (output_filter unset). LM
    # families default to ["last_token_logits"]: shipping the full padded
    # (B, S, V) logits tensor per request made warm REST 0.5 qps — clients
    # wanting everything ask for it explicitly (output_filter=["logits"]).
    default_outputs: list[str] | None = None
    # float params are cast to this dtype when the artifact is written (the
    # family's apply casts weights to its compute dtype anyway): a bf16
    # artifact halves both disk reads and the host->device transfer that
    # dominates the cold-miss path.
    store_param_dtype: str | None = None
    # mesh-aware apply factory: families whose computation itself needs the
    # chip-group mesh (ring/context-parallel attention) set this; the runtime
    # jit-compiles bind_mesh(mesh) instead of ``apply`` when serving on a
    # group. Plain TP families leave it None — their sharding is declarative
    # (partition_rules) and XLA inserts the collectives.
    bind_mesh: Callable[[Any], Callable[[Any, Mapping[str, Any]], dict[str, Any]]] | None = None


_REGISTRY: dict[str, Callable[[dict[str, Any]], ModelDef]] = {}
_DEFAULT_CONFIGS: dict[str, dict[str, Any]] = {}


def register(name: str, default_config: dict[str, Any] | None = None):
    def deco(builder: Callable[[dict[str, Any]], ModelDef]):
        _REGISTRY[name] = builder
        _DEFAULT_CONFIGS[name] = default_config or {}
        return builder

    return deco


def families() -> list[str]:
    _load_builtin_families()
    return sorted(_REGISTRY)


_BUILD_CACHE: dict[str, ModelDef] = {}  # guarded-by: _BUILD_LOCK
_BUILD_LOCK = threading.Lock()


def build(family: str, config: dict[str, Any] | None = None) -> ModelDef:
    """Build (memoized) a family instance.

    Memoization is load-bearing for multi-tenant serving performance: every
    tenant artifact of the same (family, config) shares ONE ModelDef, hence
    one ``apply`` function identity, hence one jit cache entry and one XLA
    executable — tenant N's cold load skips compilation entirely and costs
    only the params fetch + device_put. The reference cannot do this: TF
    Serving compiles/loads each SavedModel independently.
    """
    _load_builtin_families()
    if family not in _REGISTRY:
        raise KeyError(f"unknown model family {family!r}; known: {families()}")
    merged = dict(_DEFAULT_CONFIGS[family])
    merged.update(config or {})
    key = f"{family}|{json.dumps(merged, sort_keys=True, default=str)}"
    with _BUILD_LOCK:  # one ModelDef identity per key, even under racing loads
        model = _BUILD_CACHE.get(key)
        if model is None:
            model = _REGISTRY[family](merged)
            model.cache_key = key
            _BUILD_CACHE[key] = model
    return model


_BUILTIN_MODULES = (
    "half_plus_two", "mnist_cnn", "bert", "resnet", "transformer_lm", "t5", "moe_lm",
)


def _load_builtin_families() -> None:
    # import for registration side effects; cheap and idempotent
    import importlib

    for mod in _BUILTIN_MODULES:
        try:
            importlib.import_module(f"tfservingcache_tpu.models.{mod}")
        except ModuleNotFoundError as e:
            if f"models.{mod}" not in str(e):
                raise  # a real dependency error inside the module


# ---------------------------------------------------------------------------
# Artifact IO
# ---------------------------------------------------------------------------

class ArtifactError(Exception):
    pass


def _leaf_path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


_ALIGN = 16  # every leaf offset 16-byte aligned: valid frombuffer views for
             # any dtype, and friendly to vectorized host copies

# int8 transport quantization floor: leaves below this many elements (norms,
# biases, small projections) stay in their float dtype — their bytes are
# noise on the transfer and their dynamic range matters more
_QUANT_MIN_ELEMS = 65536


class QuantLeaf:
    """An int8-transported weight: ``q`` (int8) + per-output-channel
    ``scale`` (f32), dequantized to ``orig_dtype`` ON DEVICE after the
    host->HBM transfer. Registered as a pytree node (lazily, on first
    construction — a module-level registration would force the jax import
    on every light consumer of the registry) so ``packed_device_put`` ships
    q in the int8 group and scale in the f32 group without special-casing."""

    def __init__(self, q, scale, orig_dtype: str) -> None:
        _register_quantleaf()
        self.q = q
        self.scale = scale
        self.orig_dtype = orig_dtype

    def dequant_host(self) -> np.ndarray:
        return (
            np.asarray(self.q).astype(np.float32) * np.asarray(self.scale)
        ).astype(np.dtype(self.orig_dtype))


def _quantleaf_flatten(ql: QuantLeaf):
    return (ql.q, ql.scale), ql.orig_dtype


def _quantleaf_unflatten(aux, children):
    return QuantLeaf(children[0], children[1], aux)


_QUANTLEAF_REGISTERED = False


def _register_quantleaf() -> None:
    global _QUANTLEAF_REGISTERED
    if _QUANTLEAF_REGISTERED:
        return
    import jax

    try:
        jax.tree_util.register_pytree_node(
            QuantLeaf, _quantleaf_flatten, _quantleaf_unflatten
        )
    except ValueError:
        pass  # already registered (re-import)
    _QUANTLEAF_REGISTERED = True


def _quantize_int8(a: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Per-output-channel (last axis) symmetric int8: scale = amax/127 over
    the reduced axes. The standard weight-only deployment recipe — relative
    error ~0.4% on smooth weights, invisible next to bf16 compute."""
    af = a.astype(np.float32)
    reduce_axes = tuple(range(a.ndim - 1))
    amax = np.max(np.abs(af), axis=reduce_axes, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.round(af / scale), -127, 127).astype(np.int8)
    return q, scale


def save_artifact(dest_dir: str, model: ModelDef, params: Any,
                  quantize: str | None = None) -> str:
    """``quantize="int8"`` stores large float weights as int8 + per-channel
    f32 scales: the host->HBM transfer that dominates the cold-miss path
    ships ~half the bytes of a bf16 artifact (~quarter of f32), and the
    runtime dequantizes on device. Opt-in per export — outputs differ from
    the unquantized artifact by the quantization error."""
    import jax

    if quantize not in (None, "int8"):
        raise ArtifactError(f"unsupported quantize scheme {quantize!r}")
    os.makedirs(dest_dir, exist_ok=True)
    if model.store_param_dtype:
        nd = np.dtype(model.store_param_dtype)

        def cast(x):
            if isinstance(x, QuantLeaf):
                return x
            a = np.asarray(x)
            return a.astype(nd) if a.dtype.kind == "f" and a.dtype != nd else a

        params = jax.tree_util.tree_map(
            cast, params, is_leaf=lambda x: isinstance(x, QuantLeaf)
        )

    # QuantLeaf inputs (a raw_quant re-save, e.g. cli repack) are carried
    # through VERBATIM — dequantize-then-requantize would shift scales and
    # compound error on every repack
    flat, _ = jax.tree_util.tree_flatten_with_path(
        params, is_leaf=lambda x: isinstance(x, QuantLeaf)
    )

    def _leaf_dtype_name(leaf) -> str:
        if isinstance(leaf, QuantLeaf):
            return "int8"
        return np.asarray(leaf).dtype.name

    # group by dtype so the runtime's per-dtype packed transfer reads
    # contiguous file segments. dtype NAME, not .str: extension dtypes
    # (bfloat16) stringify to the void '|V2' under .str and would not
    # round-trip through np.dtype()
    flat = sorted(
        enumerate(flat), key=lambda e: (_leaf_dtype_name(e[1][1]), e[0])
    )
    manifest = []
    offset = 0
    # leaves stream straight to disk — a llama-class artifact must not hold
    # a second full copy of its params in host memory during export
    with open(os.path.join(dest_dir, PARAMS_BIN), "wb") as f:
        def write_aligned(buf: bytes) -> int:
            nonlocal offset
            pad = (-offset) % _ALIGN
            if pad:
                f.write(b"\0" * pad)
                offset += pad
            start = offset
            f.write(buf)
            offset += len(buf)
            return start

        def write_quant(entry, q, scale, orig_dtype: str):
            entry["dtype"] = "int8"
            entry["offset"] = write_aligned(q.tobytes())
            entry["nbytes"] = q.nbytes
            entry["quant"] = {
                "orig_dtype": orig_dtype,
                "scale_dtype": "float32",
                "scale_shape": list(scale.shape),
                "scale_offset": write_aligned(scale.tobytes()),
                "scale_nbytes": scale.nbytes,
            }

        for _, (path, leaf) in flat:
            if isinstance(leaf, QuantLeaf):
                q = np.ascontiguousarray(np.asarray(leaf.q))
                entry = {"path": _leaf_path_str(path), "shape": list(q.shape)}
                write_quant(entry, q,
                            np.ascontiguousarray(np.asarray(leaf.scale)),
                            leaf.orig_dtype)
                manifest.append(entry)
                continue
            a = np.ascontiguousarray(np.asarray(leaf))
            entry = {
                "path": _leaf_path_str(path),
                "dtype": a.dtype.name,
                "shape": list(a.shape),
            }
            # extension float dtypes (bfloat16) report kind 'V', not 'f' —
            # match by name too or every bf16 artifact would silently skip
            # quantization
            is_float = a.dtype.kind == "f" or a.dtype.name in (
                "bfloat16", "float16"
            )
            if (
                quantize == "int8"
                and is_float
                and a.ndim >= 2
                and a.size >= _QUANT_MIN_ELEMS
            ):
                q, scale = _quantize_int8(a)
                write_quant(entry, q, scale, a.dtype.name)
            else:
                # tobytes, not .data: extension dtypes (bfloat16) have no
                # buffer protocol; copies one leaf at a time, never the tree
                entry["offset"] = write_aligned(a.tobytes())
                entry["nbytes"] = a.nbytes
            manifest.append(entry)
    meta = {
        "format": ARTIFACT_FORMAT,
        "family": model.family,
        "config": model.config,
        "param_dtype": model.store_param_dtype,
        "quantize": quantize,
        "params": {"file": PARAMS_BIN, "manifest": manifest},
        "signature": {
            "inputs": {k: [v.dtype, list(v.shape)] for k, v in model.input_spec.items()},
            "outputs": {k: [v.dtype, list(v.shape)] for k, v in model.output_spec.items()},
            "method_name": model.method_name,
        },
    }
    # model.json LAST: its presence marks the artifact complete (providers
    # stage into unique dirs, but a direct writer gets the same safety)
    with open(os.path.join(dest_dir, MODEL_JSON), "w") as f:
        json.dump(meta, f, indent=1)
    return dest_dir


def load_artifact(path: str, raw_quant: bool = False) -> tuple[ModelDef, Any]:
    """-> (ModelDef, params pytree). Raises ArtifactError on malformed dirs.

    ``raw_quant=True`` returns int8-quantized leaves as ``QuantLeaf`` views
    (q + scale) instead of dequantizing on the host — the runtime's packed
    transfer ships those raw bytes and dequantizes on DEVICE, which is the
    whole point of the int8 artifact. Generic callers keep the default and
    get ordinary float arrays."""
    meta_path = os.path.join(path, MODEL_JSON)
    if not os.path.exists(meta_path):
        raise ArtifactError(f"not a TPUSavedModel artifact (no {MODEL_JSON}): {path}")
    with open(meta_path) as f:
        meta = json.load(f)
    fmt = meta.get("format")
    if fmt == ARTIFACT_FORMAT_V1:
        from flax import serialization

        model = build(meta["family"], meta.get("config"))
        with open(os.path.join(path, PARAMS_FILE), "rb") as f:
            # msgpack_restore avoids needing an init()-built template
            params = serialization.msgpack_restore(f.read())
        return model, _restore_lists(params)
    if fmt != ARTIFACT_FORMAT:
        raise ArtifactError(f"unsupported artifact format {fmt!r} in {path}")
    model = build(meta["family"], meta.get("config"))
    spec = meta.get("params") or {}
    bin_path = os.path.join(path, spec.get("file", PARAMS_BIN))
    manifest = spec.get("manifest")
    if manifest is None or not os.path.exists(bin_path):
        raise ArtifactError(f"artifact missing params manifest or {bin_path}")
    # ONE sequential read; every leaf is a zero-copy aligned view into it
    blob = np.fromfile(bin_path, dtype=np.uint8)
    return model, params_from_manifest(meta, blob, raw_quant=raw_quant,
                                       src=bin_path)


def params_from_manifest(meta: dict[str, Any], blob: np.ndarray,
                         raw_quant: bool = False,
                         src: str = "params blob") -> Any:
    """Rebuild the params pytree from a v2 ``model.json`` dict plus the
    raw ``params.bin`` bytes as a uint8 array — the manifest walk of
    ``load_artifact`` without the filesystem. Peer param distribution
    (protocol/peer_transfer.py) feeds this the byte image it assembled in
    RAM off the wire, so the receiver's packed entry never waits on a
    disk round-trip. Leaves are zero-copy views into ``blob``."""
    manifest = (meta.get("params") or {}).get("manifest")
    if manifest is None:
        raise ArtifactError(f"missing params manifest for {src}")
    import ml_dtypes  # registers bfloat16/float8 names with np.dtype

    del ml_dtypes
    nested: dict[str, Any] = {}
    for ent in manifest:
        dt = np.dtype(ent["dtype"])
        n = int(np.prod(ent["shape"])) if ent["shape"] else 1
        off, nbytes = int(ent["offset"]), int(ent["nbytes"])
        if nbytes != n * dt.itemsize or off + nbytes > blob.nbytes:
            raise ArtifactError(
                f"corrupt manifest entry {ent['path']!r} in {src}"
            )
        arr = np.frombuffer(blob.data, dtype=dt, count=n, offset=off).reshape(
            ent["shape"]
        )
        quant = ent.get("quant")
        if quant is not None:
            sdt = np.dtype(quant.get("scale_dtype", "float32"))
            sn = int(np.prod(quant["scale_shape"])) if quant["scale_shape"] else 1
            soff, snb = int(quant["scale_offset"]), int(quant["scale_nbytes"])
            if snb != sn * sdt.itemsize or soff + snb > blob.nbytes:
                raise ArtifactError(
                    f"corrupt quant scales for {ent['path']!r} in {src}"
                )
            scale = np.frombuffer(
                blob.data, dtype=sdt, count=sn, offset=soff
            ).reshape(quant["scale_shape"])
            ql = QuantLeaf(arr, scale, quant["orig_dtype"])
            arr = ql if raw_quant else ql.dequant_host()
        if ent["path"] == "":
            return arr  # params was a single bare array
        node = nested
        parts = ent["path"].split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return _restore_lists(nested)


def load_artifact_meta(path: str) -> dict[str, Any]:
    """Parse an artifact's ``model.json`` alone — no params bytes touched.

    ``path`` may be the artifact directory or the model.json file itself
    (the streaming fetch hands over the staged metadata file while
    params.bin is still in flight). Raises ArtifactError on malformed or
    non-v2 metadata; callers that only want the pipeline hint treat any
    raise as "precompile not possible"."""
    meta_path = path
    if os.path.isdir(path):
        meta_path = os.path.join(path, MODEL_JSON)
    if not os.path.exists(meta_path):
        raise ArtifactError(f"no {MODEL_JSON} at {path}")
    with open(meta_path) as f:
        try:
            meta = json.load(f)
        except ValueError as e:
            raise ArtifactError(f"unparseable {meta_path}: {e}") from e
    if not isinstance(meta, dict) or "family" not in meta:
        raise ArtifactError(f"malformed artifact metadata in {meta_path}")
    return meta


def abstract_params_from_meta(meta: Mapping[str, Any]) -> Any:
    """The POST-dequant params pytree as ``jax.ShapeDtypeStruct`` leaves,
    reconstructed from a v2 manifest alone (None when the format carries no
    manifest, i.e. v1 msgpack).

    This is what makes compile-while-transfer possible: the manifest names
    every leaf's path, shape and (for int8 entries) original float dtype, so
    ``jax.jit(apply).lower(...)`` can run before a single parameter byte has
    landed on the host. The tree structure must match ``load_artifact``'s
    exactly (same nesting, same list restoration) or the AOT executable
    would be traced against a different treedef than the real params."""
    import jax

    import ml_dtypes  # registers bfloat16/float8 names with np.dtype

    del ml_dtypes
    if meta.get("format") != ARTIFACT_FORMAT:
        return None
    manifest = (meta.get("params") or {}).get("manifest")
    if manifest is None:
        return None
    nested: dict[str, Any] = {}
    for ent in manifest:
        quant = ent.get("quant")
        dt = np.dtype(quant["orig_dtype"] if quant else ent["dtype"])
        leaf = jax.ShapeDtypeStruct(tuple(ent["shape"]), dt)
        if ent["path"] == "":
            return leaf  # params was a single bare array
        node = nested
        parts = ent["path"].split("/")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = leaf
    return _restore_lists(nested)


def resident_bytes_estimate(path: str) -> int | None:
    """Estimated DEVICE bytes of the artifact's params once servable (None
    if unreadable). For plain artifacts this matches the on-disk param bytes;
    for int8-quantized artifacts each quant leaf dequantizes on device to
    ``orig_dtype`` (2-4x its disk size), so capacity planners (the assignment
    warmer's headroom check) must use this, not disk bytes (ADVICE r4)."""
    try:
        import ml_dtypes  # registers bfloat16/float8 names with np.dtype

        del ml_dtypes
        with open(os.path.join(path, MODEL_JSON)) as f:
            meta = json.load(f)
        manifest = (meta.get("params") or {}).get("manifest")
        if manifest is None:
            return None
        total = 0
        for ent in manifest:
            n = int(np.prod(ent["shape"])) if ent["shape"] else 1
            quant = ent.get("quant")
            dt = np.dtype(quant["orig_dtype"] if quant else ent["dtype"])
            total += n * dt.itemsize
        return total
    except Exception:  # noqa: BLE001 - estimate only; callers fall back
        return None


def _restore_lists(tree: Any) -> Any:
    """flax msgpack round-trips Python lists as {"0": ..., "1": ...} dicts;
    convert them back so families can keep natural list-of-layers params."""
    if isinstance(tree, dict):
        restored = {k: _restore_lists(v) for k, v in tree.items()}
        if restored and all(k.isdigit() for k in restored):
            return [restored[k] for k in sorted(restored, key=int)]
        return restored
    return tree


def export_artifact(
    family: str,
    base_dir: str,
    name: str | None = None,
    version: int = 1,
    config: dict[str, Any] | None = None,
    seed: int = 0,
    quantize: str | None = None,
) -> str:
    """Initialize a family with fresh params and write
    ``<base_dir>/<name>/<version>/`` (used by the CLI, tests and bench).

    Init runs on the host CPU backend: an export is offline tooling, and
    running jax.random on an accelerator would round-trip every fresh
    parameter tensor over the host<->device link just to write it to disk."""
    import jax

    model = build(family, config)
    try:
        cpu = jax.local_devices(backend="cpu")[0]
    except RuntimeError:
        cpu = None
    if cpu is not None:
        with jax.default_device(cpu):
            params = jax.device_get(model.init(jax.random.PRNGKey(seed)))
    else:
        params = model.init(jax.random.PRNGKey(seed))
    dest = os.path.join(base_dir, name or family, str(version))
    return save_artifact(dest, model, params, quantize=quantize)

"""MNIST CNN family (BASELINE.json config #2: 10 tenant copies exercising
LRU eviction). A small flax convnet; conv + matmul work lands on the MXU,
params are a few hundred KB so many tenants fit in HBM.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register


class _CNN(nn.Module):
    num_classes: int = 10
    width: int = 32

    @nn.compact
    def __call__(self, x):
        # NHWC input; compute in bf16, accumulate/logits in f32 (TPU-friendly)
        x = x.astype(jnp.bfloat16)
        x = nn.Conv(self.width, (3, 3), padding="SAME", dtype=jnp.bfloat16)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(self.width * 2, (3, 3), padding="SAME", dtype=jnp.bfloat16)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=jnp.bfloat16)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


@register("mnist_cnn", {"num_classes": 10, "width": 32})
def build(config: dict) -> ModelDef:
    module = _CNN(num_classes=config["num_classes"], width=config["width"])

    def apply(params, inputs):
        logits = module.apply({"params": params}, inputs["image"])
        return {
            "logits": logits,
            "classes": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        }

    def init(rng):
        return module.init(rng, jnp.zeros((1, 28, 28, 1), jnp.float32))["params"]

    def loss(params, inputs, targets):
        logits = module.apply({"params": params}, inputs["image"])
        labels = jax.nn.one_hot(targets["label"], config["num_classes"])
        return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))

    return ModelDef(
        family="mnist_cnn",
        config=config,
        apply=apply,
        init=init,
        input_spec={"image": TensorSpec("float32", (-1, 28, 28, 1))},
        output_spec={
            "logits": TensorSpec("float32", (-1, config["num_classes"])),
            "classes": TensorSpec("int32", (-1,)),
        },
        loss=loss,
    )

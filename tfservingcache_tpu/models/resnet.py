"""ResNet family (BASELINE.json config #4: 1000 per-tenant variants hashed
across chips). Standard bottleneck ResNet in flax; convs run bf16 on the
MXU, batch-norm statistics are baked (inference mode) so apply stays a pure
function of params.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register

DEFAULT_CONFIG = {"depth": 50, "num_classes": 1000, "width": 64, "image_size": 224}
TINY_CONFIG = {"depth": 18, "num_classes": 10, "width": 8, "image_size": 32}

_STAGES = {
    18: (2, 2, 2, 2),
    34: (3, 4, 6, 3),
    50: (3, 4, 6, 3),
    101: (3, 4, 23, 3),
    152: (3, 8, 36, 3),
}


class _BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.GroupNorm, num_groups=32, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1), strides=(self.strides, self.strides))(
                residual
            )
            residual = norm()(residual)
        return nn.relu(y + residual)


class _BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.GroupNorm, num_groups=8, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3))(y)
        y = norm()(y)
        if residual.shape != y.shape:
            residual = conv(self.filters, (1, 1), strides=(self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class _ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int
    width: int
    bottleneck: bool = True
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), strides=(2, 2), use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(nn.GroupNorm(num_groups=min(32, self.width), dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block = _BottleneckBlock if self.bottleneck else _BasicBlock
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block(self.width * (2**i), strides=strides, dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(x)


@register("resnet", DEFAULT_CONFIG)
def build(config: dict) -> ModelDef:
    cfg = config
    depth = cfg["depth"]
    if depth not in _STAGES:
        raise ValueError(f"unsupported resnet depth {depth}; known: {sorted(_STAGES)}")
    module = _ResNet(
        stage_sizes=_STAGES[depth],
        num_classes=cfg["num_classes"],
        width=cfg["width"],
        bottleneck=depth >= 50,
    )
    size = cfg["image_size"]

    def apply(params, inputs):
        logits = module.apply({"params": params}, inputs["image"])
        return {
            "logits": logits,
            "classes": jnp.argmax(logits, axis=-1).astype(jnp.int32),
        }

    def init(rng):
        return module.init(rng, jnp.zeros((1, size, size, 3), jnp.float32))["params"]

    def loss(params, inputs, targets):
        logits = module.apply({"params": params}, inputs["image"])
        labels = jax.nn.one_hot(targets["label"], cfg["num_classes"])
        return -jnp.mean(jnp.sum(labels * jax.nn.log_softmax(logits), axis=-1))

    return ModelDef(
        family="resnet",
        config=cfg,
        apply=apply,
        init=init,
        input_spec={"image": TensorSpec("float32", (-1, size, size, 3))},
        output_spec={
            "logits": TensorSpec("float32", (-1, cfg["num_classes"])),
            "classes": TensorSpec("int32", (-1,)),
        },
        loss=loss,
    )

"""half_plus_two — the canonical TF Serving smoke-test model
(BASELINE.json config #1), as a native JAX family: y = w*x + b with
w=0.5, b=2 at export time. Trivial on purpose: it exercises the whole
fetch->compile->pin->predict path with negligible compile cost.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register


@register("half_plus_two")
def build(config: dict) -> ModelDef:
    def apply(params, inputs):
        x = inputs["x"]
        return {"y": params["w"] * x + params["b"]}

    def init(rng):
        del rng
        return {"w": jnp.float32(0.5), "b": jnp.float32(2.0)}

    def loss(params, inputs, targets):
        pred = apply(params, inputs)["y"]
        return jnp.mean((pred - targets["y"]) ** 2)

    return ModelDef(
        family="half_plus_two",
        config=config,
        apply=apply,
        init=init,
        input_spec={"x": TensorSpec("float32", (-1,))},
        output_spec={"y": TensorSpec("float32", (-1,))},
        loss=loss,
    )


def reference_output(x: np.ndarray) -> np.ndarray:
    return 0.5 * np.asarray(x, np.float32) + 2.0

"""Greedy speculative decoding: a small draft model proposes ``spec_tokens``
tokens per round, the target model verifies them in ONE chunked forward.

No reference counterpart (the reference proxies opaque Predict calls —
SURVEY.md §5). This is a TPU-shaped throughput feature: plain decode is one
MXU-starved (B, 1, D) matmul per token, serial in S; verification processes
``spec+1`` positions per target forward at MXU-friendly width, so accepted
drafts amortize the expensive model's weight reads over several tokens.

Exactness: at temperature 0 the emitted sequence matches the target
model's own greedy decode (tokens are only kept while they match the
target's argmax, and the first mismatch is replaced by the target's own
choice — the draft can change WHEN tokens are computed, never WHICH).
``tests/test_speculative.py`` asserts this token-for-token. Caveat: the
chunked verify forward and the width-1 decode forward are different matmul
shapes, so on accelerators a near-TIED argmax can round the other way —
the guarantee is "the target's greedy decode under the verify shapes",
bitwise on CPU/f32, argmax-tie-sensitive in bf16.

Cache discipline (the part that makes rollback free): a verify chunk always
starts exactly at the current accepted position, and attention masks reads
to ``k_pos <= query_pos`` — so K/V rows written for later-rejected tokens
are invisible until the next chunk overwrites them. "Rollback" is just not
advancing the position pointer (models/generation.py's mask, reused as-is).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.generation import (
    _forward_cached_dyn,
    _paged_forward_step,
    _paged_verify_step,
    _sample_per_row,
    init_cache,
)


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def _spec_decode_loop(params_t, params_d, cache_t, cache_d, first, prompt_len,
                      cfg_t, cfg_d, family_t, family_d, spec: int,
                      max_new_tokens: int):
    """The draft-propose / target-verify loop, shared by the plain and the
    cached-prefix entries (their caches differ only in how the TARGET
    prefill was produced; absolute positions are identical). Returns
    (out, rounds, cache_t, final_tok, final_idx) — final_tok is the last
    round's carry, final_idx its EMITTED index (n_done_old + a, unclamped):
    when the final round overshoots max_new_tokens the carry was never
    returned to the client and must NOT be written at the last completion
    position (see _writeback_final)."""
    b = first.shape[0]
    out = jnp.zeros((b, max_new_tokens), jnp.int32)
    out = out.at[:, 0].set(first)
    n_done = jnp.ones((b,), jnp.int32)
    rows = jnp.arange(b)[:, None]
    jrange = jnp.arange(spec + 1)
    final_idx0 = jnp.zeros((b,), jnp.int32)  # `first` sits at emitted idx 0

    def cond(carry):
        _, _, _, n_done, _, _, _ = carry
        return jnp.any(n_done < max_new_tokens)

    def body(carry):
        cache_t, cache_d, cur_tok, n_done, out, rounds, _ = carry
        # cur_tok is the accepted token AT position pos, not yet in either
        # cache (the same invariant as generation.py's scan step)
        pos = prompt_len + n_done - 1

        def draft_step(c, _):
            cache_d, tok, p = c
            logits, cache_d = _forward_cached_dyn(
                params_d, tok[:, None], cache_d, p, cfg_d, family_d
            )
            nxt = _greedy(logits[:, 0])
            return (cache_d, nxt, p + 1), nxt

        # spec+1 steps, not spec: the extra step forwards d_spec so its K/V
        # row lands in the draft cache. Without it a fully-accepted round
        # (a == spec) leaves a permanent never-written hole at pos+spec that
        # every later draft query attends to — silently decaying acceptance
        # (and the whole speedup) while the target keeps the output correct.
        (cache_d, _, _), d_toks = jax.lax.scan(
            draft_step, (cache_d, cur_tok, pos), None, length=spec + 1
        )
        d = jnp.transpose(d_toks[:spec], (1, 0))               # (B, spec)

        # one chunked target forward verifies all proposals: logits_j
        # predicts position pos+1+j
        chunk = jnp.concatenate([cur_tok[:, None], d], axis=1)  # (B, spec+1)
        logits_t, cache_t = _forward_cached_dyn(
            params_t, chunk, cache_t, pos, cfg_t, family_t
        )
        g = _greedy(logits_t)                                   # (B, spec+1)
        matches = (d == g[:, :spec]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)       # (B,) 0..spec

        # emitted this round: d_1..d_a (== g_0..g_{a-1}) then g_a — always
        # a+1 target-greedy tokens
        g_at_a = jnp.take_along_axis(g, a[:, None], axis=1)[:, 0]
        d_pad = jnp.concatenate([d, jnp.zeros((b, 1), jnp.int32)], axis=1)
        e = jnp.where(
            jrange[None, :] < a[:, None], d_pad,
            jnp.where(jrange[None, :] == a[:, None], g_at_a[:, None], 0),
        )
        idx = n_done[:, None] + jrange[None, :]
        valid = (jrange[None, :] <= a[:, None]) & (idx < max_new_tokens)
        idx = jnp.where(valid, idx, max_new_tokens)             # OOB -> drop
        out = out.at[rows, idx].set(e, mode="drop")

        carry_idx = n_done + a  # g_at_a's emitted index, unclamped
        n_done = jnp.minimum(n_done + a + 1, max_new_tokens)
        return cache_t, cache_d, g_at_a, n_done, out, rounds + 1, carry_idx

    cache_t, _, final_tok, _, out, rounds, final_idx = jax.lax.while_loop(
        cond, body,
        (cache_t, cache_d, first, n_done, out, jnp.int32(0), final_idx0),
    )
    # rounds is a cheap health signal: a well-aligned draft should emit
    # ~spec+1 tokens per round; tests use it to catch acceptance decay that
    # exactness alone can't see (output stays correct regardless)
    return out, rounds, cache_t, final_tok, final_idx


def _writeback_final(params_t, cache_t, final_tok, final_idx, prompt_len,
                     cfg_t, family_t, max_new_tokens: int):
    """One (B, 1) target forward so the LAST completion position's K/V row
    is valid: rows are then correct for the whole prompt+completion. Every
    other emitted token was the input of some later verify chunk, so its
    row is already written; rejected tokens' rows were overwritten by the
    chunk that followed their rejection (the cache discipline in the module
    docstring).

    Overshoot case (final round clamped: final_idx > max_new-1): the carry
    was NEVER emitted, while the true last token out[:, max_new-1] was an
    ACCEPTED draft input of that chunk — its row is already correct.
    Writing the carry at prompt_len+max_new-1 would stomp it with a
    different token's K/V and poison the stored prefix entry, so the
    forward is aimed one slot PAST the persisted range instead (the slack
    rows every spec cache allocates; the row is junk nobody reads)."""
    overshoot = (final_idx > max_new_tokens - 1).astype(jnp.int32)
    pos = prompt_len + max_new_tokens - 1 + overshoot
    _, cache_t = _forward_cached_dyn(
        params_t, final_tok[:, None], cache_t, pos, cfg_t, family_t,
    )
    return cache_t


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg_t_key", "cfg_d_key", "max_new_tokens", "spec_tokens",
        "family_t", "family_d", "return_cache",
    ),
)
def _speculative_jit(
    params_t,
    params_d,
    input_ids,
    prompt_len,
    *,
    cfg_t_key,
    cfg_d_key,
    max_new_tokens: int,
    spec_tokens: int,
    family_t: str,
    family_d: str,
    return_cache: bool = False,
):
    cfg_t = dict(cfg_t_key)
    cfg_d = dict(cfg_d_key)
    b, s_max = input_ids.shape
    spec = spec_tokens
    # slack for chunk writes past the last emitted position (stale rows are
    # masked off and finished examples may keep writing while others drain)
    max_len = s_max + max_new_tokens + spec + 1
    cache_t = init_cache(cfg_t, b, max_len)
    cache_d = init_cache(cfg_d, b, max_len)

    zeros = jnp.zeros((b,), jnp.int32)
    logits_t, cache_t = _forward_cached_dyn(
        params_t, input_ids, cache_t, zeros, cfg_t, family_t
    )
    _, cache_d = _forward_cached_dyn(
        params_d, input_ids, cache_d, zeros, cfg_d, family_d
    )
    last = jnp.take_along_axis(
        logits_t, (prompt_len - 1)[:, None, None], axis=1
    )[:, 0]
    first = _greedy(last)

    out, rounds, cache_t, final_tok, final_idx = _spec_decode_loop(
        params_t, params_d, cache_t, cache_d, first, prompt_len,
        cfg_t, cfg_d, family_t, family_d, spec, max_new_tokens,
    )
    if return_cache:
        cache_t = _writeback_final(
            params_t, cache_t, final_tok, final_idx, prompt_len, cfg_t,
            family_t, max_new_tokens,
        )
        return out, rounds, cache_t["k"], cache_t["v"]
    return out, rounds


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg_t_key", "cfg_d_key", "max_new_tokens", "spec_tokens",
        "family_t", "family_d", "return_cache",
    ),
)
def _speculative_from_cache_jit(
    params_t,
    params_d,
    input_ids,          # (1, S_pad) FULL prompt — the draft prefills it all
    prompt_len,         # (1,)
    suffix_ids,         # (1, S_suffix_pad) prompt tokens AFTER the prefix
    suffix_len,         # (1,)
    cached_k,           # (layers, 1, n_kv, Lpad, head_dim) TARGET prefix K/V
    cached_v,
    cached_len,         # (1,) valid prefix rows; cached_len+suffix_len==prompt_len
    *,
    cfg_t_key,
    cfg_d_key,
    max_new_tokens: int,
    spec_tokens: int,
    family_t: str,
    family_d: str,
    return_cache: bool = True,
):
    """Speculative decoding whose TARGET prefill starts from cached prompt-
    prefix K/V (runtime/prefix_cache.py): turn N of a draft-assisted
    conversation pays target prefill only for its new tokens. The draft has
    no cached rows — it prefills the full prompt, which costs a fraction of
    the target prefill it replaces. Absolute positions are identical to the
    plain path, so the verify loop is shared and the output is the same
    greedy sequence."""
    cfg_t = dict(cfg_t_key)
    cfg_d = dict(cfg_d_key)
    b, s_max = input_ids.shape
    spec = spec_tokens
    _, s_pad = suffix_ids.shape
    l_pad = cached_k.shape[3]

    # target: copy prefix rows, prefill only the suffix
    cache_t = init_cache(cfg_t, b, l_pad + s_pad + max_new_tokens + spec + 1)
    cache_t = {
        "k": jax.lax.dynamic_update_slice(
            cache_t["k"], cached_k.astype(cache_t["k"].dtype), (0, 0, 0, 0, 0)
        ),
        "v": jax.lax.dynamic_update_slice(
            cache_t["v"], cached_v.astype(cache_t["v"].dtype), (0, 0, 0, 0, 0)
        ),
    }
    start = cached_len.astype(jnp.int32)
    logits_t, cache_t = _forward_cached_dyn(
        params_t, suffix_ids, cache_t, start, cfg_t, family_t
    )
    last = jnp.take_along_axis(
        logits_t, (suffix_len - 1)[:, None, None], axis=1
    )[:, 0]
    first = _greedy(last)

    # draft: full prefill (no draft rows are cached)
    cache_d = init_cache(cfg_d, b, s_max + max_new_tokens + spec + 1)
    _, cache_d = _forward_cached_dyn(
        params_d, input_ids, cache_d, jnp.zeros((b,), jnp.int32), cfg_d,
        family_d,
    )

    out, rounds, cache_t, final_tok, final_idx = _spec_decode_loop(
        params_t, params_d, cache_t, cache_d, first, prompt_len,
        cfg_t, cfg_d, family_t, family_d, spec, max_new_tokens,
    )
    if return_cache:
        cache_t = _writeback_final(
            params_t, cache_t, final_tok, final_idx, prompt_len, cfg_t,
            family_t, max_new_tokens,
        )
        return out, rounds, cache_t["k"], cache_t["v"]
    return out, rounds


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg_t_key", "cfg_d_key", "family_t", "family_d", "spec",
        "page_tokens", "kernel",
    ),
    donate_argnums=(2, 3, 4, 5, 6, 7),
)
def _paged_spec_round_jit(  # static-bounded: cfg_t_key, cfg_d_key, family_t, family_d, spec, page_tokens, kernel -- one value per (target, draft) model pair (config/family), spec is clamped to {1,2,4,8} at attach, page_tokens is ServingConfig kv_page_tokens, kernel is a boolean
    params_t,
    params_d,
    t_k,                 # target arena (layers, n_pages, n_kv, pt, hd) — donated
    t_v,
    t_scales,            # {"k","v"} int8 per-row scales | None — donated
    d_k,                 # draft arena — donated
    d_v,
    d_scales,
    t_tables,            # (S, pps_t) i32 target block tables
    d_tables,            # (S, pps_d) i32 draft block tables
    tok,                 # (S,) carry token per lane (at position pos, unwritten)
    pos,                 # (S,) i32 write position per lane
    active,              # (S,) bool — frozen for the whole round
    rng,                 # (2,) uint32 — one key per round
    temperature,         # (S,) f32 per-lane
    top_k,               # (S,) i32 per-lane
    *,
    cfg_t_key,
    cfg_d_key,
    family_t: str,
    family_d: str,
    spec: int,
    page_tokens: int,
    kernel: bool = False,
):
    """One speculative round for EVERY lane of the continuous engine: the
    draft proposes ``spec`` greedy tokens per lane (a spec+1-step paged
    scan over its own arena — the extra step writes d_spec's K/V row so
    full acceptance leaves no hole, same reasoning as ``_spec_decode_loop``),
    then ONE multi-position target forward verifies all spec+1 positions
    and each lane accepts a variable-length prefix.

    Per-row accept counts are TRACED data — ``accept`` comes back as an
    (S,) array and ``pos`` advances by it in-graph — so every acceptance
    pattern reuses this single program (the PR 3 per-row-sampling
    discipline; the executable-count guard test pins it). Non-greedy lanes
    (temperature > 0) degrade IN-GRAPH to 1-token decode: their accept
    count is forced to 0 and their emitted token is sampled from the
    verify pass's position-0 logits — exactly the token the plain chunk
    would have produced, under the same per-row sampling math.

    Rollback is the paged arena's mask discipline verbatim: rejected-
    suffix rows in both caches sit above the new ``pos`` and are
    overwritten write-before-read by the next round's first write at the
    carry position. Returns (t_k, t_v, t_scales, d_k, d_v, d_scales,
    tok', pos', toks (S, spec+1), accept (S,)) where lane ``s`` emits
    ``toks[s, :accept[s]]`` this round (accept = a+1 for active lanes,
    0 for frozen ones)."""
    cfg_t = dict(cfg_t_key)
    cfg_d = dict(cfg_d_key)

    cache_t = {"k": t_k, "v": t_v}
    if t_scales is not None:
        cache_t["k_scale"] = t_scales["k"]
        cache_t["v_scale"] = t_scales["v"]
    cache_d = {"k": d_k, "v": d_v}
    if d_scales is not None:
        cache_d["k_scale"] = d_scales["k"]
        cache_d["v_scale"] = d_scales["v"]

    def draft_step(c, _):
        cache_d, tk, p = c
        logits, cache_d = _paged_forward_step(
            params_d, tk, cache_d, d_tables, p, cfg_d, family_d,
            page_tokens, kernel=kernel,
        )
        nxt = _greedy(logits[:, 0])
        return (cache_d, nxt, p + 1), nxt

    (cache_d, _, _), d_toks = jax.lax.scan(
        draft_step, (cache_d, tok, pos), None, length=spec + 1
    )
    d = jnp.transpose(d_toks[:spec], (1, 0))                # (S, spec)

    # one multi-position target forward scores all spec+1 positions:
    # logits_t[:, j] predicts position pos+1+j
    chunk = jnp.concatenate([tok[:, None], d], axis=1)      # (S, spec+1)
    logits_t, cache_t = _paged_verify_step(
        params_t, chunk, cache_t, t_tables, pos, cfg_t, family_t,
        page_tokens, kernel=kernel,
    )
    g = _greedy(logits_t)                                   # (S, spec+1)
    matches = (d == g[:, :spec]).astype(jnp.int32)
    a = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)       # (S,) 0..spec

    # greedy rows emit g[:, :a+1] (for j < a, d_j == g_j so the target-
    # greedy rows ARE the emitted stream); non-greedy rows accept nothing
    # and emit one token sampled from the position-0 logits — identical
    # math to the plain chunk's _sample_per_row step
    greedy_row = temperature <= 0.0
    e0 = _sample_per_row(logits_t[:, 0], rng, temperature, top_k)
    a = jnp.where(greedy_row, a, 0)
    toks = g.at[:, 0].set(jnp.where(greedy_row, g[:, 0], e0))
    accept = jnp.where(active, a + 1, 0)                    # emitted count
    carry = jnp.take_along_axis(toks, a[:, None], axis=1)[:, 0]
    tok = jnp.where(active, carry, tok)
    pos = pos + accept

    t_scales = (
        {"k": cache_t["k_scale"], "v": cache_t["v_scale"]}
        if t_scales is not None else None
    )
    d_scales = (
        {"k": cache_d["k_scale"], "v": cache_d["v_scale"]}
        if d_scales is not None else None
    )
    return (cache_t["k"], cache_t["v"], t_scales,
            cache_d["k"], cache_d["v"], d_scales,
            tok, pos, toks, accept)


def speculative_generate(
    model_def_t: Any,
    params_t: Any,
    model_def_d: Any,
    params_d: Any,
    input_ids,
    prompt_lengths=None,
    max_new_tokens: int = 32,
    spec_tokens: int = 4,
    return_rounds: bool = False,
    return_cache: bool = False,
    cached_kv: tuple | None = None,
) -> jax.Array:
    """Greedy decode of the TARGET model, accelerated by the draft.

    Both models must share the decoder-LM cache layout (transformer_lm /
    moe_lm families) and the same vocabulary. Returns (B, max_new_tokens)
    int32 matching the target's own greedy decode token-for-token — exactly
    in exact arithmetic; on accelerators the chunked verify matmul and the
    width-1 decode matmul may tile/reassociate differently, so a near-tied
    argmax can break the other way (same caveat as any shape-dependent
    float reduction). ``return_rounds=True`` also returns the verify-round
    count — the acceptance-health signal tests use.

    ``return_cache=True`` (B=1) also returns the TARGET's post-decode K/V
    (rows valid for the whole prompt+completion — a final writeback forward
    covers the last carry), so the runtime can prime the prefix cache.
    ``cached_kv=(suffix_ids, suffix_len, k, v, cached_len)`` starts the
    target prefill from cached prefix rows instead of the full prompt (the
    draft still prefills the full ``input_ids``); the emitted sequence is
    the same greedy decode either way.
    """
    for md, role in ((model_def_t, "target"), (model_def_d, "draft")):
        if md.family not in ("transformer_lm", "moe_lm"):
            raise ValueError(
                f"speculative decoding supports transformer_lm/moe_lm "
                f"{role}s, not {md.family!r}"
            )
    if model_def_t.config["vocab_size"] != model_def_d.config["vocab_size"]:
        raise ValueError(
            "draft and target must share a vocabulary: "
            f"{model_def_d.config['vocab_size']} vs "
            f"{model_def_t.config['vocab_size']}"
        )
    if spec_tokens < 1:
        raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s = input_ids.shape
    if prompt_lengths is None:
        prompt_lengths = jnp.full((b,), s, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if s + max_new_tokens > model_def_t.config["max_seq"]:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds max_seq "
            f"{model_def_t.config['max_seq']}"
        )
    key = lambda cfg: tuple(sorted((k, v) for k, v in cfg.items()))
    common = dict(
        cfg_t_key=key(model_def_t.config),
        cfg_d_key=key(model_def_d.config),
        max_new_tokens=max_new_tokens,
        spec_tokens=spec_tokens,
        family_t=model_def_t.family,
        family_d=model_def_d.family,
        return_cache=return_cache,
    )
    if cached_kv is not None:
        if b != 1:
            raise ValueError("cached-prefix speculative decoding is B=1 only")
        suffix_ids, suffix_len, ck, cv, cached_len = cached_kv
        res = _speculative_from_cache_jit(
            params_t, params_d, input_ids, prompt_lengths,
            jnp.asarray(suffix_ids, jnp.int32),
            jnp.asarray(suffix_len, jnp.int32).reshape(1),
            ck, cv, jnp.asarray(cached_len, jnp.int32).reshape(1),
            **common,
        )
    else:
        if return_cache and b != 1:
            raise ValueError("return_cache speculative decoding is B=1 only")
        res = _speculative_jit(
            params_t, params_d, input_ids, prompt_lengths, **common
        )
    if return_cache:
        out, rounds, k, v = res
        return (out, rounds, k, v) if return_rounds else (out, k, v)
    out, rounds = res
    return (out, rounds) if return_rounds else out

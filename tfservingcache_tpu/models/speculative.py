"""Greedy speculative decoding: a small draft model proposes ``spec_tokens``
tokens per round, the target model verifies them in ONE chunked forward.

No reference counterpart (the reference proxies opaque Predict calls —
SURVEY.md §5). This is a TPU-shaped throughput feature: plain decode is one
MXU-starved (B, 1, D) matmul per token, serial in S; verification processes
``spec+1`` positions per target forward at MXU-friendly width, so accepted
drafts amortize the expensive model's weight reads over several tokens.

Exactness: at temperature 0 the emitted sequence matches the target
model's own greedy decode (tokens are only kept while they match the
target's argmax, and the first mismatch is replaced by the target's own
choice — the draft can change WHEN tokens are computed, never WHICH).
``tests/test_speculative.py`` asserts this token-for-token. Caveat: the
chunked verify forward and the width-1 decode forward are different matmul
shapes, so on accelerators a near-TIED argmax can round the other way —
the guarantee is "the target's greedy decode under the verify shapes",
bitwise on CPU/f32, argmax-tie-sensitive in bf16.

Cache discipline (the part that makes rollback free): a verify chunk always
starts exactly at the current accepted position, and attention masks reads
to ``k_pos <= query_pos`` — so K/V rows written for later-rejected tokens
are invisible until the next chunk overwrites them. "Rollback" is just not
advancing the position pointer (models/generation.py's mask, reused as-is).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.generation import _forward_cached_dyn, init_cache


def _greedy(logits) -> jax.Array:
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit,
    static_argnames=(
        "cfg_t_key", "cfg_d_key", "max_new_tokens", "spec_tokens",
        "family_t", "family_d",
    ),
)
def _speculative_jit(
    params_t,
    params_d,
    input_ids,
    prompt_len,
    *,
    cfg_t_key,
    cfg_d_key,
    max_new_tokens: int,
    spec_tokens: int,
    family_t: str,
    family_d: str,
):
    cfg_t = dict(cfg_t_key)
    cfg_d = dict(cfg_d_key)
    b, s_max = input_ids.shape
    spec = spec_tokens
    # slack for chunk writes past the last emitted position (stale rows are
    # masked off and finished examples may keep writing while others drain)
    max_len = s_max + max_new_tokens + spec + 1
    cache_t = init_cache(cfg_t, b, max_len)
    cache_d = init_cache(cfg_d, b, max_len)

    zeros = jnp.zeros((b,), jnp.int32)
    logits_t, cache_t = _forward_cached_dyn(
        params_t, input_ids, cache_t, zeros, cfg_t, family_t
    )
    _, cache_d = _forward_cached_dyn(
        params_d, input_ids, cache_d, zeros, cfg_d, family_d
    )
    last = jnp.take_along_axis(
        logits_t, (prompt_len - 1)[:, None, None], axis=1
    )[:, 0]
    first = _greedy(last)

    out = jnp.zeros((b, max_new_tokens), jnp.int32)
    out = out.at[:, 0].set(first)
    n_done = jnp.ones((b,), jnp.int32)
    rows = jnp.arange(b)[:, None]
    jrange = jnp.arange(spec + 1)

    def cond(carry):
        _, _, _, n_done, _, _ = carry
        return jnp.any(n_done < max_new_tokens)

    def body(carry):
        cache_t, cache_d, cur_tok, n_done, out, rounds = carry
        # cur_tok is the accepted token AT position pos, not yet in either
        # cache (the same invariant as generation.py's scan step)
        pos = prompt_len + n_done - 1

        def draft_step(c, _):
            cache_d, tok, p = c
            logits, cache_d = _forward_cached_dyn(
                params_d, tok[:, None], cache_d, p, cfg_d, family_d
            )
            nxt = _greedy(logits[:, 0])
            return (cache_d, nxt, p + 1), nxt

        # spec+1 steps, not spec: the extra step forwards d_spec so its K/V
        # row lands in the draft cache. Without it a fully-accepted round
        # (a == spec) leaves a permanent never-written hole at pos+spec that
        # every later draft query attends to — silently decaying acceptance
        # (and the whole speedup) while the target keeps the output correct.
        (cache_d, _, _), d_toks = jax.lax.scan(
            draft_step, (cache_d, cur_tok, pos), None, length=spec + 1
        )
        d = jnp.transpose(d_toks[:spec], (1, 0))               # (B, spec)

        # one chunked target forward verifies all proposals: logits_j
        # predicts position pos+1+j
        chunk = jnp.concatenate([cur_tok[:, None], d], axis=1)  # (B, spec+1)
        logits_t, cache_t = _forward_cached_dyn(
            params_t, chunk, cache_t, pos, cfg_t, family_t
        )
        g = _greedy(logits_t)                                   # (B, spec+1)
        matches = (d == g[:, :spec]).astype(jnp.int32)
        a = jnp.sum(jnp.cumprod(matches, axis=1), axis=1)       # (B,) 0..spec

        # emitted this round: d_1..d_a (== g_0..g_{a-1}) then g_a — always
        # a+1 target-greedy tokens
        g_at_a = jnp.take_along_axis(g, a[:, None], axis=1)[:, 0]
        d_pad = jnp.concatenate([d, jnp.zeros((b, 1), jnp.int32)], axis=1)
        e = jnp.where(
            jrange[None, :] < a[:, None], d_pad,
            jnp.where(jrange[None, :] == a[:, None], g_at_a[:, None], 0),
        )
        idx = n_done[:, None] + jrange[None, :]
        valid = (jrange[None, :] <= a[:, None]) & (idx < max_new_tokens)
        idx = jnp.where(valid, idx, max_new_tokens)             # OOB -> drop
        out = out.at[rows, idx].set(e, mode="drop")

        n_done = jnp.minimum(n_done + a + 1, max_new_tokens)
        return cache_t, cache_d, g_at_a, n_done, out, rounds + 1

    _, _, _, _, out, rounds = jax.lax.while_loop(
        cond, body, (cache_t, cache_d, first, n_done, out, jnp.int32(0))
    )
    # rounds is a cheap health signal: a well-aligned draft should emit
    # ~spec+1 tokens per round; tests use it to catch acceptance decay that
    # exactness alone can't see (output stays correct regardless)
    return out, rounds


def speculative_generate(
    model_def_t: Any,
    params_t: Any,
    model_def_d: Any,
    params_d: Any,
    input_ids,
    prompt_lengths=None,
    max_new_tokens: int = 32,
    spec_tokens: int = 4,
    return_rounds: bool = False,
) -> jax.Array:
    """Greedy decode of the TARGET model, accelerated by the draft.

    Both models must share the decoder-LM cache layout (transformer_lm /
    moe_lm families) and the same vocabulary. Returns (B, max_new_tokens)
    int32 matching the target's own greedy decode token-for-token — exactly
    in exact arithmetic; on accelerators the chunked verify matmul and the
    width-1 decode matmul may tile/reassociate differently, so a near-tied
    argmax can break the other way (same caveat as any shape-dependent
    float reduction). ``return_rounds=True`` also returns the verify-round
    count — the acceptance-health signal tests use.
    """
    for md, role in ((model_def_t, "target"), (model_def_d, "draft")):
        if md.family not in ("transformer_lm", "moe_lm"):
            raise ValueError(
                f"speculative decoding supports transformer_lm/moe_lm "
                f"{role}s, not {md.family!r}"
            )
    if model_def_t.config["vocab_size"] != model_def_d.config["vocab_size"]:
        raise ValueError(
            "draft and target must share a vocabulary: "
            f"{model_def_d.config['vocab_size']} vs "
            f"{model_def_t.config['vocab_size']}"
        )
    if spec_tokens < 1:
        raise ValueError(f"spec_tokens must be >= 1, got {spec_tokens}")
    input_ids = jnp.asarray(input_ids, jnp.int32)
    b, s = input_ids.shape
    if prompt_lengths is None:
        prompt_lengths = jnp.full((b,), s, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if s + max_new_tokens > model_def_t.config["max_seq"]:
        raise ValueError(
            f"prompt {s} + max_new_tokens {max_new_tokens} exceeds max_seq "
            f"{model_def_t.config['max_seq']}"
        )
    key = lambda cfg: tuple(sorted((k, v) for k, v in cfg.items()))
    out, rounds = _speculative_jit(
        params_t,
        params_d,
        input_ids,
        prompt_lengths,
        cfg_t_key=key(model_def_t.config),
        cfg_d_key=key(model_def_d.config),
        max_new_tokens=max_new_tokens,
        spec_tokens=spec_tokens,
        family_t=model_def_t.family,
        family_d=model_def_d.family,
    )
    return (out, rounds) if return_rounds else out

"""moe_lm — mixture-of-experts decoder LM (Switch-style top-1 routing).

No reference counterpart (the reference serves opaque SavedModels and
implements no parallelism — SURVEY.md §2 inventory); this family exists so
expert parallelism is a first-class, servable capability: expert weights
carry an ``("expert", …)`` partition rule, so on a mesh with an "expert"
axis each chip group holds E/ep experts and XLA inserts the dispatch/combine
all-to-alls from the shardings.

TPU-first routing design: the GShard/Switch dense-dispatch formulation —
one-hot dispatch/combine tensors contracted with einsum — keeps every shape
static under jit (no data-dependent gather), trades a capacity-factor bound
(dropped tokens pass through the residual) for MXU-friendly dense matmuls.
Routing runs in f32; expert FFNs in bf16.

Serving caveat inherent to capacity routing: expert capacity is computed
over the whole flattened (padded) batch, so which tokens drop depends on
batch composition — outputs are deterministic per padded shape but NOT
batch-composition-invariant. The generate coalescer therefore never
co-batches moe_lm requests (runtime/batcher.py).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp

from tfservingcache_tpu.models.registry import ModelDef, TensorSpec, register
from tfservingcache_tpu.models.transformer_lm import _attention_block, _rmsnorm

DEFAULT_CONFIG: dict[str, Any] = {
    "vocab_size": 2048,
    "d_model": 256,
    "n_layers": 4,
    "n_heads": 8,
    "n_kv_heads": 8,
    "d_ff": 512,            # per-expert FFN width
    "n_experts": 8,
    "capacity_factor": 1.25,
    "aux_loss_weight": 0.01,
    "max_seq": 1024,
    "rope_theta": 10000.0,
    "dtype": "bfloat16",
}


def _moe_block(params: dict, x: jax.Array, cfg: dict) -> tuple[jax.Array, jax.Array]:
    """Top-1 routed expert FFN over (B, S, D) -> (output, aux_loss).

    Dense GShard dispatch: tokens -> (token, expert, capacity_slot) one-hot,
    experts applied batched over their leading (sharded) axis, combine
    weighted by the router gate. Tokens past an expert's capacity drop (the
    residual connection carries them unchanged).
    """
    b, s, d = x.shape
    e = cfg["n_experts"]
    t = b * s
    capacity = max(1, math.ceil(cfg["capacity_factor"] * t / e))
    xt = x.reshape(t, d)

    router_logits = (xt.astype(jnp.float32) @ params["router"].astype(jnp.float32))
    probs = jax.nn.softmax(router_logits, axis=-1)              # (t, e) f32
    gate = jnp.max(probs, axis=-1)                              # (t,)
    expert_ix = jnp.argmax(probs, axis=-1)                      # (t,)
    onehot = jax.nn.one_hot(expert_ix, e, dtype=jnp.float32)    # (t, e)

    # position of each token within its expert's queue (0-based); tokens at
    # position >= capacity are dropped
    pos = jnp.cumsum(onehot, axis=0) * onehot - onehot          # (t, e)
    keep = onehot * (pos < capacity)
    dispatch = keep[..., None] * jax.nn.one_hot(
        pos.astype(jnp.int32), capacity, dtype=jnp.float32
    )
    # (t, e, c)

    # NOTE: the dispatch gather-matmul stays f32 — bf16 operands change the
    # EP-sharded cross-device reduction enough to break parity with the
    # replicated path (tests/test_parallel.py), and routing fidelity beats
    # the marginal MXU win here
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt.astype(jnp.float32))
    expert_in = expert_in.astype(x.dtype)
    h = jax.nn.silu(jnp.einsum("ecd,edf->ecf", expert_in, params["w1"]))
    out_e = jnp.einsum("ecf,efd->ecd", h, params["w2"])         # (e, c, d)

    combine = dispatch * gate[:, None, None]
    y = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), out_e)

    # Switch load-balance aux loss: e * sum_e(frac_tokens_e * mean_prob_e)
    frac_tokens = jnp.mean(onehot, axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac_tokens * mean_prob)
    return y.reshape(b, s, d), aux


def _forward(params: dict, input_ids: jax.Array, cfg: dict) -> tuple[jax.Array, jax.Array]:
    dtype = jnp.dtype(cfg["dtype"])
    x = params["embed"][input_ids].astype(dtype)
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params["layers"]:
        x = x + _attention_block(
            jax.tree_util.tree_map(lambda w: w.astype(dtype), layer["attn"]),
            _rmsnorm(x, layer["ln1"]),
            cfg,
        )
        moe_params = {
            "router": layer["moe"]["router"],  # stays f32 inside the block
            "w1": layer["moe"]["w1"].astype(dtype),
            "w2": layer["moe"]["w2"].astype(dtype),
        }
        y, aux = _moe_block(moe_params, _rmsnorm(x, layer["ln2"]), cfg)
        x = x + y
        aux_total = aux_total + aux
    x = _rmsnorm(x, params["ln_f"])
    logits = (x @ params["embed"].astype(dtype).T).astype(jnp.float32)
    return logits, aux_total / max(len(params["layers"]), 1)


@register("moe_lm", DEFAULT_CONFIG)
def build(config: dict) -> ModelDef:
    cfg = config

    def apply(params, inputs):
        logits, _ = _forward(params, inputs["input_ids"].astype(jnp.int32), cfg)
        return {"logits": logits}

    def init(rng):
        d, v, ff, e = cfg["d_model"], cfg["vocab_size"], cfg["d_ff"], cfg["n_experts"]
        n_heads, n_kv = cfg["n_heads"], cfg["n_kv_heads"]
        head_dim = d // n_heads
        keys = jax.random.split(rng, cfg["n_layers"] + 1)

        def dense(key, fan_in, shape):
            return jax.random.normal(key, shape, jnp.float32) / math.sqrt(fan_in)

        layers = []
        for i in range(cfg["n_layers"]):
            ks = jax.random.split(keys[i], 7)
            layers.append(
                {
                    "attn": {
                        "wq": dense(ks[0], d, (d, n_heads * head_dim)),
                        "wk": dense(ks[1], d, (d, n_kv * head_dim)),
                        "wv": dense(ks[2], d, (d, n_kv * head_dim)),
                        "wo": dense(ks[3], n_heads * head_dim, (n_heads * head_dim, d)),
                    },
                    "moe": {
                        "router": dense(ks[4], d, (d, e)),
                        "w1": dense(ks[5], d, (e, d, ff)),
                        "w2": dense(ks[6], ff, (e, ff, d)),
                    },
                    "ln1": jnp.ones((d,), jnp.float32),
                    "ln2": jnp.ones((d,), jnp.float32),
                }
            )
        return {
            "embed": dense(keys[-1], d, (v, d)),
            "layers": layers,
            "ln_f": jnp.ones((d,), jnp.float32),
        }

    def loss(params, inputs, targets):
        logits, aux = _forward(params, inputs["input_ids"].astype(jnp.int32), cfg)
        labels = targets["labels"].astype(jnp.int32)
        logp = jax.nn.log_softmax(logits[:, :-1, :], axis=-1)
        tgt = labels[:, 1:]
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return jnp.mean(nll) + cfg["aux_loss_weight"] * aux

    # Expert parallelism: expert-batched FFN weights shard over the "expert"
    # mesh axis (leading dim = experts); attention keeps the flagship's
    # megatron TP over "model". Rules referencing an absent mesh axis degrade
    # to replicated (parallel/sharding.spec_for), so the family runs on
    # data-only, data x expert, or data x expert x model meshes unchanged.
    partition_rules = {
        "embed": (None, "model"),
        r"layers/\d+/attn/w[qkv]": (None, "model"),
        r"layers/\d+/attn/wo": ("model", None),
        r"layers/\d+/moe/router": (None,),
        r"layers/\d+/moe/w[12]": ("expert", None, None),
        r".*ln.*": (None,),
    }

    def last_token_logits(outputs, dyn_sizes):
        # device-side slice at the last REAL position (seq is bucket-padded)
        logits = outputs["logits"]
        s = dyn_sizes.get("seq", logits.shape[1])
        b = dyn_sizes.get("batch", logits.shape[0])
        return logits[:b, s - 1, :]

    return ModelDef(
        family="moe_lm",
        config=cfg,
        apply=apply,
        init=init,
        input_spec={"input_ids": TensorSpec("int32", ("batch", "seq"))},
        output_spec={"logits": TensorSpec("float32", ("batch", "seq", cfg["vocab_size"]))},
        partition_rules=partition_rules,
        loss=loss,
        derived_outputs={
            "last_token_logits": (
                last_token_logits,
                TensorSpec("float32", ("batch", cfg["vocab_size"])),
            )
        },
        # same LM serving default as transformer_lm: next-token logits out of
        # the box, full (B, S, V) logits via output_filter=["logits"]
        default_outputs=["last_token_logits"],
        store_param_dtype=cfg["dtype"],
    )

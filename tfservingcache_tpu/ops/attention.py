"""Attention ops: a Pallas TPU flash-attention kernel + jnp reference.

No reference-counterpart exists (the reference proxies opaque tensors and
never computes; SURVEY.md §5) — this is the TPU-native compute core for the
transformer families. Design per /opt/skills/guides/pallas_guide.md:

  - online-softmax over K/V blocks so the (S x S) score matrix never
    materializes in HBM (memory O(block_q x block_k) in VMEM);
  - block sizes aligned to the MXU/VPU tiling (multiples of 128 lanes);
  - fp32 accumulation regardless of input dtype (bf16 in, f32 softmax);
  - causal masking skips fully-masked K blocks via the loop bound itself.

The public entry ``attention`` dispatches: Pallas kernel on TPU backends,
jnp reference elsewhere (tests compare the two in interpret mode).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _tpu_compiler_params(pltpu, dimension_semantics: tuple):
    """jax moved TPUCompilerParams -> CompilerParams across the versions this
    repo meets in the wild; resolve whichever this jax ships."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(dimension_semantics=dimension_semantics)


# ---------------------------------------------------------------------------
# jnp reference implementation
# ---------------------------------------------------------------------------

def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True
) -> jax.Array:
    """(B, Hq, S, D) x (B, Hkv, S, D) attention, fp32 softmax, out in q.dtype.

    GQA-native: Hkv may divide Hq; query heads are grouped over their shared
    K/V head via a reshape, so repeated K/V are never materialized (the whole
    point of GQA's HBM saving — VERDICT.md round-1 weak #7)."""
    b, hq, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    # dots in the INPUT dtype (bf16 = full MXU rate, half the HBM reads),
    # f32 accumulation via preferred_element_type — for f32 inputs this is
    # bit-for-bit the old upcast math, for bf16 it is the fast path the
    # flash kernel must honestly beat
    qg = q.reshape(b, hkv, g, sq, d)
    s = jnp.einsum(
        "bkgqd,bkKd->bkgqK", qg, k, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # p @ v stays f32: a bf16-rounded p makes the sharded (TP/EP) einsum
    # diverge from the replicated one beyond parity tolerances — this is
    # the correctness yardstick, the Pallas kernel is the fast path
    o = jnp.einsum("bkgqK,bkKd->bkgqd", p, v.astype(jnp.float32))
    return o.reshape(b, hq, sq, d).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash kernel
# ---------------------------------------------------------------------------

def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, *, sm_scale: float, causal: bool, block_q: int,
    block_k: int, valid_len: int,
):
    from jax.experimental import pallas as pl

    # Keep operands in their input dtype (bf16) for the MXU dots: a bf16
    # matmul runs at full MXU rate and halves VMEM traffic vs the round-1
    # design that upcast q/k/v to f32 first (the 0.86x regression,
    # VERDICT r2 weak #2). Accumulation stays f32 via preferred_element_type;
    # sm_scale is applied to the f32 scores, not the bf16 operands.
    q = q_ref[0]                                            # (bq, d)
    qi = pl.program_id(1)
    seq_len = k_ref.shape[1]
    q_offset = qi * block_q

    if causal:
        # only K blocks at or before this Q block's last row participate
        num_k_blocks = jnp.minimum(
            (q_offset + block_q + block_k - 1) // block_k, seq_len // block_k
        )
    else:
        num_k_blocks = seq_len // block_k

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]                        # (bk, d)
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale                                                        # (bq, bk) f32
        k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < valid_len  # padded K rows never participate
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))          # (bq, 1)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc * alpha + pv, m_new, l_new

    acc = jnp.zeros((q.shape[0], q_ref.shape[2]), jnp.float32)
    m = jnp.full((q.shape[0], 1), NEG_INF, jnp.float32)
    l = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_k_blocks, body, (acc, m, l))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


# Resident-K/V limit: the 2D-grid kernel pulls each program's WHOLE padded
# K/V row into VMEM (O(S*D) — fine and hardware-proven fast at serving
# shapes, fatal at long-context lengths on a ~16 MiB/core VMEM). Above this
# K+V byte size the streamed 3D-grid kernel runs instead, whose VMEM is
# O(block_q*d + block_k*d) regardless of S (VERDICT r3 weak #3 / next #5).
KV_RESIDENT_LIMIT_BYTES = 4 << 20


def flash_variant(s_padded: int, d: int, itemsize: int) -> str:
    """Which kernel a (padded) shape dispatches to: "resident" | "streamed"."""
    kv_bytes = 2 * s_padded * d * itemsize
    return "resident" if kv_bytes <= KV_RESIDENT_LIMIT_BYTES else "streamed"


def _flash_streamed_kernel(
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref, *, sm_scale: float,
    causal: bool, block_q: int, block_k: int, valid_len: int, num_k: int,
):
    """One (q-block, k-block) grid step: online-softmax update of the VMEM
    scratch accumulators. K/V arrive one block per step (double-buffered by
    the Pallas pipeline), so VMEM use is independent of sequence length."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)
    q_offset = qi * block_q
    k_offset = kj * block_k

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    def _body():
        q = q_ref[0]                                            # (bq, d)
        k = k_ref[0]                                            # (bk, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                            # (bq, bk) f32
        k_pos = k_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = k_pos < valid_len
        if causal:
            q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            valid = valid & (q_pos >= k_pos)
        s = jnp.where(valid, s, NEG_INF)
        m_prev = m_ref[:, :1]                                   # (bq, 1)
        l_prev = l_ref[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        # m/l scratch is (bq, 128) — the VMEM lane tile — holding the value
        # broadcast across lanes; only lane 0 is read back
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    if causal:
        # a causal block whose first key strictly follows this q block's last
        # row is fully masked: skip its MXU work (its DMA is already in
        # flight — the bandwidth cost of a static grid — but no compute)
        pl.when(k_offset <= q_offset + block_q - 1)(_body)
    else:
        _body()

    @pl.when(kj == num_k - 1)
    def _final():
        o_ref[0] = (
            acc_ref[...] / jnp.maximum(l_ref[:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret")
)
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> jax.Array:
    """Flash attention over (B, Hq, S, D) x (B, Hkv, S, D). S is padded to a
    block multiple internally. GQA-native: the kernel instance for query head
    h reads K/V head h // (Hq/Hkv) via its BlockSpec index map — grouped K/V
    are streamed, never repeated in HBM.

    Two kernels behind one entry, chosen statically by padded K/V bytes
    (``flash_variant``): the resident 2D-grid kernel (whole K/V row in VMEM;
    hardware-proven fastest at serving lengths) up to
    ``KV_RESIDENT_LIMIT_BYTES``, and a streamed 3D-grid kernel (K/V one
    block per grid step, online-softmax state in VMEM scratch) beyond it —
    so ring-servable long-context lengths (S >= 16k) can never hand
    ``pallas_call`` K/V rows that exceed VMEM.

    Default blocks auto-select: S is first padded to a 128-lane tile multiple,
    then block_q/block_k take the largest of (256)/(512, 256) that divides the
    padded length, falling back to 128 — the v5e-tuned sizes without the
    pathological lcm-padding an asymmetric fixed default would hit on
    non-power-of-two sequence lengths (e.g. generate's exact-size fallback)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, d = q.shape
    hkv = k.shape[1]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    g = h // hkv
    sm_scale = 1.0 / math.sqrt(d)
    sp_tile = s + ((-s) % 128)
    if block_q is None:
        block_q = 256 if sp_tile % 256 == 0 else 128
    else:
        block_q = min(block_q, max(s, 16))
    if block_k is None:
        block_k = next(bk for bk in (512, 256, 128) if sp_tile % bk == 0)
    else:
        block_k = min(block_k, max(s, 16))
    pad = (-s) % math.lcm(block_q, block_k)  # both block counts must divide sp
    if pad:
        zeros = lambda x: jnp.pad(x, ((0, 0), (0, 0), (0, pad), (0, 0)))
        q, k, v = zeros(q), zeros(k), zeros(v)
    sp = q.shape[2]
    qf = q.reshape(b * h, sp, d)
    kf = k.reshape(b * hkv, sp, d)
    vf = v.reshape(b * hkv, sp, d)

    if flash_variant(sp, d, q.dtype.itemsize) == "resident":
        kernel = functools.partial(
            _flash_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, valid_len=s,
        )
        grid = (b * h, sp // block_q)
        # program i covers flat (batch, q-head) index i; its K/V row is the
        # owning group's head: batch * hkv + (head // g)
        kv_index = lambda i, j: (i // h * hkv + (i % h) // g, 0, 0)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
                pl.BlockSpec((1, sp, d), kv_index),
                pl.BlockSpec((1, sp, d), kv_index),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            out_shape=jax.ShapeDtypeStruct((b * h, sp, d), q.dtype),
            interpret=interpret,
            compiler_params=_tpu_compiler_params(
                pltpu, ("parallel", "arbitrary")
            ),
        )(qf, kf, vf)
    else:
        num_k = sp // block_k
        kernel = functools.partial(
            _flash_streamed_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, valid_len=s, num_k=num_k,
        )
        grid = (b * h, sp // block_q, num_k)
        kv_index = lambda i, j, kj: (i // h * hkv + (i % h) // g, kj, 0)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda i, j, kj: (i, j, 0)),
                pl.BlockSpec((1, block_k, d), kv_index),
                pl.BlockSpec((1, block_k, d), kv_index),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), lambda i, j, kj: (i, j, 0)),
            out_shape=jax.ShapeDtypeStruct((b * h, sp, d), q.dtype),
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),      # acc
                pltpu.VMEM((block_q, 128), jnp.float32),    # m (lane-bcast)
                pltpu.VMEM((block_q, 128), jnp.float32),    # l (lane-bcast)
            ],
            interpret=interpret,
            compiler_params=_tpu_compiler_params(
                pltpu, ("parallel", "parallel", "arbitrary")
            ),
        )(qf, kf, vf)
    out = out.reshape(b, h, sp, d)
    if pad:
        out = out[:, :, :s, :]
    return out


def _flash_carry_kernel(
    rel_ref, q_ref, k_ref, v_ref, acc_in_ref, m_in_ref, l_in_ref,
    acc_out_ref, m_out_ref, l_out_ref, acc_s, m_s, l_s, *,
    sm_scale: float, causal: bool, block_q: int, block_k: int, num_k: int,
):
    """Streamed flash step that THREADS the online-softmax carry: scratch is
    seeded from (acc_in, m_in, l_in) at kj==0 and written back at the last
    kj, so a caller can chain calls over K/V blocks that arrive one at a
    time — ring attention's ppermute hops (parallel/ring_attention.py).
    ``rel_ref`` (SMEM) holds k_off - q_off: global positions are runtime
    values under shard_map (axis_index), never compile-time constants."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    kj = pl.program_id(2)
    rel = rel_ref[0]

    @pl.when(kj == 0)
    def _init():
        acc_s[...] = acc_in_ref[0]
        m_s[...] = jnp.broadcast_to(m_in_ref[0], m_s.shape)
        l_s[...] = jnp.broadcast_to(l_in_ref[0], l_s.shape)

    def _body():
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale
        if causal:
            iq = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) + qi * block_q
            ik = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1) + kj * block_k
            s = jnp.where(iq - ik >= rel, s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # guard the all-masked case: with m_new still NEG_INF,
        # exp(NEG_INF - NEG_INF) would be 1 and corrupt l/acc — a fully
        # masked future block must be a strict no-op on the carry
        p = jnp.where(s > NEG_INF / 2, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(jnp.minimum(m_prev - m_new, 0.0))
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_s[...] = acc_s[...] * alpha + pv
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    if causal:
        # skip blocks wholly above the causal frontier (rel is traced, so
        # the bound is a runtime predicate, not a shorter grid)
        pl.when(qi * block_q + block_q - 1 - kj * block_k >= rel)(_body)
    else:
        _body()

    @pl.when(kj == num_k - 1)
    def _final():
        acc_out_ref[0] = acc_s[...]
        m_out_ref[0] = m_s[:, :1]
        l_out_ref[0] = l_s[:, :1]


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret"),
)
def flash_attention_carry(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    acc: jax.Array,
    m: jax.Array,
    l: jax.Array,
    rel: jax.Array,
    causal: bool = True,
    block_q: int | None = None,
    block_k: int | None = None,
    interpret: bool = False,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """One flash pass of local Q against ONE K/V block with carried
    online-softmax state — the ring-attention inner step, score matrix never
    materialized. Shapes: q/k/v (B, H, Sq|Sk, D) (Hkv may divide H);
    acc (B, H, Sq, D) f32; m/l (B, H, Sq, 1) f32; ``rel`` scalar int32 =
    k_off - q_off in global positions. Sq/Sk must be multiples of 128 (ring
    shards are; no padding path here). Returns updated (acc, m, l);
    normalize ``acc / max(l, eps)`` after the last block."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    hkv, sk = k.shape[1], k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not a multiple of kv heads {hkv}")
    if sq % 128 or sk % 128:
        raise ValueError(f"carry kernel needs 128-multiple seq, got {sq}/{sk}")
    g = h // hkv
    if block_q is None:
        block_q = 256 if sq % 256 == 0 else 128
    if block_k is None:
        block_k = next(bk for bk in (512, 256, 128) if sk % bk == 0)
    sm_scale = 1.0 / math.sqrt(d)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hkv, sk, d)
    vf = v.reshape(b * hkv, sk, d)
    accf = acc.reshape(b * h, sq, d)
    mf = m.reshape(b * h, sq, 1)
    lf = l.reshape(b * h, sq, 1)
    rel_arr = jnp.asarray(rel, jnp.int32).reshape((1,))

    num_k = sk // block_k
    kernel = functools.partial(
        _flash_carry_kernel, sm_scale=sm_scale, causal=causal,
        block_q=block_q, block_k=block_k, num_k=num_k,
    )
    grid = (b * h, sq // block_q, num_k)
    kv_index = lambda i, j, kj: (i // h * hkv + (i % h) // g, kj, 0)
    q_index = lambda i, j, kj: (i, j, 0)
    stat_spec = pl.BlockSpec((1, block_q, 1), q_index)
    acc_o, m_o, l_o = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),       # rel
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_q, d), q_index),      # acc in
            stat_spec,                                   # m in
            stat_spec,                                   # l in
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            stat_spec,
            stat_spec,
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b * h, sq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=interpret,
        compiler_params=_tpu_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")
        ),
    )(rel_arr, qf, kf, vf, accf, mf, lf)
    return (
        acc_o.reshape(b, h, sq, d),
        m_o.reshape(b, h, sq, 1),
        l_o.reshape(b, h, sq, 1),
    )


# ---------------------------------------------------------------------------
# Paged-KV attention (continuous decode engine)
# ---------------------------------------------------------------------------

def paged_gather_kv(
    pages: jax.Array, tables: jax.Array, page_tokens: int
) -> jax.Array:
    """Assemble each lane's logical K or V row from the shared page arena.

    ``pages`` is the arena ``(n_pages, Hkv, page_tokens, D)``; ``tables``
    is the per-lane block table ``(S, pages_per_slot)`` of page indices.
    Logical position ``p`` of lane ``s`` lives at
    ``pages[tables[s, p // page_tokens], :, p % page_tokens]`` — the gather
    lays pages out in block-table order, so the result
    ``(S, Hkv, pages_per_slot * page_tokens, D)`` is positionally identical
    to a dense per-lane cache row and the dense causal mask applies as-is.
    A lane only ever gathers its OWN pages plus the shared trash page, so
    no cross-lane bytes are touched even before masking.

    SILENT-JUNK HAZARD (documented + checked, ISSUE 14): a table entry of
    0 is the trash page — last-writer junk from every parked lane. Junk is
    harmless only while it sits strictly ABOVE ``pos`` (the mask hides it);
    a live lane whose table maps page 0 at a slot BELOW ``pos // page_tokens``
    would attend over garbage with no error anywhere. The admission
    protocol guarantees this cannot happen (reserve_pages covers the full
    prompt + max_new budget up front); ``TPUSC_PAGECHECK=1`` turns the
    guarantee into an assertion at every chunk dispatch
    (model_runtime._check_trash_unreachable)."""
    s_lanes, pps = tables.shape
    _, hkv, pt, d = pages.shape
    gathered = pages[tables]                       # (S, PPS, Hkv, pt, D)
    return gathered.transpose(0, 2, 1, 3, 4).reshape(
        s_lanes, hkv, pps * pt, d
    )


def paged_decode_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    page_tokens: int,
) -> jax.Array:
    """Single-position attention over a paged KV arena — the decode-step
    counterpart of the dense slot read in ``_forward_cached_dyn``.

    Shapes: q ``(S, Hq, 1, D)`` (one query per lane, post-RoPE),
    k_pages/v_pages ``(n_pages, Hkv, page_tokens, D)``, tables
    ``(S, pages_per_slot)`` int32, pos ``(S,)`` int32 query positions.
    Returns f32 ``(S, Hq, 1, D)``.

    The math mirrors the dense path operation-for-operation (GQA grouped
    K/V, dots in the stored dtype with f32 accumulation via
    ``preferred_element_type``, mask ``k_pos <= pos`` at NEG_INF, probs
    cast to the cache dtype for the value dot) so that with
    ``page_tokens`` dividing ``max_seq`` the reductions run over the same
    length in the same order and greedy decode is token-for-token
    identical to the dense engine. Junk rows — trash-page bytes behind
    unreserved table entries and a lane's own not-yet-written positions —
    sit strictly above ``pos`` and are masked before the softmax."""
    s_lanes, hq, _, d = q.shape
    hkv = k_pages.shape[1]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    kc = paged_gather_kv(k_pages, tables, page_tokens)   # (S, Hkv, L, D)
    vc = paged_gather_kv(v_pages, tables, page_tokens)
    qg = q.reshape(s_lanes, hkv, g, 1, d)
    s = jnp.einsum(
        "bkgqd,bkld->bkgql", qg, kc, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    k_pos = jnp.arange(kc.shape[2])
    mask = k_pos[None, None, :] <= pos[:, None, None]    # (S, 1, L)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bkgql,bkld->bkgqd", p.astype(vc.dtype), vc,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(s_lanes, hq, 1, d)


def paged_verify_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    page_tokens: int,
) -> jax.Array:
    """Multi-token-query attention over a paged KV arena — the verify pass
    of in-engine speculative decoding (ISSUE 16).

    Shapes: q ``(S, Hq, T, D)`` (T = spec_tokens + 1 query positions per
    lane, post-RoPE), k_pages/v_pages ``(n_pages, Hkv, page_tokens, D)``,
    tables ``(S, pages_per_slot)`` int32, pos ``(S,)`` int32 positions of
    each lane's FIRST query token. Returns f32 ``(S, Hq, T, D)``.

    Query index ``t`` of lane ``s`` sits at position ``pos[s] + t`` and
    attends with the causal mask ``k_pos <= pos[s] + t`` — with T == 1 this
    degenerates exactly to ``paged_decode_attention``'s mask, and the math
    below mirrors it operation-for-operation (GQA grouped K/V, f32
    accumulation, probs cast to the cache dtype) so the two paths are
    parity-exact over the shared positions. The caller has already
    scattered the T draft K/V rows into the lane's PRIVATE pages at
    ``pos..pos+T-1``; rows above the eventually-accepted prefix are junk a
    later round overwrites — same discipline as the solo verify chunk."""
    s_lanes, hq, t, d = q.shape
    hkv = k_pages.shape[1]
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    g = hq // hkv
    kc = paged_gather_kv(k_pages, tables, page_tokens)   # (S, Hkv, L, D)
    vc = paged_gather_kv(v_pages, tables, page_tokens)
    qg = q.reshape(s_lanes, hkv, g, t, d)
    s = jnp.einsum(
        "bkgqd,bkld->bkgql", qg, kc, preferred_element_type=jnp.float32
    ) / math.sqrt(d)
    k_pos = jnp.arange(kc.shape[2])
    q_pos = pos[:, None] + jnp.arange(t)[None, :]        # (S, T)
    mask = k_pos[None, None, :] <= q_pos[:, :, None]     # (S, T, L)
    s = jnp.where(mask[:, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum(
        "bkgql,bkld->bkgqd", p.astype(vc.dtype), vc,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(s_lanes, hq, t, d)


def dequantize_pages(pages: jax.Array, scales: jax.Array) -> jax.Array:
    """Expand an int8 page arena ``(n_pages, Hkv, page_tokens, D)`` against
    its per-(page, head, token) f32 scales ``(n_pages, Hkv, page_tokens)``
    back to f32 rows. This is the REFERENCE dequant — the Pallas paged
    kernel performs the same multiply in VMEM on the one page it just
    streamed, so the f32 arena never materializes in HBM on the fast path."""
    return pages.astype(jnp.float32) * scales[..., None]


def _paged_decode_kernel(
    tables_ref, pos_ref, q_ref, k_ref, v_ref, *rest, sm_scale: float,
    page_tokens: int, num_pages: int, quantized: bool,
):
    """One (lane, kv-head, table-slot) grid step of paged decode attention.

    The grid's last dimension walks the lane's block-table row; the
    BlockSpec index maps (scalar-prefetched tables/pos) turn each step into
    a DMA of exactly one arena page — the kernel reads the arena IN PLACE,
    so the ``pages[tables]`` gathered intermediate of ``paged_gather_kv``
    (a full extra HBM round-trip of every lane's live KV per decode step)
    never exists. Online-softmax carry lives in VMEM scratch exactly like
    ``_flash_streamed_kernel``; table slots past ``pos // page_tokens`` are
    clamped to the last live page by the index map (consecutive equal block
    indices elide the re-fetch) and skipped by ``pl.when``, so bytes
    streamed track each lane's true length, not pages_per_slot.

    ``quantized``: K/V blocks arrive int8 with per-(page, head, token) f32
    scale rows; dequant happens here, on the VMEM-resident page — int8
    halves the HBM bytes per KV token, which is the whole win."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_s, m_s, l_s = rest
    else:
        o_ref, acc_s, m_s, l_s = rest

    s_i = pl.program_id(0)
    j = pl.program_id(2)
    pos = pos_ref[s_i]

    @pl.when(j == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    # a table slot is live iff its first token is at or before pos — the
    # same visibility rule as the reference mask, so the two paths reduce
    # over the same token set
    @pl.when(j <= pos // page_tokens)
    def _body():
        q = q_ref[0, 0]                                     # (g, d)
        k = k_ref[0, 0]                                     # (pt, d)
        v = v_ref[0, 0]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                        # (g, pt) f32
        k_pos = j * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(k_pos <= pos, s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # j <= pos//page_tokens guarantees >= 1 visible token in this page,
        # so m_new is finite and masked entries underflow to exactly 0
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_s[...] = acc_s[...] * alpha + pv
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(j == num_pages - 1)
    def _final():
        o_ref[0, 0] = (
            acc_s[...] / jnp.maximum(l_s[:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_tokens", "interpret"))
def paged_decode_attention_kernel(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    *,
    page_tokens: int,
    interpret: bool = False,
) -> jax.Array:
    """Fused paged decode attention: same contract as
    ``paged_decode_attention`` (q ``(S, Hq, 1, D)``, arena pages
    ``(n_pages, Hkv, page_tokens, D)``, tables ``(S, pages_per_slot)``,
    pos ``(S,)`` -> f32 ``(S, Hq, 1, D)``), but ONE pass over the KV bytes:
    block tables and positions ride in as scalar-prefetch operands so the
    Pallas pipeline itself walks each lane's pages straight out of the
    arena. With ``k_scale``/``v_scale`` (``(n_pages, Hkv, page_tokens)``
    f32) the arena is int8 and dequantized in VMEM per streamed page.

    Tables/pos are TRACED data (SMEM), same discipline as the reference
    path: page recycling/admission churn never mints a new program."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_lanes, hq, _, d = q.shape
    n_pages_arena, hkv, pt, _ = k_pages.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if pt != page_tokens:
        raise ValueError(f"arena page_tokens {pt} != {page_tokens}")
    g = hq // hkv
    pps = tables.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    quantized = k_scale is not None

    qg = q.reshape(s_lanes, hkv, g, d)
    tables = tables.astype(jnp.int32)
    pos = pos.astype(jnp.int32)

    def q_index(s, h, j, tbl, ps):
        return (s, h, 0, 0)

    def kv_index(s, h, j, tbl, ps):
        # clamp dead trailing slots to the lane's last live page: the block
        # index repeats, so the pipeline skips the re-fetch — streamed bytes
        # scale with pos, and the trash page behind unreserved entries is
        # only ever touched where the reference would read it too
        jj = jnp.minimum(j, ps[s] // page_tokens)
        return (tbl[s, jj], h, 0, 0)

    def scale_index(s, h, j, tbl, ps):
        jj = jnp.minimum(j, ps[s] // page_tokens)
        return (tbl[s, jj], h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, g, d), q_index),
        pl.BlockSpec((1, 1, pt, d), kv_index),
        pl.BlockSpec((1, 1, pt, d), kv_index),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, pt), scale_index),
            pl.BlockSpec((1, 1, pt), scale_index),
        ]
        operands += [k_scale, v_scale]

    kernel = functools.partial(
        _paged_decode_kernel, sm_scale=sm_scale, page_tokens=page_tokens,
        num_pages=pps, quantized=quantized,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_lanes, hkv, pps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, g, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((g, d), jnp.float32),      # acc
            pltpu.VMEM((g, 128), jnp.float32),    # m (lane-bcast)
            pltpu.VMEM((g, 128), jnp.float32),    # l (lane-bcast)
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_lanes, hkv, g, d), jnp.float32),
        interpret=interpret,
        compiler_params=_tpu_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")
        ),
    )(tables, pos, *operands)
    return out.reshape(s_lanes, hq, 1, d)


TPU_BACKENDS = ("tpu", "axon")  # axon = tunneled TPU plugin in this image


def attention(q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = True) -> jax.Array:  # static-bounded: causal -- boolean domain (two programs max)
    """Dispatch: Pallas flash kernel on TPU, jnp reference elsewhere (the
    kernel's interpret mode is for tests, too slow for CPU serving).

    Gate: head_dim a multiple of 64 (Mosaic pads the 128-lane dim; d=64 still
    wins from the unmaterialized (S,S) score matrix — the round-1 d%128 gate
    excluded the most common head dims, VERDICT.md weak #2), seq >= 128 so
    there's at least one full block of work."""
    if (
        jax.default_backend() in TPU_BACKENDS
        and q.shape[-1] % 64 == 0
        and q.shape[2] >= 128
        and k.shape[2] == q.shape[2]  # kernel assumes self-attention lengths
        and q.shape[1] % k.shape[1] == 0
    ):
        return flash_attention(q, k, v, causal=causal)
    return attention_reference(q, k, v, causal=causal)


# Tests flip this to force the Pallas paged kernel through its interpreter
# on CPU (tier-1 parity without a chip). Trace-time only: flip it BEFORE the
# first paged dispatch or clear the jit caches of callers.
PAGED_KERNEL_INTERPRET = False


def paged_attention(  # static-bounded: kernel, page_tokens, PAGED_KERNEL_INTERPRET -- kernel and the interpret flag are booleans (two programs max); page_tokens is one value per slot state (ServingConfig kv_page_tokens)
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    page_tokens: int,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    kernel: bool = True,
) -> jax.Array:
    """Paged decode dispatch, mirroring ``attention``'s gate: the fused
    Pallas kernel on TPU backends when shapes qualify (head_dim a multiple
    of 64 — Mosaic pads the lane dim; GQA divisibility), the gather+einsum
    reference everywhere else. ``kernel=False`` (serving.kv_paged_kernel)
    forces the reference path unconditionally — byte-for-byte today's
    behavior. An int8 arena (``k_scale`` present) is dequantized in-kernel
    on the fast path; the reference fallback materializes the dequantized
    pages first (exact same math, minus the bandwidth win)."""
    if kernel and (
        PAGED_KERNEL_INTERPRET
        or (
            jax.default_backend() in TPU_BACKENDS
            and q.shape[-1] % 64 == 0
            and q.shape[1] % k_pages.shape[1] == 0
        )
    ):
        return paged_decode_attention_kernel(
            q, k_pages, v_pages, tables, pos, k_scale, v_scale,
            page_tokens=page_tokens, interpret=PAGED_KERNEL_INTERPRET,
        )
    if k_scale is not None:
        k_pages = dequantize_pages(k_pages, k_scale)
        v_pages = dequantize_pages(v_pages, v_scale)
    return paged_decode_attention(q, k_pages, v_pages, tables, pos,
                                  page_tokens)


def _paged_verify_kernel(
    tables_ref, pos_ref, q_ref, k_ref, v_ref, *rest, sm_scale: float,
    page_tokens: int, num_pages: int, num_queries: int, group: int,
    quantized: bool,
):
    """One (lane, kv-head, table-slot) grid step of paged VERIFY attention.

    Same streaming skeleton as ``_paged_decode_kernel``, but the query
    block carries T query positions folded into the row axis — row ``r``
    of the ``(T*g, d)`` block is query offset ``r // g`` of the lane, at
    position ``pos + r // g``. One extra iota-compare per page gives each
    row its own causal frontier, so the T-position verify pass of a spec
    round streams the arena exactly ONCE instead of T times. Visibility
    extends to the page holding ``pos + T - 1`` (the draft rows the caller
    just scattered); rows whose frontier ends earlier simply mask the
    whole page — at j == 0 every row sees k_pos 0, so the online-softmax
    max is finite from the first step and fully-masked later pages
    contribute exp(NEG_INF - finite) == 0, never NaN."""
    from jax.experimental import pallas as pl

    if quantized:
        ks_ref, vs_ref, o_ref, acc_s, m_s, l_s = rest
    else:
        o_ref, acc_s, m_s, l_s = rest

    s_i = pl.program_id(0)
    j = pl.program_id(2)
    pos = pos_ref[s_i]

    @pl.when(j == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)

    # a table slot is live iff ANY query row can see it: the deepest
    # frontier is pos + T - 1 (the last draft row, written this round)
    @pl.when(j <= (pos + num_queries - 1) // page_tokens)
    def _body():
        q = q_ref[0, 0]                                     # (T*g, d)
        k = k_ref[0, 0]                                     # (pt, d)
        v = v_ref[0, 0]
        if quantized:
            k = k.astype(jnp.float32) * ks_ref[0, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, 0][:, None]
            q = q.astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                                        # (T*g, pt) f32
        k_pos = j * page_tokens + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        # row r is query offset r // g: per-row causal frontier pos + r//g
        q_off = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0) // group
        s = jnp.where(k_pos <= pos + q_off, s, NEG_INF)
        m_prev = m_s[:, :1]
        l_prev = l_s[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc_s[...] = acc_s[...] * alpha + pv
        m_s[...] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[...] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(j == num_pages - 1)
    def _final():
        o_ref[0, 0] = (
            acc_s[...] / jnp.maximum(l_s[:, :1], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("page_tokens", "interpret"))
def paged_verify_attention_kernel(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    *,
    page_tokens: int,
    interpret: bool = False,
) -> jax.Array:
    """Fused paged verify attention: same contract as
    ``paged_verify_attention`` (q ``(S, Hq, T, D)``, arena pages, tables,
    pos -> f32 ``(S, Hq, T, D)``) with one pass over the KV bytes. The T
    query positions fold into the GQA group axis — blocks become
    ``(T*g, d)`` with row ``r`` at query offset ``r // g`` — so the grid,
    index maps, and scalar-prefetch discipline are identical to
    ``paged_decode_attention_kernel`` and T never becomes a grid dim.
    T is a shape, not a static arg: one program per (config, spec_tokens)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_lanes, hq, t_q, d = q.shape
    n_pages_arena, hkv, pt, _ = k_pages.shape
    if hq % hkv:
        raise ValueError(f"q heads {hq} not a multiple of kv heads {hkv}")
    if pt != page_tokens:
        raise ValueError(f"arena page_tokens {pt} != {page_tokens}")
    g = hq // hkv
    pps = tables.shape[1]
    sm_scale = 1.0 / math.sqrt(d)
    quantized = k_scale is not None

    # (S, Hq, T, D) -> (S, hkv, T*g, d) with row r = t*g + gi, so the
    # kernel recovers the query offset as r // g
    qg = (
        q.reshape(s_lanes, hkv, g, t_q, d)
        .transpose(0, 1, 3, 2, 4)
        .reshape(s_lanes, hkv, t_q * g, d)
    )
    tables = tables.astype(jnp.int32)
    pos = pos.astype(jnp.int32)

    def q_index(s, h, j, tbl, ps):
        return (s, h, 0, 0)

    def kv_index(s, h, j, tbl, ps):
        # the last live page now holds pos + T - 1 (draft rows written
        # this round); clamp dead trailing slots to it, same elision as
        # the decode kernel
        jj = jnp.minimum(j, (ps[s] + t_q - 1) // page_tokens)
        return (tbl[s, jj], h, 0, 0)

    def scale_index(s, h, j, tbl, ps):
        jj = jnp.minimum(j, (ps[s] + t_q - 1) // page_tokens)
        return (tbl[s, jj], h, 0)

    in_specs = [
        pl.BlockSpec((1, 1, t_q * g, d), q_index),
        pl.BlockSpec((1, 1, pt, d), kv_index),
        pl.BlockSpec((1, 1, pt, d), kv_index),
    ]
    operands = [qg, k_pages, v_pages]
    if quantized:
        in_specs += [
            pl.BlockSpec((1, 1, pt), scale_index),
            pl.BlockSpec((1, 1, pt), scale_index),
        ]
        operands += [k_scale, v_scale]

    kernel = functools.partial(
        _paged_verify_kernel, sm_scale=sm_scale, page_tokens=page_tokens,
        num_pages=pps, num_queries=t_q, group=g, quantized=quantized,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_lanes, hkv, pps),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, t_q * g, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((t_q * g, d), jnp.float32),      # acc
            pltpu.VMEM((t_q * g, 128), jnp.float32),    # m (lane-bcast)
            pltpu.VMEM((t_q * g, 128), jnp.float32),    # l (lane-bcast)
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            (s_lanes, hkv, t_q * g, d), jnp.float32
        ),
        interpret=interpret,
        compiler_params=_tpu_compiler_params(
            pltpu, ("parallel", "parallel", "arbitrary")
        ),
    )(tables, pos, *operands)
    return (
        out.reshape(s_lanes, hkv, t_q, g, d)
        .transpose(0, 1, 3, 2, 4)
        .reshape(s_lanes, hq, t_q, d)
    )


def paged_attention_verify(  # static-bounded: kernel, page_tokens, PAGED_KERNEL_INTERPRET -- kernel and the interpret flag are booleans (two programs max); page_tokens is one value per slot state (ServingConfig kv_page_tokens)
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    tables: jax.Array,
    pos: jax.Array,
    page_tokens: int,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    kernel: bool = True,
) -> jax.Array:
    """Multi-token-query (verify) dispatch with exactly ``paged_attention``'s
    gate: fused Pallas kernel on TPU backends when shapes qualify, the
    gather+einsum reference elsewhere, ``kernel=False`` forcing the
    reference unconditionally. Per-row acceptance downstream is traced
    data; only (config, spec_tokens) mints programs here."""
    if kernel and (
        PAGED_KERNEL_INTERPRET
        or (
            jax.default_backend() in TPU_BACKENDS
            and q.shape[-1] % 64 == 0
            and q.shape[1] % k_pages.shape[1] == 0
        )
    ):
        return paged_verify_attention_kernel(
            q, k_pages, v_pages, tables, pos, k_scale, v_scale,
            page_tokens=page_tokens, interpret=PAGED_KERNEL_INTERPRET,
        )
    if k_scale is not None:
        k_pages = dequantize_pages(k_pages, k_scale)
        v_pages = dequantize_pages(v_pages, v_scale)
    return paged_verify_attention(q, k_pages, v_pages, tables, pos,
                                  page_tokens)

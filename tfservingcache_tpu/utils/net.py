"""Network helpers."""

from __future__ import annotations

import socket
from typing import AsyncIterator


async def aiter_lines(resp) -> AsyncIterator[bytes]:
    """Yield newline-delimited records from an aiohttp streaming response.

    ``async for line in resp.content`` readline-caps at 64 KiB and raises on
    longer lines — a single k8s Endpoints watch event for a few hundred pods
    exceeds that — so buffer arbitrary chunks and split explicitly."""
    buf = b""
    async for chunk in resp.content.iter_any():
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if line.strip():
                yield line
    if buf.strip():
        yield buf


def outbound_ip(probe_addr: tuple[str, int] = ("8.8.8.8", 80)) -> str:
    """Best-effort outbound interface IP via the UDP-connect trick, falling
    back to localhost (reference etcd.go:152-166)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(0.5)
            s.connect(probe_addr)
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"

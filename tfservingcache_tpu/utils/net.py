"""Network helpers."""

from __future__ import annotations

import socket


def outbound_ip(probe_addr: tuple[str, int] = ("8.8.8.8", 80)) -> str:
    """Best-effort outbound interface IP via the UDP-connect trick, falling
    back to localhost (reference etcd.go:152-166)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(0.5)
            s.connect(probe_addr)
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"

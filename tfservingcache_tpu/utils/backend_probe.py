"""Bounded child-process probe of the configured JAX backend.

The image registers the axon PJRT plugin (a tunneled TPU). When the tunnel
is down, in-process backend init blocks for ~20 minutes before raising
Unavailable — any caller that wants to *decide* (fall back to CPU, skip a
hardware path, report a diagnostic) must ask a child process with a timeout
instead of touching ``jax.devices()`` itself. ``bench.py`` and
``tools/tpu_bench_watcher.py`` carry their own battle-tested variants whose
exact behavior is baked into committed artifacts; new callers should use
this one rather than hand-rolling a fourth.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time

# per-process memo for cached_backend_answers(); None = never probed
_memo: tuple[bool, str] | None = None  # guarded-by: _memo_lock
_memo_lock = threading.Lock()


def cached_backend_answers(
    timeout_s: float = 90.0, retries: int = 0, backoff_s: float = 5.0
) -> tuple[bool, str]:
    """``backend_answers`` with the verdict memoized per process.

    A process that probes more than once (driver entry retries, several
    subsystems each deciding CPU-vs-TPU) would otherwise pay the full
    child-process spin-up — worst case ~90 s per probe, and with the default
    retry schedule nearly 5 minutes — every time, for an answer that does
    not change within a process's lifetime: the backend env is fixed at
    startup and a mid-process tunnel recovery can't be used anyway once
    callers have pinned CPU. First call wins; later calls (any arguments)
    return the memoized verdict. Defaults to ``retries=0``: the memo makes
    the verdict permanent, so burning minutes of backoff to avoid
    memoizing a blip is a worse trade than one bounded attempt.

    ``backend_answers`` itself stays uncached for callers (and tests) that
    need a fresh probe.
    """
    global _memo
    with _memo_lock:
        if _memo is None:
            _memo = backend_answers(
                timeout_s=timeout_s, retries=retries, backoff_s=backoff_s
            )
        return _memo


def backend_answers(
    timeout_s: float = 90.0, retries: int = 2, backoff_s: float = 5.0
) -> tuple[bool, str]:
    """(ok, diagnostic): does the configured backend come up in a child?

    Retries transient failures so a momentary tunnel blip doesn't silently
    downgrade the caller to CPU. The child inherits the environment, so it
    resolves exactly the backend the caller's in-process init would.
    """
    code = (
        "import jax; d = jax.devices();"
        "print('ok', d[0].platform, len(d))"
    )
    last = ""
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], timeout=timeout_s,
                capture_output=True, text=True,
            )
            if r.returncode == 0 and r.stdout.startswith("ok"):
                return True, r.stdout.strip()
            last = (r.stderr or r.stdout).strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"backend init did not answer within {timeout_s:.0f}s"
        if attempt < retries:
            time.sleep(backoff_s * (attempt + 1))
    return False, last

"""Bounded child-process probe of the configured JAX backend.

The image registers the axon PJRT plugin (a tunneled TPU). When the tunnel
is down, in-process backend init blocks for ~20 minutes before raising
Unavailable — any caller that wants to *decide* (fall back to CPU, skip a
hardware path, report a diagnostic) must ask a child process with a timeout
instead of touching ``jax.devices()`` itself. ``bench.py`` and
``tools/tpu_bench_watcher.py`` carry their own battle-tested variants whose
exact behavior is baked into committed artifacts; new callers should use
this one rather than hand-rolling a fourth.
"""

from __future__ import annotations

import subprocess
import sys
import time


def backend_answers(
    timeout_s: float = 90.0, retries: int = 2, backoff_s: float = 5.0
) -> tuple[bool, str]:
    """(ok, diagnostic): does the configured backend come up in a child?

    Retries transient failures so a momentary tunnel blip doesn't silently
    downgrade the caller to CPU. The child inherits the environment, so it
    resolves exactly the backend the caller's in-process init would.
    """
    code = (
        "import jax; d = jax.devices();"
        "print('ok', d[0].platform, len(d))"
    )
    last = ""
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code], timeout=timeout_s,
                capture_output=True, text=True,
            )
            if r.returncode == 0 and r.stdout.startswith("ok"):
                return True, r.stdout.strip()
            last = (r.stderr or r.stdout).strip()[-300:]
        except subprocess.TimeoutExpired:
            last = f"backend init did not answer within {timeout_s:.0f}s"
        if attempt < retries:
            time.sleep(backoff_s * (attempt + 1))
    return False, last

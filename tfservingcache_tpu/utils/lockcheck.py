"""TPUSC_LOCKCHECK=1 — dynamic complement to tpusc-check's TPUSC001 rule.

A class carrying a ``_tpusc_guarded`` registry (``{"_field": "_lock"}``) and
the ``@lockchecked`` decorator gets every guarded-field access checked at
runtime: the declared lock must be held (``Lock.locked()`` /
``RLock._is_owned()`` / ``Condition._is_owned()``) or a violation is
recorded.  Violations are collected — not raised — so a soak run surfaces
every distinct unguarded access instead of dying on the first; tests call
``assert_clean()`` at the end.

When ``TPUSC_LOCKCHECK`` is unset the decorator is an exact no-op: classes
are returned unchanged and there is zero steady-state overhead.

Known imprecision (shared with every sampling checker): ``Lock.locked()``
is true when *any* thread holds the lock, so a cross-thread race where the
other thread holds the lock at the sampled instant can pass.  RLocks and
Conditions use owner-aware ``_is_owned`` and do not have this gap.  The
static rule (TPUSC001) has no such blind spot for ``self.`` accesses.
"""

from __future__ import annotations

import os
import sys
import threading

ENABLED = os.environ.get("TPUSC_LOCKCHECK", "") == "1"

_violations: list[str] = []
_seen: set[tuple] = set()
_reg_lock = threading.Lock()
_MAX_VIOLATIONS = 1000
_READY_FLAG = "_tpusc_lc_ready"


def violations() -> list[str]:
    with _reg_lock:
        return list(_violations)


def reset() -> None:
    with _reg_lock:
        _violations.clear()
        _seen.clear()


def assert_clean() -> None:
    """No-op when disabled; raises with every recorded violation otherwise."""
    if not ENABLED:
        return
    got = violations()
    if got:
        raise AssertionError(
            "TPUSC_LOCKCHECK recorded guarded-field violations:\n  "
            + "\n  ".join(got)
        )


def _held(lock: object) -> bool:
    is_owned = getattr(lock, "_is_owned", None)  # RLock, Condition
    if callable(is_owned):
        try:
            return bool(is_owned())
        except Exception:
            pass
    locked = getattr(lock, "locked", None)
    if callable(locked):
        try:
            return bool(locked())
        except Exception:
            pass
    return True  # not a lock-like object: don't generate noise


def _record(cls_name: str, field: str, lockname: str, op: str) -> None:
    # stack: caller -> __getattribute__/__setattr__ -> _check -> _record
    frame = sys._getframe(3)
    site = f"{frame.f_code.co_filename.rsplit('/', 1)[-1]}:{frame.f_lineno}"
    key = (cls_name, field, op, site)
    with _reg_lock:
        if key in _seen or len(_violations) >= _MAX_VIOLATIONS:
            return
        _seen.add(key)
        _violations.append(
            f"{cls_name}.{field} {op} at {site} without holding {lockname}"
        )


def lockchecked(cls):
    """Class decorator: instrument ``_tpusc_guarded`` fields when enabled."""
    if not ENABLED:
        return cls
    guarded: dict[str, str] = {}
    for base in reversed(cls.__mro__):
        guarded.update(getattr(base, "_tpusc_guarded", None) or {})
    if not guarded:
        return cls

    orig_init = cls.__init__
    orig_getattribute = cls.__getattribute__
    orig_setattr = cls.__setattr__

    def _check(self, name: str, op: str) -> None:
        try:
            object.__getattribute__(self, _READY_FLAG)
        except AttributeError:
            return  # still constructing: single-owner
        lockname = guarded[name]
        try:
            lock = object.__getattribute__(self, lockname)
        except AttributeError:
            _record(cls.__name__, name, lockname, f"{op} (lock missing)")
            return
        if not _held(lock):
            _record(cls.__name__, name, lockname, op)

    def __init__(self, *args, **kwargs):
        # Depth-track nested wrapped __init__s (decorated subclass calling a
        # decorated base via super()): only the OUTERMOST completion arms the
        # checks, else the base's return would flag the subclass's remaining
        # construction writes.
        try:
            depth = object.__getattribute__(self, "_tpusc_lc_depth")
        except AttributeError:
            depth = 0
        object.__setattr__(self, "_tpusc_lc_depth", depth + 1)
        try:
            orig_init(self, *args, **kwargs)
        finally:
            object.__setattr__(self, "_tpusc_lc_depth", depth)
        if depth == 0:
            object.__setattr__(self, _READY_FLAG, True)

    def __getattribute__(self, name):
        if name in guarded:
            _check(self, name, "read")
        return orig_getattribute(self, name)

    def __setattr__(self, name, value):
        if name in guarded:
            _check(self, name, "write")
        return orig_setattr(self, name, value)

    cls.__init__ = __init__
    cls.__getattribute__ = __getattribute__
    cls.__setattr__ = __setattr__
    return cls

"""Per-tenant resource accounting: the cost-attribution ledger.

The flight recorder (utils/flight_recorder.py) made the ENGINE observable;
this module makes the TENANTS observable — who is spending the HBM, the KV
arena pages, the decode steps, and the peer wire. Every tier feeds the same
per-tenant (``name@version``) ledger of monotonic resource integrals:

- **Engine steps** (runtime/batcher.py): each chunk boundary / batch drain
  lands its prefill and decode step-seconds plus tokens in/out on the one
  tenant the dispatch served (each scheduler thread and each coalesced
  batch is single-model by construction, so there is no cross-tenant
  apportionment ambiguity at a boundary).
- **KV pages** (runtime/batcher.py page gauge sites): page-seconds as the
  integral of DISTINCT pages held over time — a shared-prefix page mapped
  by N lanes of the tenant counts once, matching ``page_stats()``'s
  shared+private census, so Σ per-tenant page-seconds equals the arena
  occupancy integral (the conservation law tests/test_accounting.py pins).
- **Residency** (runtime/model_runtime.py, cache/host_tier.py,
  cache/manager.py): HBM / host-DRAM / disk byte-seconds from gauge stamps
  at load/evict sites, plus cold-load seconds and counts by source tier.
- **The wire** (protocol/peer_transfer.py): bytes this node streams to
  peers on a tenant's behalf — work done FOR OTHERS is attributed to the
  tenant that caused it, not lost.

Integrals use the gauge-integral trick: a level change at time t folds
``prev_level * (t - t_prev)`` into the running total, so reads just settle
the live levels to "now". Everything is monotonic; the ``/monitoring/
tenants`` endpoint additionally keeps reset-on-scrape marks (like the
flight ring's watermarks) so each scrape interval can read its own window.

The **dominant-share** score ranks tenants the DRF way: a tenant's share
of each dimension's fleet total, maxed over dimensions. When one tenant's
share of recent step-time exceeds ``noisy_neighbor_share`` while another
tenant has rows queued, the ledger fires a ``noisy_neighbor`` flight dump
(RECORDER's per-(reason, model) cooldown dedupes the stream to one file
per incident).

Like the recorder, the ledger is a process-wide default instance
(``LEDGER``): accounting is write-mostly, bounded, and never raises on the
hot path. Tests construct their own instances or clear the global.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any

from tfservingcache_tpu.utils.flight_recorder import RECORDER
from tfservingcache_tpu.utils.lockcheck import lockchecked
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("accounting")

# Monotonic integral dimensions, in wire order: NodeStatus piggybacks each
# tenant as a plain list of these values (cluster/status.py), so — like
# flight_recorder.STEP_FIELDS — new names go at the END and existing
# positions never change.
DIMENSIONS = (
    "tokens_in",              # prompt tokens admitted
    "tokens_out",             # tokens emitted (excludes wasted overshoot)
    "prefill_step_seconds",   # wall seconds spent prefilling this tenant
    "decode_step_seconds",    # wall seconds of decode dispatches
    "kv_page_seconds",        # integral of distinct KV pages held x time
    "hbm_byte_seconds",       # integral of HBM residency bytes x time
    "host_byte_seconds",      # integral of host-tier DRAM bytes x time
    "disk_byte_seconds",      # integral of disk-cache bytes x time
    "cold_load_seconds",      # wall seconds of cold loads (all tiers)
    "peer_bytes_served",      # bytes streamed to peers for this tenant
)

# Live levels the ledger integrates over time -> the integral they feed.
GAUGE_DIMS = {
    "kv_pages": "kv_page_seconds",
    "hbm_bytes": "hbm_byte_seconds",
    "host_bytes": "host_byte_seconds",
    "disk_bytes": "disk_byte_seconds",
}


class _Account:
    """One tenant's ledger row. Mutated only under TenantLedger._lock."""

    __slots__ = ("totals", "gauges", "owners", "loads", "load_counts",
                 "marks", "published", "published_loads")

    def __init__(self) -> None:
        self.totals: dict[str, float] = dict.fromkeys(DIMENSIONS, 0.0)
        self.gauges: dict[str, tuple[float, float]] = {}  # dim -> (level, t)
        self.owners: dict[str, str] = {}  # dim -> gauge_sync owner token
        self.loads: dict[str, float] = {}        # tier -> cold seconds
        self.load_counts: dict[str, int] = {}    # tier -> reload count
        self.marks: dict[str, float] = {}        # totals at last reset scrape
        self.published: dict[str, float] = {}    # totals at last publish()
        self.published_loads: dict[str, float] = {}

    def settle(self, now: float) -> None:
        """Fold live gauge levels into their integrals up to ``now``."""
        for gdim, (level, t) in self.gauges.items():
            if now > t:
                if level:
                    self.totals[GAUGE_DIMS[gdim]] += level * (now - t)
                self.gauges[gdim] = (level, now)


@lockchecked
class TenantLedger:
    """Per-tenant resource integrals, one small lock around plain dicts:
    every write is a handful of float adds (the < 50 us chunk-boundary
    budget shared with the flight recorder), every read settles gauges to
    now first so integrals are exact at observation time."""

    _tpusc_guarded = {"_accounts": "_lock", "_win": "_lock"}

    def __init__(
        self,
        enabled: bool = True,
        noisy_share: float = 0.8,
        noisy_window_s: float = 5.0,
        noisy_min_step_s: float = 0.25,
    ) -> None:
        self.enabled = bool(enabled)
        self.noisy_share = float(noisy_share)
        self.noisy_window_s = float(noisy_window_s)
        self.noisy_min_step_s = float(noisy_min_step_s)
        self._lock = threading.Lock()
        self._accounts: dict[str, _Account] = {}
        # noisy-neighbor sliding window over note_step calls: the deque
        # holds (t, tenant, step_s, queued); the sums are maintained
        # incrementally so the hot path never rescans the window.
        self._win: collections.deque = collections.deque()
        self._win_step: dict[str, float] = {}    # guarded-by: _lock (via _win)
        self._win_queued: dict[str, int] = {}    # guarded-by: _lock (via _win)
        self._win_total = 0.0                    # guarded-by: _lock (via _win)
        # global arena occupancy integral (conservation check's other side)
        self._arena_level = 0.0
        self._arena_t: float | None = None
        self._arena_integral = 0.0

    def configure(
        self,
        enabled: bool | None = None,
        noisy_share: float | None = None,
        noisy_window_s: float | None = None,
        noisy_min_step_s: float | None = None,
    ) -> None:
        """Apply config to the process-wide ledger (server startup)."""
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if noisy_share is not None:
                self.noisy_share = float(noisy_share)
            if noisy_window_s is not None:
                self.noisy_window_s = float(noisy_window_s)
            if noisy_min_step_s is not None:
                self.noisy_min_step_s = float(noisy_min_step_s)

    # -- write side (hot path) ----------------------------------------------
    def _account(self, tenant: str) -> _Account:  # lock-held: _lock
        acct = self._accounts.get(tenant)
        if acct is None:
            acct = self._accounts[tenant] = _Account()
        return acct

    def note_step(
        self,
        tenant: str,
        engine: str,
        prefill_s: float = 0.0,
        decode_s: float = 0.0,
        tokens_in: int = 0,
        tokens_out: int = 0,
        queue_depth: int = 0,
    ) -> None:
        """One engine chunk boundary / batch drain for ``tenant``. Also
        advances the noisy-neighbor window; the dump (if any) fires outside
        the lock so file IO never blocks a scheduler thread's next admit."""
        if not self.enabled:
            return
        now = time.monotonic()
        step_s = prefill_s + decode_s
        noisy = None
        with self._lock:
            t = self._account(tenant).totals
            t["prefill_step_seconds"] += prefill_s
            t["decode_step_seconds"] += decode_s
            t["tokens_in"] += tokens_in
            t["tokens_out"] += tokens_out
            noisy = self._advance_window(now, tenant, step_s, queue_depth > 0)
        if noisy is not None:
            share, win_total = noisy
            # RECORDER's per-(reason, model) cooldown turns the per-step
            # stream of exceedances into one dump per incident.
            RECORDER.dump(
                "noisy_neighbor", model=tenant, engine=engine,
                step_share=round(share, 4),
                window_step_seconds=round(win_total, 6),
                window_s=self.noisy_window_s,
                share_threshold=self.noisy_share,
                tenants=self.snapshot(top=8)["top"],
            )

    def _advance_window(  # lock-held: _lock
        self, now: float, tenant: str, step_s: float, queued: bool
    ) -> tuple[float, float] | None:
        """Slide the step-time window; returns (share, window_total) when
        ``tenant`` is over the noisy threshold while ANOTHER tenant has
        rows queued. Caller holds _lock."""
        win = self._win
        win.append((now, tenant, step_s, queued))
        self._win_step[tenant] = self._win_step.get(tenant, 0.0) + step_s
        if queued:
            self._win_queued[tenant] = self._win_queued.get(tenant, 0) + 1
        self._win_total += step_s
        horizon = now - self.noisy_window_s
        while win and win[0][0] < horizon:
            t0, ten, s0, q0 = win.popleft()
            self._win_step[ten] -= s0
            self._win_total -= s0
            if q0:
                left = self._win_queued.get(ten, 1) - 1
                if left <= 0:
                    self._win_queued.pop(ten, None)
                else:
                    self._win_queued[ten] = left
        total = self._win_total
        if total < self.noisy_min_step_s:
            return None
        share = self._win_step.get(tenant, 0.0) / total
        if share < self.noisy_share:
            return None
        if not any(t != tenant for t in self._win_queued):
            return None
        return share, total

    def gauge_set(self, tenant: str, dim: str, level: float) -> None:
        """Stamp a live level (pages or bytes); integrates the PREVIOUS
        level over the elapsed interval into the dimension's integral."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            acct = self._account(tenant)
            prev = acct.gauges.get(dim)
            if prev is not None:
                lv, t = prev
                if lv and now > t:
                    acct.totals[GAUGE_DIMS[dim]] += lv * (now - t)
            acct.gauges[dim] = (float(level), now)

    def gauge_sync(
        self, dim: str, levels: dict[str, float], owner: str = ""
    ) -> None:
        """Bulk stamp one gauge dimension from a residency walk: tenants in
        ``levels`` get their level set; tenants this ``owner`` previously
        stamped that are absent from ``levels`` are zeroed (the evict side
        of a load/evict pair, without a hook at every evict site). The
        owner token scopes the zeroing so several runtimes/tiers in one
        process (multi-group, in-process test fleets) never zero each
        other's residents."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            for tenant, level in levels.items():
                acct = self._account(tenant)
                prev = acct.gauges.get(dim)
                if prev is not None:
                    lv, t = prev
                    if lv and now > t:
                        acct.totals[GAUGE_DIMS[dim]] += lv * (now - t)
                acct.gauges[dim] = (float(level), now)
                acct.owners[dim] = owner
            for tenant, acct in self._accounts.items():
                if tenant in levels or acct.owners.get(dim) != owner:
                    continue
                prev = acct.gauges.get(dim)
                if prev is None or prev[0] == 0.0:
                    continue
                lv, t = prev
                if now > t:
                    acct.totals[GAUGE_DIMS[dim]] += lv * (now - t)
                acct.gauges[dim] = (0.0, now)

    def note_arena(self, pages: int) -> None:
        """Global arena occupancy level (summed distinct pages across
        models) — the independent integral the conservation test compares
        Σ per-tenant kv_page_seconds against."""
        if not self.enabled:
            return
        now = time.monotonic()
        with self._lock:
            if self._arena_t is not None and now > self._arena_t:
                self._arena_integral += self._arena_level * (now - self._arena_t)
            self._arena_level = float(pages)
            self._arena_t = now

    def note_load(self, tenant: str, tier: str, seconds: float) -> None:
        """One ensure_servable resolution: which tier satisfied the reload
        (hbm | host | disk | peer | store) and what it cost in wall time."""
        if not self.enabled:
            return
        with self._lock:
            acct = self._account(tenant)
            acct.totals["cold_load_seconds"] += seconds
            acct.loads[tier] = acct.loads.get(tier, 0.0) + seconds
            acct.load_counts[tier] = acct.load_counts.get(tier, 0) + 1

    def note_peer_served(self, tenant: str, nbytes: int) -> None:
        """Bytes this node streamed TO a peer on the tenant's behalf."""
        if not self.enabled:
            return
        with self._lock:
            self._account(tenant).totals["peer_bytes_served"] += nbytes

    # -- read side -----------------------------------------------------------
    def arena_page_seconds(self) -> float:
        now = time.monotonic()
        with self._lock:
            if self._arena_t is not None and now > self._arena_t:
                self._arena_integral += self._arena_level * (now - self._arena_t)
                self._arena_t = now
            return self._arena_integral

    @staticmethod
    def _shares(
        accounts: dict[str, _Account],
    ) -> dict[str, tuple[float, str]]:
        """Dominant share per tenant: its fraction of each dimension's
        cross-tenant total, maxed over dimensions (DRF-style)."""
        sums = dict.fromkeys(DIMENSIONS, 0.0)
        for acct in accounts.values():
            for d in DIMENSIONS:
                sums[d] += acct.totals[d]
        out: dict[str, tuple[float, str]] = {}
        for tenant, acct in accounts.items():
            best, best_dim = 0.0, DIMENSIONS[0]
            for d in DIMENSIONS:
                if sums[d] > 0.0:
                    s = acct.totals[d] / sums[d]
                    if s > best:
                        best, best_dim = s, d
            out[tenant] = (best, best_dim)
        return out

    def snapshot(
        self,
        top: int = 0,
        dim: str | None = None,
        model: str | None = None,
        reset: bool = False,
    ) -> dict[str, Any]:
        """JSON-ready ledger state: the ``/monitoring/tenants`` payload.
        ``top`` keeps the k highest tenants (by ``dim``, default dominant
        share); ``model`` restricts to one tenant key and stamps
        ``model_filter``/``model_found`` so an unknown tenant is
        distinguishable from an idle one; ``reset`` consumes the
        reset-on-scrape marks (each scrape reads its own window)."""
        now = time.monotonic()
        with self._lock:
            for acct in self._accounts.values():
                acct.settle(now)
            shares = self._shares(self._accounts)
            found = model is None or model in self._accounts
            keys = list(self._accounts)
            if model is not None:
                keys = [k for k in keys if k == model]
            tenants: dict[str, Any] = {}
            for tenant in keys:
                acct = self._accounts[tenant]
                share, share_dim = shares[tenant]
                tenants[tenant] = {
                    "totals": {d: round(acct.totals[d], 6) for d in DIMENSIONS},
                    "window": {
                        d: round(acct.totals[d] - acct.marks.get(d, 0.0), 6)
                        for d in DIMENSIONS
                    },
                    "gauges": {
                        g: lv for g, (lv, _t) in acct.gauges.items() if lv
                    },
                    "loads": {
                        tier: {
                            "seconds": round(acct.loads[tier], 6),
                            "count": acct.load_counts.get(tier, 0),
                        }
                        for tier in acct.loads
                    },
                    "dominant_share": round(share, 6),
                    "dominant_dim": share_dim,
                }
                if reset:
                    acct.marks = dict(acct.totals)
            if self._arena_t is not None and now > self._arena_t:
                self._arena_integral += self._arena_level * (now - self._arena_t)
                self._arena_t = now
            arena = self._arena_integral
        if dim is not None and dim in DIMENSIONS:
            order = sorted(
                tenants, key=lambda t: tenants[t]["totals"][dim], reverse=True
            )
        else:
            order = sorted(
                tenants, key=lambda t: tenants[t]["dominant_share"],
                reverse=True,
            )
        if top > 0:
            order = order[:top]
            tenants = {t: tenants[t] for t in order}
        out: dict[str, Any] = {
            "dimensions": list(DIMENSIONS),
            "tenants": tenants,
            "top": order,
            "arena_page_seconds": round(arena, 6),
        }
        if model is not None:
            out["model_filter"] = model
            out["model_found"] = found
        return out

    def summary(self, max_tenants: int = 8) -> dict[str, list[float]]:
        """Compact wire form for the fleet status plane: tenant key -> the
        DIMENSIONS vector (positional, like STEP_FIELDS), top tenants by
        dominant share. FleetView sums these across nodes and recomputes
        fleet-wide dominant shares from the sums."""
        now = time.monotonic()
        with self._lock:
            for acct in self._accounts.values():
                acct.settle(now)
            shares = self._shares(self._accounts)
            order = sorted(
                self._accounts, key=lambda t: shares[t][0], reverse=True
            )[: max(0, max_tenants)]
            return {
                t: [round(self._accounts[t].totals[d], 3) for d in DIMENSIONS]
                for t in order
            }

    def publish(self, metrics: Any) -> None:
        """Mirror the ledger into the ``tpusc_tenant_*`` families at scrape
        time (delta-inc since the last publish, so the hot path never
        touches prometheus). No-op unless ``metrics.model_labels`` is on —
        per-tenant series without per-model labels would all fold into one
        meaningless all_models pile. Never raises (diagnostics path)."""
        if metrics is None or not getattr(metrics, "model_labels", False):
            return
        now = time.monotonic()
        try:
            with self._lock:
                shares = self._shares(self._accounts)
                work = []
                for tenant, acct in self._accounts.items():
                    acct.settle(now)
                    deltas = {}
                    for d in DIMENSIONS:
                        dv = acct.totals[d] - acct.published.get(d, 0.0)
                        if dv > 0.0:
                            deltas[d] = dv
                            acct.published[d] = acct.totals[d]
                    load_deltas = {}
                    for tier, secs in acct.loads.items():
                        dv = secs - acct.published_loads.get(tier, 0.0)
                        if dv > 0.0:
                            load_deltas[tier] = dv
                            acct.published_loads[tier] = secs
                    work.append((tenant, deltas, load_deltas, shares[tenant][0]))
            for tenant, deltas, load_deltas, share in work:
                name, _, version = tenant.rpartition("@")
                label = metrics.model_label(name or tenant, version)
                for d, dv in deltas.items():
                    if d == "tokens_in":
                        metrics.tenant_tokens.labels(label, "in").inc(dv)
                    elif d == "tokens_out":
                        metrics.tenant_tokens.labels(label, "out").inc(dv)
                    elif d == "prefill_step_seconds":
                        metrics.tenant_step_seconds.labels(label, "prefill").inc(dv)
                    elif d == "decode_step_seconds":
                        metrics.tenant_step_seconds.labels(label, "decode").inc(dv)
                    elif d == "kv_page_seconds":
                        metrics.tenant_kv_page_seconds.labels(label).inc(dv)
                    elif d == "hbm_byte_seconds":
                        metrics.tenant_byte_seconds.labels(label, "hbm").inc(dv)
                    elif d == "host_byte_seconds":
                        metrics.tenant_byte_seconds.labels(label, "host").inc(dv)
                    elif d == "disk_byte_seconds":
                        metrics.tenant_byte_seconds.labels(label, "disk").inc(dv)
                    elif d == "peer_bytes_served":
                        metrics.tenant_peer_bytes_served.labels(label).inc(dv)
                    # cold_load_seconds lands tier-split below
                for tier, dv in load_deltas.items():
                    metrics.tenant_cold_load_seconds.labels(label, tier).inc(dv)
                metrics.tenant_dominant_share.labels(label).set(share)
        except Exception as e:  # noqa: BLE001 — diagnostics must stay non-fatal
            log.warning("tenant metrics publish failed: %s", e)

    def clear(self) -> None:
        with self._lock:
            self._accounts.clear()
            self._win.clear()
            self._win_step.clear()
            self._win_queued.clear()
            self._win_total = 0.0
            self._arena_level = 0.0
            self._arena_t = None
            self._arena_integral = 0.0


# Process-wide default (same rationale as RECORDER / TRACER): accounting is
# always on, write-mostly, and bounded by tenant count; server startup
# applies config.observability knobs via configure(). Tests construct their
# own instances or clear the global.
LEDGER = TenantLedger()

"""Trustworthy on-device timing for jittable array functions.

Naive loops (call N times, ``block_until_ready``) lie on remote-attached
accelerators: async dispatch, transport-level result caching of identical
(executable, inputs) pairs, and transfer-queue backpressure all corrupt the
measurement — the round-2 flash-kernel "0.86x regression" and its later
"50x speedup" were BOTH artifacts of such timing. The fix: chain the N
executions *inside one compiled program* with a data dependency between
iterations, so the device must genuinely run every iteration, and subtract
a 1-iteration run to cancel dispatch/transfer overhead.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Sequence


def chained_device_time(
    fn: Callable[..., Any],
    args: Sequence[Any],
    iters: int = 16,
) -> float:
    """Seconds per call of ``fn(*args)`` measured on device.

    ``fn`` must be traceable and return an array (or pytree; the first leaf
    feeds the inter-iteration dependency). ``args[0]`` must be a float array:
    iteration i+1 perturbs it by ``1e-6 * out[0]`` so no two iterations are
    identical and the chain cannot be hoisted, cached, or reordered.
    """
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames="n")
    def loop(args, n):
        def body(carry, _):
            a, acc = carry
            out = fn(*a)
            first = jnp.ravel(jax.tree_util.tree_leaves(out)[0])[0]
            a = (a[0] + first.astype(a[0].dtype) * 1e-6,) + tuple(a[1:])
            return (a, acc + first.astype(jnp.float32)), None
        (a, acc), _ = jax.lax.scan(body, (tuple(args), jnp.float32(0)), None, length=n)
        return acc

    args = tuple(args)
    float(loop(args, 1))        # compile the 1-iter program
    float(loop(args, iters))    # compile the n-iter program
    t0 = time.perf_counter()
    float(loop(args, 1))
    t1 = time.perf_counter()
    float(loop(args, iters))
    t2 = time.perf_counter()
    return max((t2 - t1) - (t1 - t0), 1e-9) / (iters - 1)

"""Trustworthy on-device timing for jittable array functions.

Naive loops (call N times, ``block_until_ready``) lie on remote-attached
accelerators: async dispatch, transport-level result caching of identical
(executable, inputs) pairs, and transfer-queue backpressure all corrupt the
measurement — the round-2 flash-kernel "0.86x regression" and its later
"50x speedup" were BOTH artifacts of such timing. The fix: chain the N
executions *inside one compiled program* with a data dependency between
iterations, so the device must genuinely run every iteration, and subtract
a 1-iteration run to cancel dispatch/transfer overhead.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Sequence


def chained_device_time(
    fn: Callable[..., Any],
    args: Sequence[Any],
    iters: int = 16,
    repeats: int = 3,
    max_iters: int = 1024,
    return_valid: bool = False,
) -> float | tuple[float, bool]:
    """Seconds per call of ``fn(*args)`` measured on device.

    ``fn`` must be traceable and return an array (or pytree; the first leaf
    feeds the inter-iteration dependency). ``args[0]`` must be a float array:
    iteration i+1 perturbs it by ``1e-6 * out[0]`` so no two iterations are
    identical and the chain cannot be hoisted, cached, or reordered.

    Every *timed* call also gets a freshly perturbed ``args[0]`` — re-running
    an (executable, inputs) pair the warmup already executed can be answered
    from the transport's result cache without touching the device, which
    flattens both sides of a comparison to the noise floor. The per-iter
    estimate is the median over ``repeats`` independent (1-iter, n-iter)
    pairs.

    ``iters`` is a STARTING chain length, not a fixed one: if the n-iter run
    does not take at least 2x the 1-iter run (median over the round), the
    subtraction is dispatch noise and the chain grows 4x — up to
    ``max_iters`` — re-compiling the longer chain each time. Budget
    accordingly for very cheap ``fn``: worst case ~4 extra compiles and a
    ``max_iters``-long chain per call. If dominance is never reached even at
    ``max_iters``, the (noisy) max_iters estimate is returned rather than
    failing — callers that publish the number should pass
    ``return_valid=True`` to get ``(estimate, dominated)`` back and mark the
    row noisy when ``dominated`` is False, instead of printing dispatch
    noise as if it were kernel time.
    """
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnames="n")
    def loop(args, n):
        def body(carry, _):
            a, acc = carry
            out = fn(*a)
            first = jnp.ravel(jax.tree_util.tree_leaves(out)[0])[0]
            a = (a[0] + first.astype(a[0].dtype) * 1e-6,) + tuple(a[1:])
            return (a, acc + first.astype(jnp.float32)), None
        (a, acc), _ = jax.lax.scan(body, (tuple(args), jnp.float32(0)), None, length=n)
        return acc

    args = tuple(args)

    salt = [0]
    # step must survive rounding in args[0]'s dtype AT ITS MAGNITUDE: eps is
    # the spacing at 1.0, so an absolute step washes out for inputs of
    # magnitude >~ 8 (and bf16 eps ~8e-3 already needs it at magnitude 1) —
    # scale by max|args[0]| so at least the largest elements change
    scale = max(1.0, float(jnp.max(jnp.abs(args[0].astype(jnp.float32)))))
    step = 8 * float(jnp.finfo(args[0].dtype).eps) * scale

    def fresh() -> tuple:
        salt[0] += 1
        a0 = args[0] + jnp.asarray(salt[0] * step, args[0].dtype)
        jax.block_until_ready(a0)
        return (a0,) + args[1:]

    def measure(n: int) -> list[tuple[float, float]]:
        float(loop(args, 1))    # compile the 1-iter program
        float(loop(args, n))    # compile the n-iter program
        pairs = []
        for _ in range(repeats):
            a_short, a_long = fresh(), fresh()
            t0 = time.perf_counter()
            float(loop(a_short, 1))
            t1 = time.perf_counter()
            float(loop(a_long, n))
            t2 = time.perf_counter()
            pairs.append((t1 - t0, t2 - t1))
        return pairs

    # A fast kernel at small iters can vanish under dispatch overhead: the
    # n-iter run takes barely longer than the 1-iter run, the subtraction
    # lands at (or below) zero, and the caller would report a nonsense
    # "0.000 ms" (the r5 kernel-check small-shape artifact). Grow the chain
    # until the long run clearly dominates the short one, so the subtraction
    # carries signal, not noise.
    dominated = False
    while True:
        pairs = measure(iters)
        shorts = sorted(s for s, _ in pairs)
        longs = sorted(l for _, l in pairs)
        if longs[len(longs) // 2] >= 2.0 * shorts[len(shorts) // 2]:
            dominated = True
            break
        if iters >= max_iters:
            break
        iters = min(iters * 4, max_iters)
    estimates = sorted(
        max(l - s, 1e-9) / (iters - 1) for s, l in pairs
    )
    est = estimates[len(estimates) // 2]
    if return_valid:
        return est, dominated
    return est

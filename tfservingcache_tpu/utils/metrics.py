"""Prometheus metrics.

Metric-name parity with the reference where the concept survives
(pkg/tfservingproxy/tfservingproxy.go:25-32, pkg/cachemanager/cachemanager.go:24-43),
plus TPU-native additions (compile time, HBM residency) that have no
reference counterpart. Per-model labels are optional to bound cardinality
(reference cachemanager.go:251-258 "all_models" fallback).
"""

from __future__ import annotations

import asyncio

from prometheus_client import (
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

ALL_MODELS = "all_models"
# Cardinality-overflow bucket: once max_model_labels distinct name:version
# values exist, NEW tenants fold here so a 1000-tenant churn cannot explode
# every {model=...} family. Established labels keep resolving.
OTHER_MODELS = "__other__"
DEFAULT_MAX_MODEL_LABELS = 512


class Metrics:
    """One instance per process; injected (no promauto-style globals so tests
    can build many nodes in-process without collisions)."""

    def __init__(
        self,
        model_labels: bool = False,
        max_model_labels: int = DEFAULT_MAX_MODEL_LABELS,
    ) -> None:
        self.registry = CollectorRegistry()
        self.model_labels = model_labels
        self.max_model_labels = max(1, int(max_model_labels))
        # distinct labels handed out; set.add is GIL-atomic, so a racy
        # concurrent first-sighting can overshoot the cap by a label or two
        # — acceptable, the cap bounds growth, it is not a hard quota
        self._seen_model_labels: set[str] = set()
        r = self.registry
        # Exposed names match the reference exactly (prometheus_client appends
        # "_total" to counters, so the constructor names omit it):
        #   tfservingcache_proxy_requests_total / _proxy_failures_total
        #     (reference tfservingproxy.go:25-32) — and unlike the reference,
        #     the failure counter only counts failures (SURVEY.md §2 C3 bug);
        #   tfservingcache_cache_total / _cache_hits_total / _cache_misses_total
        #     (reference cachemanager.go:24-35).
        self.request_count = Counter(
            "tfservingcache_proxy_requests", "The total number of requests", ["protocol"], registry=r
        )
        self.request_failures = Counter(
            "tfservingcache_proxy_failures", "The total number of failed requests", ["protocol"], registry=r
        )
        # End-to-end client-experienced latency (no reference counterpart:
        # its two histograms time only the ensure step). route=local is a
        # request this node served itself; route=forwarded left via the ring
        # to a hash-owned peer — the pair splits "the model was slow" from
        # "the hop was slow" without a trace in hand.
        self.request_duration = Histogram(
            "tpusc_request_duration_seconds",
            "End-to-end request latency as the client experienced it "
            "(protocol=rest|grpc, verb=predict|classify|regress|generate|"
            "metadata|status|..., outcome=ok|error, route=local|forwarded)",
            ["protocol", "verb", "outcome", "route"],
            registry=r,
            buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                     0.5, 1, 2.5, 5, 10, 30, 60),
        )
        self.requests_in_flight = Gauge(
            "tpusc_requests_in_flight",
            "Requests currently being served (admitted, response not yet sent)",
            ["protocol"], registry=r,
        )
        self.batcher_queue_depth = Gauge(
            "tpusc_batcher_queue_depth",
            "Requests parked in a forming micro-batch, waiting for the "
            "device gate (kind = predict | generate)",
            ["kind"], registry=r,
        )
        self.cache_total = Counter(
            "tfservingcache_cache", "Cache lookups", ["model"], registry=r
        )
        self.cache_hits = Counter(
            "tfservingcache_cache_hits", "Cache hits", ["model"], registry=r
        )
        self.cache_misses = Counter(
            "tfservingcache_cache_misses", "Cache misses", ["model"], registry=r
        )
        self.cache_duration = Histogram(
            "tfservingcache_cache_duration_seconds",
            "Total time spent ensuring a model is servable",
            ["model"],
            registry=r,
            buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60),
        )
        self.cache_fetch_duration = Histogram(
            "tfservingcache_cache_fetch_duration_seconds",
            "Time spent fetching model artifacts from the provider",
            ["model"],
            registry=r,
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60),
        )
        # TPU-native additions (no reference counterpart)
        self.compile_duration = Histogram(
            "tpusc_compile_duration_seconds",
            "XLA compile+warmup time per model load",
            ["model"],
            registry=r,
            buckets=(0.05, 0.1, 0.25, 0.5, 1, 2, 5, 10, 30, 60, 120),
        )
        # labeled by chip group: one host may run several group runtimes,
        # each with its own HBM budget (ring members = chip groups)
        self.hbm_bytes_in_use = Gauge(
            "tpusc_hbm_bytes_in_use", "Bytes of HBM pinned by resident models",
            ["group"], registry=r,
        )
        # High-water twin of the gauge above: a scrape-interval peak instead
        # of an instant sample, so a between-scrapes residency spike is
        # visible. Backed by the flight recorder's watermarks — reading
        # GET /monitoring/engine resets the marks (reset-on-scrape; the
        # gauge then re-arms at the next update). See OBSERVABILITY.md.
        self.hbm_bytes_peak = Gauge(
            "tpusc_hbm_bytes_peak",
            "High-water HBM bytes pinned by resident models since the last "
            "/monitoring/engine scrape",
            ["group"], registry=r,
        )
        self.models_resident = Gauge(
            "tpusc_models_resident", "Models currently AVAILABLE in the runtime",
            ["group"], registry=r,
        )
        self.disk_bytes_in_use = Gauge(
            "tpusc_disk_cache_bytes_in_use", "Bytes used by the disk artifact cache", registry=r
        )
        self.evictions = Counter(
            "tpusc_evictions_total", "Evictions", ["tier"], registry=r
        )
        # multi-tier residency observability (cache/host_tier.py): which
        # tier satisfied each ensure_servable — hbm = already warm, host =
        # packed-chunk promotion (no fetch, no decode), disk = artifact
        # re-read + full load, store = provider fetch. The mix is the
        # direct answer to "what are my reloads costing".
        self.reload_source = Counter(
            "tpusc_reload_source",
            "ensure_servable resolutions by serving tier "
            "(tier = hbm | host | disk | store | peer)",
            ["tier"], registry=r,
        )
        self.host_tier_bytes = Gauge(
            "tpusc_host_tier_bytes",
            "Host DRAM held by the warm tier's packed parameter chunks",
            registry=r,
        )
        self.host_tier_bytes_peak = Gauge(
            "tpusc_host_tier_bytes_peak",
            "High-water warm-tier DRAM bytes since the last "
            "/monitoring/engine scrape (reset-on-scrape)",
            registry=r,
        )
        # continuous batching observability: how often requests coalesce and
        # how many ride each device call (kind = predict | generate)
        self.coalesced_batches = Counter(
            "tpusc_coalesced_batches", "Multi-request device calls",
            ["kind"], registry=r,
        )
        self.coalesced_requests = Counter(
            "tpusc_coalesced_requests", "Requests served via a coalesced call",
            ["kind"], registry=r,
        )
        # iteration-level continuous batching (runtime/batcher.py
        # ContinuousGenerateEngine). The engine label makes coalesce vs
        # continuous comparable on the SAME metric: the coalescer records
        # its head-of-line gate stall and post-hoc padded-step waste under
        # engine="coalesce".
        # model label gated on the metrics.model_labels flag (same
        # cardinality rule as the cache counters): off = one "all_models"
        # series summed across models, on = per-model lane occupancy, so a
        # saturated model's lanes are attributable instead of hiding inside
        # a global sum.
        self.gen_slots_active = Gauge(
            "tpusc_gen_slots_active",
            "Decode slots currently occupied by in-flight generate requests "
            "(per model when model_labels is on, else one all_models series "
            "summed across models; capacity is serving.generate_slots per "
            "model)",
            ["model"], registry=r,
        )
        self.gen_wasted_steps = Counter(
            "tpusc_gen_wasted_steps",
            "Decode steps computed for a row AFTER its request already "
            "finished (EOS or its own max_new_tokens): batch-drain padding "
            "under coalesce, chunk overshoot (< chunk size) under continuous",
            ["engine"], registry=r,
        )
        self.gen_admission_wait = Histogram(
            "tpusc_gen_admission_wait_seconds",
            "Time a generate request waited before decoding began on its "
            "behalf: slot-free wait under continuous, in-flight gate stall "
            "under coalesce",
            ["engine"], registry=r,
            buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25,
                     .5, 1, 2.5, 5, 10),
        )
        # gen_admission_wait only observes AT admission: a request stuck
        # behind page exhaustion is invisible until it finally admits. This
        # gauge is the live view — the age of the oldest still-queued row,
        # updated at every chunk boundary (0 when the queue is empty).
        self.gen_oldest_queued_age = Gauge(
            "tpusc_gen_oldest_queued_age_seconds",
            "Age of the oldest generate request still waiting for admission "
            "(slot or KV-page starvation shows here BEFORE the request "
            "admits; 0 = queue empty)",
            ["engine"], registry=r,
        )
        # Transparent crash recovery (runtime/batcher.py triage/_respawn,
        # serving.generate_recovery): rows requeued into a replacement
        # scheduler after an engine-thread death instead of failing.
        # reason=mid_decode rows re-prefill prompt + emitted tokens; queued
        # rows only changed queues. Zero in a healthy fleet — a nonzero
        # rate is a crash rate wearing its recovery hat.
        self.requests_recovered = Counter(
            "tpusc_requests_recovered",
            "Generate rows transparently requeued after an engine-thread "
            "crash (reason=mid_decode|queued)",
            ["reason"], registry=r,
        )
        # Scenario-lab chaos drills (lab/faults.py, armed only via
        # observability.lab_faults): one increment per fault firing. Always
        # zero unless an operator armed the injector; alert on nonzero in
        # any environment that should never run drills.
        self.fault_injected = Counter(
            "tpusc_fault_injected",
            "Scenario-lab fault injections fired (kind=kill_engine|"
            "freeze_scheduler|stall_store|corrupt_peer_chunk|drop_peer)",
            ["kind"], registry=r,
        )
        # Per-request phase attribution (runtime/batcher.py engines): where
        # a generate request's wall time went — admission queue, prompt
        # prefill, decode steps, or response assembly. The same clocks land
        # as attrs on the request's trace root, so /monitoring/traces
        # answers "where did the time go" without cross-referencing.
        # The per-priority `class` label rides the model_labels cardinality
        # gate (ISSUE 20 satellite: 3 classes x 4 phases x 2 engines is
        # cheap, but the flag keeps default deployments at the old arity);
        # callers go through observe_phase so neither arity leaks out.
        phase_labels = (
            ["phase", "engine", "class"] if model_labels
            else ["phase", "engine"]
        )
        self.request_phase = Histogram(
            "tpusc_request_phase_seconds",
            "Per-request latency attribution by phase "
            "(phase=queue|prefill|decode|respond, "
            "engine=continuous|coalesce; class=high|normal|low "
            "when model_labels is on)",
            phase_labels, registry=r,
            buckets=(.0005, .001, .0025, .005, .01, .025, .05, .1, .25,
                     .5, 1, 2.5, 5, 10, 30),
        )
        # paged KV arena (serving.kv_page_tokens > 0): occupancy of the
        # shared page pool and the per-retirement waste that page granularity
        # + unconsumed max_new headroom cost — the observability the arena
        # sizing math in PERF.md "Paged KV" reads from.
        self.gen_kv_pages_used = Gauge(
            "tpusc_gen_kv_pages_used",
            "KV arena pages currently reserved by in-flight continuous "
            "generate rows (summed across models)",
            registry=r,
        )
        self.gen_kv_pages_total = Gauge(
            "tpusc_gen_kv_pages_total",
            "Usable KV arena pages (excluding the trash page), summed "
            "across models with live paged slot states",
            registry=r,
        )
        self.gen_kv_pages_used_peak = Gauge(
            "tpusc_gen_kv_pages_used_peak",
            "High-water KV arena pages reserved since the last "
            "/monitoring/engine scrape (reset-on-scrape)",
            registry=r,
        )
        self.gen_kv_pages_shared = Gauge(
            "tpusc_gen_kv_pages_shared",
            "KV arena pages currently referenced by MORE than one owner "
            "(shared-prefix pages mapped read-only into multiple lanes' "
            "block tables and/or held by the radix prefix index); each "
            "counted once — gen_kv_pages_used minus this is the private "
            "page population",
            registry=r,
        )
        self.gen_prefix_hits = Counter(
            "tpusc_gen_prefix_hits",
            "Continuous-engine admissions that reused prompt-prefix KV: "
            "kind=exact skipped prefill entirely (radix index full match, "
            "first token sampled from cached logits), kind=shared paid "
            "only a suffix prefill (radix partial match or dense "
            "prefix-cache reuse)",
            ["engine", "kind"], registry=r,
        )
        # SLO-aware engine (chunked prefill + priority classes + streaming):
        # per-class preemption pressure, how many partial-prefill dispatches
        # the interleaver issued, and streamed frames by protocol surface —
        # the attribution trail for the slo_engine bench arms.
        self.gen_preemptions = Counter(
            "tpusc_gen_preemptions",
            "Decoding lanes preempted by a higher-priority admission "
            "(KV parked through the conversation codec, lane requeued, "
            "resumed O(new tokens) when pages free), labeled by the "
            "priority class of the VICTIM lane",
            ["class"], registry=r,
        )
        self.gen_prefill_chunks = Counter(
            "tpusc_gen_prefill_chunks",
            "Partial-prefill dispatches issued by the continuous engine's "
            "chunked-prefill interleaver (serving.prefill_chunk_tokens > 0); "
            "one increment per chunk, so chunks/admission gauges how much "
            "long-prompt prefill was broken up",
            registry=r,
        )
        self.gen_stream_frames = Counter(
            "tpusc_gen_stream_frames",
            "Token frames written to streaming generate clients "
            "(protocol = sse | grpc)",
            ["protocol"], registry=r,
        )
        self.gen_kv_arena_bytes = Gauge(
            "tpusc_gen_kv_arena_bytes",
            "Device bytes allocated to the paged KV arena (pages plus, "
            "for dtype=int8, the f32 dequant scale buffers), labeled by "
            "arena element type (serving.kv_arena_dtype; the model dtype "
            "when unset) — capacity-vs-budget evidence for the int8 arena",
            ["dtype"], registry=r,
        )
        # conversation KV lifecycle (cache/conversation_kv.py): parked
        # decode state by residency tier, and how resume lookups resolve —
        # hit = served from host DRAM, spilled = read back from the disk
        # level (still O(new tokens) prefill, just a slower import), miss =
        # cold full prefill.
        self.kv_parked_bytes = Gauge(
            "tpusc_kv_parked_bytes",
            "Bytes of parked conversation KV state by residency tier "
            "(tier = host | disk)",
            ["tier"], registry=r,
        )
        self.kv_parked_conversations = Gauge(
            "tpusc_kv_parked_conversations",
            "Conversations with parked KV state across the host and disk "
            "levels of the conversation tier",
            registry=r,
        )
        self.kv_resume = Counter(
            "tpusc_kv_resume",
            "conversation_id resume lookups at continuous-engine admission "
            "(outcome = hit | spilled | miss)",
            ["outcome"], registry=r,
        )
        self.gen_kv_page_waste = Histogram(
            "tpusc_gen_kv_page_waste_tokens",
            "Per retired row: reserved page capacity minus tokens that "
            "actually occupied it (prompt + emitted) — internal "
            "fragmentation of fixed pages plus unconsumed max_new headroom",
            registry=r,
            buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
        )
        self.assignment_warms = Counter(
            "tpusc_assignment_warms_total",
            "Models pre-loaded by the ring-assignment warmer",
            registry=r,
        )
        # scrape_and_merge degrades gracefully when a sidecar exporter is
        # down — but "gracefully" must not mean "silently": this counts the
        # targets each merge dropped (unreachable or unparseable), so an
        # exporter that died weeks ago is an alertable signal, not a gap
        # someone notices during an incident.
        self.scrape_errors = Counter(
            "tpusc_scrape_errors",
            "Sidecar metrics targets dropped from a /metrics merge "
            "(unreachable, non-200, or unparseable)",
            registry=r,
        )
        self.prefix_cache_hits = Counter(
            "tpusc_prefix_cache_hits_total",
            "generate requests that reused a cached prompt-prefix KV",
            registry=r,
        )
        self.prefix_cache_misses = Counter(
            "tpusc_prefix_cache_misses_total",
            "generate requests that paid full prefill (prefix cache on)",
            registry=r,
        )
        self.prefix_cache_bytes = Gauge(
            "tpusc_prefix_cache_bytes",
            "Device bytes held by cached prompt-prefix KV entries",
            registry=r,
        )
        self.cold_stage_seconds = Histogram(
            "tpusc_cold_stage_seconds",
            "Per-stage cold-load time (provider_fetch/artifact_read/"
            "device_transfer/device_dequant/host_dequant/compile_warmup/"
            "transfer_sync; dequant stages appear for quantized artifacts "
            "only, so encodings stay separable): "
            "the in-production answer to 'where do my cold seconds go' and "
            "to the int8-vs-bf16 crossover (compare device_transfer + "
            "device_dequant across artifact encodings on YOUR link)",
            ["stage"], registry=r,
            buckets=(.005, .02, .05, .1, .25, .5, 1, 2, 5, 10, 30),
        )
        self.cold_overlap_ratio = Histogram(
            "tpusc_cold_overlap_ratio",
            "Σ(per-stage seconds)/wall seconds per runtime load: ~1.0 means "
            "the stages ran strictly back-to-back (serialized path), >1 "
            "means the pipelined cold load overlapped them (AOT compile and "
            "per-leaf dequant running during the transfer) — the higher, "
            "the more of the compile the transfer hid",
            registry=r,
            buckets=(0.8, 0.95, 1.0, 1.1, 1.2, 1.35, 1.5, 1.75, 2.0, 2.5, 3.0, 4.0),
        )
        self.group_reforms = Counter(
            "tpusc_group_reform_events_total",
            "Cross-host group failure-containment events",
            ["group", "event"], registry=r,  # event: torn_down | reformed
        )
        self.group_healthy = Gauge(
            "tpusc_group_healthy",
            "1 while the cross-host group serves; 0 while torn down/re-forming",
            ["group"], registry=r,
        )
        # fleet status plane (cluster/status.py): this node's view of its
        # peers. health is the router's soft route-around signal (error
        # EWMA x latency factor x staleness decay); age is how old the
        # peer's last NodeStatus is; replicas inverts the fleet residency
        # map ("how many nodes hold model M at tier T"), the input to
        # ROADMAP item 4's replication decisions. Peer label cardinality is
        # bounded by ring membership (departed peers are pruned); model
        # cardinality by cluster.status_max_models per peer.
        self.peer_health_score = Gauge(
            "tpusc_peer_health_score",
            "Composite per-peer health in [0,1] as THIS node scores it: "
            "forward-error EWMA x latency factor x status-staleness decay "
            "(peers below cluster.health_threshold are deprioritized in "
            "p2c replica ordering, never hard-dropped)",
            ["peer"], registry=r,
        )
        self.peer_status_age = Gauge(
            "tpusc_peer_status_age_seconds",
            "Seconds since this peer's last NodeStatus was received "
            "(piggybacked on a routed hop or polled)",
            ["peer"], registry=r,
        )
        self.fleet_model_replicas = Gauge(
            "tpusc_fleet_model_replicas",
            "Nodes currently advertising this model at this residency tier "
            "(tier = hbm | host | disk), from the fleet status exchange",
            ["model", "tier"], registry=r,
        )
        # peer param distribution (cache/providers/peer.py): cold misses
        # sourced from a warm peer's host tier instead of the store
        self.peer_fetch_bytes = Counter(
            "tpusc_peer_fetch_bytes",
            "Packed parameter bytes streamed FROM peers on cold misses "
            "(outcome = ok | error | not_found; error/not_found count the "
            "bytes received before the stream gave up and fell back to "
            "the store)",
            ["outcome"], registry=r,
        )
        # load-adaptive replication (cluster/replication.py): the
        # controller's desired per-model ring replica count N
        self.model_replicas_target = Gauge(
            "tpusc_model_replicas_target",
            "Per-model ring replica count N the replica controller "
            "currently targets (grows with in-flight load toward "
            "cluster.max_replicas_per_model, decays to the "
            "proxy.replicas_per_model floor with hysteresis)",
            ["model"], registry=r,
        )
        self.spec_draft_autodisabled = Counter(
            "tpusc_spec_draft_autodisabled_total",
            "Draft models auto-disabled after sustained low acceptance",
            registry=r,
        )
        # model label gated on metrics.model_labels (off = one all_models
        # series, last-write-wins across pairs exactly as before; on = the
        # TARGET model's acceptance is attributable per tenant)
        self.spec_tokens_per_round = Gauge(
            "tpusc_spec_tokens_per_round",
            "Most recent speculative acceptance (emitted tokens per verify "
            "round; spec_tokens+1 = every proposal accepted; labeled by "
            "target model when model_labels is on, else one all_models "
            "series)",
            ["model"], registry=r,
        )
        # cumulative acceptance by engine (engine = solo | continuous):
        # rate(accepted)/rate(rounds) is the fleet acceptance trend the
        # last-write-wins gauge above cannot provide
        self.spec_accepted_tokens = Counter(
            "tpusc_spec_accepted_tokens",
            "Tokens emitted by speculative verify rounds (accepted draft "
            "prefix + the target's own correction token)",
            ["engine"], registry=r,
        )
        self.spec_rounds = Counter(
            "tpusc_spec_rounds",
            "Speculative draft/verify rounds executed (per active lane "
            "under the continuous engine)",
            ["engine"], registry=r,
        )
        # per-tenant cost attribution (utils/accounting.py TenantLedger):
        # the ledger's monotonic integrals mirrored at scrape time via
        # LEDGER.publish() — series appear only when metrics.model_labels
        # is on (per-tenant cost without a model label is meaningless).
        # TPUSC004: family construction stays in this module.
        self.tenant_tokens = Counter(
            "tpusc_tenant_tokens",
            "Tokens attributed to this tenant (direction = in, prompt "
            "tokens admitted | out, tokens emitted)",
            ["model", "direction"], registry=r,
        )
        self.tenant_step_seconds = Counter(
            "tpusc_tenant_step_seconds",
            "Engine wall seconds spent on this tenant's rows "
            "(phase = prefill | decode); each scheduler dispatch is "
            "single-model, so step time lands wholly on its tenant",
            ["model", "phase"], registry=r,
        )
        self.tenant_kv_page_seconds = Counter(
            "tpusc_tenant_kv_page_seconds",
            "Integral of DISTINCT KV arena pages held by this tenant over "
            "time (a shared-prefix page counts once, per page_stats())",
            ["model"], registry=r,
        )
        self.tenant_byte_seconds = Counter(
            "tpusc_tenant_byte_seconds",
            "Integral of this tenant's residency bytes over time by tier "
            "(tier = hbm | host | disk)",
            ["model", "tier"], registry=r,
        )
        self.tenant_cold_load_seconds = Counter(
            "tpusc_tenant_cold_load_seconds",
            "Wall seconds of ensure_servable resolutions for this tenant "
            "by serving tier (tier = hbm | host | disk | peer | store)",
            ["model", "tier"], registry=r,
        )
        self.tenant_peer_bytes_served = Counter(
            "tpusc_tenant_peer_bytes_served",
            "Packed parameter bytes this node streamed TO peers on the "
            "tenant's behalf (work done for others, attributed not lost)",
            ["model"], registry=r,
        )
        self.tenant_dominant_share = Gauge(
            "tpusc_tenant_dominant_share",
            "Max over dimensions of this tenant's share of the node total "
            "(DRF-style dominant share in [0,1]; the noisy-neighbor signal)",
            ["model"], registry=r,
        )

    def observe_phase(
        self, phase: str, engine: str, cls: str, v: float
    ) -> None:
        """Observe one request-phase sample, routing the priority class to
        the extra label only when ``model_labels`` enabled it at
        construction — the one place that knows the histogram's arity."""
        if self.model_labels:
            self.request_phase.labels(phase, engine, cls or "normal").observe(v)
        else:
            self.request_phase.labels(phase, engine).observe(v)

    def model_label(self, name: str, version: int | str) -> str:
        if not self.model_labels:
            return ALL_MODELS
        label = f"{name}:{version}"
        seen = self._seen_model_labels
        if label in seen:
            return label
        if len(seen) >= self.max_model_labels:
            return OTHER_MODELS
        seen.add(label)
        return label

    def render(self) -> bytes:
        """Text exposition of this registry (served on the metrics path;
        reference merges TF Serving's scrape here too — metrics.go:16-53 —
        which disappears now that serving is in-process)."""
        return generate_latest(self.registry)


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # exposition-format HELP escaping: backslash and newline only — the
    # parser unescaped these, so re-emitting raw would corrupt the merge
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _emit_families(families, skip: set[str]) -> tuple[list[str], set[str]]:
    """Re-emit parsed metric families as exposition text, skipping family
    names already emitted (cross-exporter duplicates like python_gc_* would
    otherwise make Prometheus reject the whole scrape)."""
    out: list[str] = []
    emitted: set[str] = set()
    for fam in families:
        if fam.name in skip:
            continue
        emitted.add(fam.name)
        out.append(f"# HELP {fam.name} {_escape_help(fam.documentation)}")
        out.append(f"# TYPE {fam.name} {fam.type}")
        for s in fam.samples:
            labels = ",".join(
                f'{k}="{_escape_label(str(v))}"' for k, v in sorted(s.labels.items())
            )
            label_part = f"{{{labels}}}" if labels else ""
            out.append(f"{s.name}{label_part} {s.value}")
    return out, emitted


def _merge_summed(texts: list[str], on_error) -> bytes:
    """Series-level merge: one HELP/TYPE per family, counter samples with
    identical label sets SUMMED across sources, everything else first-source
    -wins (sources are ordered own-first). This is the fleet-aggregation
    merge mode: peers exporting per-tenant counter series (model_labels on)
    combine into fleet totals instead of the first peer shadowing the rest."""
    from prometheus_client.parser import text_string_to_metric_families

    fams: dict[str, dict] = {}
    for text in texts:
        try:
            parsed = list(text_string_to_metric_families(text))
        except ValueError as e:
            on_error(e)
            continue
        for fam in parsed:
            ent = fams.get(fam.name)
            if ent is None:
                ent = fams[fam.name] = {
                    "doc": fam.documentation,
                    "type": fam.type,
                    "samples": {},
                }
            for s in fam.samples:
                key = (s.name, tuple(sorted(s.labels.items())))
                cur = ent["samples"].get(key)
                if cur is None:
                    ent["samples"][key] = s.value
                elif ent["type"] == "counter" and not s.name.endswith("_created"):
                    ent["samples"][key] = cur + s.value
                # non-counter duplicates (and _created stamps): first wins
    out: list[str] = []
    for name, ent in fams.items():
        # the parser strips the counter "_total" suffix from the family
        # name; re-emit it (generate_latest's plain-text convention) so a
        # re-parse reassociates the _total samples with their family
        # instead of orphaning them into untyped duplicates
        ename = name
        if ent["type"] == "counter" and all(
            sname.endswith(("_total", "_created"))
            for sname, _ in ent["samples"]
        ):
            ename = name + "_total"
        out.append(f"# HELP {ename} {_escape_help(ent['doc'])}")
        out.append(f"# TYPE {ename} {ent['type']}")
        for (sname, litems), value in ent["samples"].items():
            labels = ",".join(
                f'{k}="{_escape_label(str(v))}"' for k, v in litems
            )
            label_part = f"{{{labels}}}" if labels else ""
            out.append(f"{sname}{label_part} {value}")
    return ("\n".join(out) + "\n").encode()


async def scrape_and_merge(
    own: bytes,
    targets: list[str],
    timeout_s: float = 2.0,
    metrics: "Metrics | None" = None,
    sum_counters: bool = False,
) -> bytes:
    """Merge externally-scraped text-format metrics into one exposition.

    Reference equivalent: MetricsHandler's live scrape of TF Serving's
    metrics endpoint merged with the process's own registry
    (pkg/taskhandler/metrics.go:16-53). Serving moved in-process, but the
    same trick folds sidecar exporters (e.g. libtpu / node exporters) into
    this node's single /metrics endpoint. Targets are fetched concurrently
    (a down sidecar costs one timeout, not one per target), each body is
    parsed and re-emitted with cross-exporter duplicate families dropped
    (own registry wins), and unreachable/corrupt targets are skipped —
    counted in ``tpusc_scrape_errors_total`` and logged at warning, so a
    degraded merge is visible, not silent.

    ``sum_counters`` (config ``metrics.scrape_sum_counters``) switches to a
    series-level merge: counter samples with identical label sets are
    SUMMED across own+targets (per-tenant fleet aggregation), other types
    stay first-source-wins. Default off: the family-level dedup above is
    byte-stable and cheaper."""
    if not targets:
        return own
    import logging

    import aiohttp
    from prometheus_client.parser import text_string_to_metric_families

    async def fetch(session: aiohttp.ClientSession, url: str) -> str | None:
        try:
            async with session.get(url) as resp:
                if resp.status != 200:
                    raise ValueError(f"HTTP {resp.status}")
                return await resp.text()
        except Exception as e:  # noqa: BLE001 — degraded scrape is non-fatal
            logging.getLogger("tpusc.metrics").warning(
                "metrics scrape of %s failed: %s", url, e
            )
            if metrics is not None:
                metrics.scrape_errors.inc()
            return None

    async with aiohttp.ClientSession(
        timeout=aiohttp.ClientTimeout(total=timeout_s)
    ) as session:
        bodies = await asyncio.gather(*(fetch(session, url) for url in targets))

    if sum_counters:
        def _on_parse_error(e: Exception) -> None:
            logging.getLogger("tpusc.metrics").warning(
                "metrics merge source unparseable: %s", e
            )
            if metrics is not None:
                metrics.scrape_errors.inc()

        return _merge_summed(
            [own.decode()] + [b for b in bodies if b is not None],
            _on_parse_error,
        )

    seen = {f.name for f in text_string_to_metric_families(own.decode())}
    parts = [own.rstrip(b"\n")]
    for url, body in zip(targets, bodies):
        if body is None:
            continue
        try:
            lines, emitted = _emit_families(text_string_to_metric_families(body), seen)
        except ValueError as e:
            logging.getLogger("tpusc.metrics").warning(
                "metrics scrape of %s unparseable: %s", url, e
            )
            if metrics is not None:
                metrics.scrape_errors.inc()
            continue
        seen |= emitted
        if lines:
            parts.append("\n".join(lines).encode())
    return b"\n".join(parts) + b"\n"

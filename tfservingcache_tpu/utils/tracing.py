"""Per-request, per-stage tracing.

The reference has no tracing at all (SURVEY.md §5: "no OpenTelemetry/pprof
anywhere"; latency visibility is two Prometheus histograms) — this is
greenfield. Design: a process-wide ring buffer of completed request traces,
each a tree of spans (route -> ensure -> fetch/compile -> infer), ambient
via contextvars so call sites never thread a handle. Cross-thread hops
(the serving pool running JAX work) join the request's trace because
LocalServingBackend runs executor jobs under ``contextvars.copy_context``.

Overhead when idle: one contextvar lookup + two ``monotonic()`` calls per
span — cheap enough to leave always-on; the buffer bounds memory.
"""

from __future__ import annotations

import contextvars
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tpusc_current_span", default=None
)


@dataclass
class Span:
    name: str
    attrs: dict[str, Any]
    start_s: float                      # wall-clock epoch (for display)
    t0: float = 0.0                     # monotonic (for duration)
    duration_s: float = 0.0
    error: str = ""
    children: list["Span"] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.attrs:
            d["attrs"] = self.attrs
        if self.error:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d


class Tracer:
    def __init__(self, capacity: int = 256) -> None:
        self.capacity = capacity
        self._lock = threading.Lock()
        self._traces: list[Span] = []

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        """Open a span under the ambient parent; a span with no parent is a
        root trace and lands in the ring buffer on completion."""
        sp = Span(name=name, attrs=attrs, start_s=time.time(), t0=time.monotonic())
        parent = _current_span.get()
        token = _current_span.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp.duration_s = time.monotonic() - sp.t0
            _current_span.reset(token)
            if parent is not None:
                # list.append is atomic under the GIL; concurrent child spans
                # of one request (gather'd ensures) interleave safely
                parent.children.append(sp)
            else:
                with self._lock:
                    self._traces.append(sp)
                    if len(self._traces) > self.capacity:
                        del self._traces[: len(self._traces) - self.capacity]

    def annotate(self, **attrs: Any) -> None:
        """Attach attributes to the innermost open span, if any."""
        sp = _current_span.get()
        if sp is not None:
            sp.attrs.update(attrs)

    def attach(self, parent: Span, name: str, duration_s: float,
               start_s: float | None = None, **attrs: Any) -> Span:
        """Attach an externally-timed, already-completed span as a child of
        ``parent``. For work that ran on an executor thread whose ambient
        context predates ``parent`` (the pipelined cold load's AOT compile),
        ``span()`` can't parent it — and for overlapped work Σ(children) may
        legitimately exceed the parent's wall time, which is exactly what
        ``cold_overlap_ratio`` measures."""
        sp = Span(name=name, attrs=attrs,
                  start_s=time.time() if start_s is None else start_s,
                  duration_s=duration_s)
        parent.children.append(sp)
        return sp

    def recent(self, n: int = 50) -> list[dict[str, Any]]:
        with self._lock:
            return [s.to_dict() for s in self._traces[-n:]][::-1]

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


# Process-wide default. Diagnostics are write-mostly and bounded, so a global
# (unlike Metrics, which stays injected for registry isolation) keeps every
# call site plumbing-free; tests snapshot/clear it.
TRACER = Tracer()

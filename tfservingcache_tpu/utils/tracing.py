"""Per-request, per-stage distributed tracing.

The reference has no tracing at all (SURVEY.md §5: "no OpenTelemetry/pprof
anywhere"; latency visibility is two Prometheus histograms) — this is
greenfield. Design: a process-wide ring buffer of completed request traces,
each a tree of spans (route -> ensure -> fetch/compile -> infer), ambient
via contextvars so call sites never thread a handle. Cross-thread hops
(the serving pool running JAX work) join the request's trace because
LocalServingBackend runs executor jobs under ``contextvars.copy_context``.

Distributed layer: every span carries a 64-bit span id and inherits its
root's 128-bit trace id. A routed hop propagates context with a W3C-style
``traceparent`` (HTTP header / gRPC metadata); the serving peer adopts the
trace id, and on completion ships its finished subtree back inline
(compressed JSON on a response header / gRPC trailer) so the router can
graft it under its own ``route`` span — one request, one stitched trace,
even when node A routed it to node B.

Slow-trace retention: chatty fast requests wrap the main ring in seconds,
which is exactly when the one 4-second outlier you need has been evicted.
Roots slower than ``slow_threshold_s`` are retained in a separate bounded
buffer and surface via ``query(min_duration_s=...)``.

Overhead when idle: one contextvar lookup, two ``monotonic()`` calls, and
one 64-bit random id per span — cheap enough to leave always-on (guarded by
tests/test_observability.py); the buffers bound memory.
"""

from __future__ import annotations

import base64
import contextvars
import json
import random
import re
import threading
import time
import zlib

from tfservingcache_tpu.utils.lockcheck import lockchecked
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Iterator

_current_span: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "tpusc_current_span", default=None
)
# (trace_id, parent_span_id) extracted from an inbound traceparent: the next
# root span opened in this context adopts it instead of minting a new trace
_remote_parent: contextvars.ContextVar[tuple[str, str] | None] = contextvars.ContextVar(
    "tpusc_remote_parent", default=None
)

# W3C trace-context: version "00", 16-byte trace id, 8-byte parent span id,
# flags. Ids of all zeros are invalid per the spec.
_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-(?P<trace>[0-9a-f]{32})-(?P<span>[0-9a-f]{16})-[0-9a-f]{2}$"
)

# SystemRandom would be overkill (ids are diagnostics, not secrets) and
# os.urandom costs a syscall per span; Random is a few hundred ns.
_rand = random.Random()


def _new_span_id() -> str:
    return f"{_rand.getrandbits(64):016x}"


def _new_trace_id() -> str:
    return f"{_rand.getrandbits(128):032x}"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """-> (trace_id, parent_span_id) or None for absent/malformed headers
    (a garbage header must never fail the request it arrived on)."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    if m is None:
        return None
    trace, span = m.group("trace"), m.group("span")
    if trace == "0" * 32 or span == "0" * 16:
        return None
    return trace, span


def format_traceparent(sp: "Span | None" = None) -> str | None:
    """traceparent for the given (default: ambient) span, or None when no
    span is open — callers simply omit the header then."""
    sp = sp if sp is not None else _current_span.get()
    if sp is None or not sp.trace_id:
        return None
    return f"00-{sp.trace_id}-{sp.span_id}-01"


@contextmanager
def remote_parent(ctx: tuple[str, str] | None) -> Iterator[None]:
    """While active, the next ROOT span adopts ``ctx`` = (trace_id,
    parent_span_id) — the protocol servers wrap their request span in this
    after extracting an inbound traceparent. A None ctx is a no-op so call
    sites don't need to branch."""
    if ctx is None:
        yield
        return
    token = _remote_parent.set(ctx)
    try:
        yield
    finally:
        _remote_parent.reset(token)


def current_ids() -> tuple[str, str] | None:
    """(trace_id, span_id) of the innermost open span, or None outside any
    request context. The JSON log formatter joins log lines to traces here."""
    sp = _current_span.get()
    if sp is None:
        return None
    return sp.trace_id, sp.span_id


@dataclass
class Span:
    name: str
    attrs: dict[str, Any]
    start_s: float                      # wall-clock epoch (for display)
    t0: float = 0.0                     # monotonic (for duration)
    duration_s: float = 0.0
    error: str = ""
    children: list["Span"] = field(default_factory=list)
    trace_id: str = ""                  # 128-bit hex; shared by the whole tree
    span_id: str = ""                   # 64-bit hex; unique per span
    parent_id: str = ""                 # remote parent span id (adopted roots)
    remote: bool = False                # subtree grafted back from a peer
    root: "Span | None" = field(default=None, repr=False, compare=False)

    def to_dict(self, _root: bool = True) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "start_s": round(self.start_s, 6),
            "duration_s": round(self.duration_s, 6),
        }
        if self.span_id:
            d["span_id"] = self.span_id
        if self.trace_id and (_root or self.remote):
            # children inherit the root's trace id; repeating it per span
            # would bloat the wire subtree for no information. Remote grafts
            # keep theirs so a stitched trace shows the ids matching up.
            d["trace_id"] = self.trace_id
        if self.parent_id:
            d["parent_id"] = self.parent_id
        if self.remote:
            d["remote"] = True
        if self.attrs:
            d["attrs"] = self.attrs
        if self.error:
            d["error"] = self.error
        if self.children:
            d["children"] = [c.to_dict(_root=False) for c in self.children]
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Span":
        sp = cls(
            name=str(d.get("name", "?")),
            attrs=dict(d.get("attrs") or {}),
            start_s=float(d.get("start_s", 0.0)),
            duration_s=float(d.get("duration_s", 0.0)),
            error=str(d.get("error", "")),
            trace_id=str(d.get("trace_id", "")),
            span_id=str(d.get("span_id", "")),
            parent_id=str(d.get("parent_id", "")),
            remote=bool(d.get("remote", False)),
        )
        sp.children = [cls.from_dict(c) for c in d.get("children") or []]
        return sp


# Wire form of a completed subtree: compact JSON -> zlib -> urlsafe base64,
# so it fits an HTTP response header or an ASCII gRPC trailer value. Beyond
# the size cap the tree is degraded (attrs dropped, then a root-only stub)
# rather than blowing the peer's header-size limit.
WIRE_TRACE_LIMIT = 6 << 10


def serialize_span(sp: Span, limit: int = WIRE_TRACE_LIMIT) -> str:
    def pack(d: dict[str, Any]) -> str:
        raw = json.dumps(d, separators=(",", ":"), default=str).encode()
        return base64.urlsafe_b64encode(zlib.compress(raw, 6)).decode()

    blob = pack(sp.to_dict())
    if len(blob) <= limit:
        return blob

    def strip_attrs(d: dict[str, Any]) -> dict[str, Any]:
        d = {k: v for k, v in d.items() if k != "attrs"}
        if "children" in d:
            d["children"] = [strip_attrs(c) for c in d["children"]]
        return d

    blob = pack(strip_attrs(sp.to_dict()))
    if len(blob) <= limit:
        return blob
    stub = sp.to_dict()
    stub.pop("children", None)
    stub.setdefault("attrs", {})["truncated"] = True
    return pack(stub)


def deserialize_span(payload: str | bytes) -> Span | None:
    """None on any malformed payload: a peer's corrupt trace trailer must
    cost the stitched subtree, never the response."""
    try:
        if isinstance(payload, str):
            payload = payload.encode()
        raw = zlib.decompress(base64.urlsafe_b64decode(payload))
        d = json.loads(raw)
        if not isinstance(d, dict):
            return None
        return Span.from_dict(d)
    except Exception:  # noqa: BLE001 — by contract: garbage in, None out
        return None


@lockchecked
class Tracer:
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {"_traces": "_lock", "_slow": "_lock"}

    def __init__(
        self,
        capacity: int = 256,
        slow_threshold_s: float = 1.0,
        slow_capacity: int = 64,
    ) -> None:
        self.capacity = capacity
        # tail sampling: roots slower than this survive in _slow even after
        # the main ring wraps; 0 disables the tier
        self.slow_threshold_s = slow_threshold_s
        self.slow_capacity = slow_capacity
        self._lock = threading.Lock()
        self._traces: list[Span] = []
        self._slow: list[Span] = []
        # called (outside the lock) with every root span that enters the
        # slow-retention tier — the SLO-breach trigger for the engine
        # flight recorder (utils/flight_recorder.py). Must never raise
        # into the request; failures are swallowed.
        self.slow_hook = None

    def configure(
        self,
        capacity: int | None = None,
        slow_threshold_s: float | None = None,
        slow_capacity: int | None = None,
    ) -> None:
        """Apply config to the process-wide tracer (server startup)."""
        with self._lock:
            if capacity is not None:
                self.capacity = capacity
            if slow_threshold_s is not None:
                self.slow_threshold_s = slow_threshold_s
            if slow_capacity is not None:
                self.slow_capacity = slow_capacity

    @contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Span]:
        """Open a span under the ambient parent; a span with no parent is a
        root trace (adopting any inbound remote context) and lands in the
        ring buffer on completion."""
        sp = Span(name=name, attrs=attrs, start_s=time.time(), t0=time.monotonic())
        sp.span_id = _new_span_id()
        parent = _current_span.get()
        if parent is not None:
            sp.trace_id = parent.trace_id
            sp.root = parent.root or parent
        else:
            rp = _remote_parent.get()
            if rp is not None:
                sp.trace_id, sp.parent_id = rp
            else:
                sp.trace_id = _new_trace_id()
            sp.root = sp
        token = _current_span.set(sp)
        try:
            yield sp
        except BaseException as e:
            sp.error = f"{type(e).__name__}: {e}"
            raise
        finally:
            sp.duration_s = time.monotonic() - sp.t0
            _current_span.reset(token)
            if parent is not None:
                # list.append is atomic under the GIL; concurrent child spans
                # of one request (gather'd ensures) interleave safely
                parent.children.append(sp)
            else:
                is_slow = False
                with self._lock:
                    self._traces.append(sp)
                    if len(self._traces) > self.capacity:
                        del self._traces[: len(self._traces) - self.capacity]
                    if self.slow_threshold_s and sp.duration_s >= self.slow_threshold_s:
                        is_slow = True
                        self._slow.append(sp)
                        if len(self._slow) > self.slow_capacity:
                            del self._slow[: len(self._slow) - self.slow_capacity]
                hook = self.slow_hook
                if is_slow and hook is not None:
                    try:
                        hook(sp)
                    except Exception:  # noqa: BLE001 — diagnostics stay non-fatal
                        pass

    def annotate(self, **attrs: Any) -> None:
        """Attach attributes to the innermost open span, if any."""
        sp = _current_span.get()
        if sp is not None:
            sp.attrs.update(attrs)

    def annotate_root(self, **attrs: Any) -> None:
        """Attach attributes to the ROOT of the open trace — how deep layers
        label the whole request (the router marking route=forwarded, the
        local backend stamping the model id) without threading a handle."""
        sp = _current_span.get()
        if sp is not None:
            (sp.root or sp).attrs.update(attrs)

    def attach(self, parent: Span, name: str, duration_s: float,
               start_s: float | None = None, **attrs: Any) -> Span:
        """Attach an externally-timed, already-completed span as a child of
        ``parent``. For work that ran on an executor thread whose ambient
        context predates ``parent`` (the pipelined cold load's AOT compile),
        ``span()`` can't parent it — and for overlapped work Σ(children) may
        legitimately exceed the parent's wall time, which is exactly what
        ``cold_overlap_ratio`` measures."""
        sp = Span(name=name, attrs=attrs,
                  start_s=time.time() if start_s is None else start_s,
                  duration_s=duration_s)
        sp.span_id = _new_span_id()
        sp.trace_id = parent.trace_id
        parent.children.append(sp)
        return sp

    def attach_remote(self, parent: Span, payload: str | bytes,
                      **attrs: Any) -> Span | None:
        """Graft a peer's serialized completed subtree under ``parent`` —
        the stitch that turns two per-node traces into one logical trace.
        Returns the grafted root, or None for an undecodable payload."""
        sp = deserialize_span(payload)
        if sp is None:
            return None
        sp.remote = True
        if not sp.trace_id:
            sp.trace_id = parent.trace_id
        sp.attrs.update(attrs)
        parent.children.append(sp)
        return sp

    def query(
        self,
        n: int = 50,
        min_duration_s: float | None = None,
        trace_id: str | None = None,
    ) -> list[dict[str, Any]]:
        """Most-recent-first completed traces, searching BOTH the main ring
        and the slow-retention tier (so a >threshold trace stays findable
        after fast traffic wraps the ring)."""
        with self._lock:
            spans = list(self._traces)
            seen = {id(s) for s in spans}
            spans.extend(s for s in self._slow if id(s) not in seen)
        spans.sort(key=lambda s: s.start_s)
        if trace_id is not None:
            spans = [s for s in spans if s.trace_id == trace_id]
        if min_duration_s is not None:
            spans = [s for s in spans if s.duration_s >= min_duration_s]
        return [s.to_dict() for s in spans[-n:]][::-1]

    def recent(self, n: int = 50) -> list[dict[str, Any]]:
        return self.query(n=n)

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._slow.clear()


# Process-wide default. Diagnostics are write-mostly and bounded, so a global
# (unlike Metrics, which stays injected for registry isolation) keeps every
# call site plumbing-free; tests snapshot/clear it.
TRACER = Tracer()

"""Structured logging setup (reference: logrus config, cmd/taskhandler/cfg.go:28-61).

``fmt=json`` lines are trace-correlated: a log call made anywhere inside a
request's span tree (including serving-pool threads, which run under
``contextvars.copy_context``) carries the request's ``trace_id``/``span``
fields, so ``grep trace_id=... service.log`` reconstructs one request's log
story and joins it to /monitoring/traces. Outside a request context the
fields are absent — no empty-string spam for scrapers to special-case.
"""

from __future__ import annotations

import json
import logging
import sys
import time

from tfservingcache_tpu.utils.tracing import current_ids

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "panic": logging.CRITICAL,
}

# Attributes every LogRecord is born with — anything else on the record was
# passed by the caller via ``extra={...}`` and belongs in the JSON payload.
# (makeLogRecord keeps this version-proof: 3.12 added ``taskName``.)
_STD_RECORD_KEYS = frozenset(vars(logging.makeLogRecord({}))) | {
    "message", "asctime", "taskName",
}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime(record.created)),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "logger": record.name,
        }
        ids = current_ids()
        if ids is not None:
            payload["trace_id"], payload["span"] = ids
        for key, val in record.__dict__.items():
            # logrus-style structured fields: emit extra={...} attributes
            # (dropping them silently was the old behavior) without letting
            # a caller clobber the core keys above
            if key in _STD_RECORD_KEYS or key.startswith("_") or key in payload:
                continue
            payload[key] = val
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


def setup_logging(level: str = "info", fmt: str = "text") -> None:
    root = logging.getLogger()
    root.setLevel(_LEVELS.get(level.lower(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S")
        )
    root.handlers[:] = [handler]


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"tpusc.{name}")

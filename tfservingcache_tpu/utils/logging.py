"""Structured logging setup (reference: logrus config, cmd/taskhandler/cfg.go:28-61)."""

from __future__ import annotations

import json
import logging
import sys
import time

_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "panic": logging.CRITICAL,
}


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        payload = {
            "time": time.strftime("%Y-%m-%dT%H:%M:%S%z", time.localtime(record.created)),
            "level": record.levelname.lower(),
            "msg": record.getMessage(),
            "logger": record.name,
        }
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload)


def setup_logging(level: str = "info", fmt: str = "text") -> None:
    root = logging.getLogger()
    root.setLevel(_LEVELS.get(level.lower(), logging.INFO))
    handler = logging.StreamHandler(sys.stderr)
    if fmt == "json":
        handler.setFormatter(JsonFormatter())
    else:
        handler.setFormatter(
            logging.Formatter("%(asctime)s %(levelname)-5s %(name)s: %(message)s", "%H:%M:%S")
        )
    root.handlers[:] = [handler]


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"tpusc.{name}")

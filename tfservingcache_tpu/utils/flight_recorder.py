"""Engine flight recorder: always-on per-step telemetry + anomaly dumps.

The continuous engine (runtime/batcher.py) is a black box between
"admitted" and "retired": the SLO histograms say a request was slow, the
traces say which request, but neither says what the ENGINE was doing —
queue depth, lane occupancy, page pressure, wasted steps — at the moment
it went wrong. This module is the box's flight recorder:

- **Step ring**: one fixed-size record per dispatched decode chunk (and
  per coalescer batch drain) into a per-model ring buffer, ~4096 entries
  by default. Writes are lock-free on the hot path: a preallocated list,
  an ``itertools.count`` (atomic under the GIL) for slot assignment, and
  one tuple build — tens of microseconds, guarded by
  tests/test_flight_recorder.py (< 50 us/step).
- **Phase notes**: the per-request phase clocks (queue -> prefill ->
  decode -> respond) that also feed ``tpusc_request_phase_seconds`` are
  mirrored here (bounded deque per model) so a dump carries the exact
  per-request attribution for the window that triggered it.
- **Watermarks**: high-water marks (HBM in use, host-tier bytes, KV arena
  pages) observed at the existing gauge-update sites. Reset-on-scrape:
  ``GET /monitoring/engine`` returns them and zeroes the marks, so each
  scrape interval reports its own peak (pass ``reset=0`` to peek).
- **Anomaly dumps**: SLO breach (hooked into the tracer's slow-trace
  retention path), page-exhaustion blocking, and engine-thread crash each
  write the full ring + engine state to a bounded spool dir
  (``observability.flight_dir``). Dumps are deduplicated (per trace id)
  and rate-limited (per reason+model cooldown) so one incident is one
  file, not a disk-filling stream. ``tools/engine_dump.py`` pretty-prints
  them for postmortems.

Like the tracer (utils/tracing.py) the recorder is a process-wide default
instance: diagnostics are write-mostly and bounded, so a global keeps
every call site plumbing-free; tests construct their own instances or
snapshot/clear the global. Rings record from construction; dumps stay OFF
until ``configure(flight_dir=...)`` (server startup) so bare components in
tests never touch the filesystem.
"""

from __future__ import annotations

import collections
import itertools
import json
import os
import threading
import time
from typing import Any

from tfservingcache_tpu.utils.logging import get_logger

from tfservingcache_tpu.utils.lockcheck import lockchecked

log = get_logger("flight_recorder")

# One record per dispatched chunk / batch drain. Fixed tuple layout (not a
# dict) keeps the hot-path write a single list-slot assignment; the names
# are the serialization contract for snapshots, dumps, and
# tools/engine_dump.py.
STEP_FIELDS = (
    "t_wall",          # epoch seconds at record time
    "engine",          # "continuous" | "coalesce"
    "step_ms",         # wall time of this chunk boundary / batch drain
    "chunk",           # decode steps computed per lane this dispatch
    "active",          # lanes (rows) the dispatch computed for
    "admitted",        # rows admitted at this boundary
    "retired",         # rows retired at this boundary
    "pages_used",      # KV arena pages reserved after this step (0 = dense)
    "pages_free",      # KV arena pages free after this step
    "wasted",          # steps computed for already-finished rows this step
    "queue_depth",     # rows still waiting for admission
    "oldest_wait_ms",  # age of the oldest queued row (0 when queue empty)
    # appended fields (ISSUE 9 shared-prefix KV) — new names go at the END
    # so the positional indices older dumps/tools rely on stay valid
    "pages_shared",    # arena pages referenced by >1 owner after this step
    "prefix_hits",     # admissions this boundary that reused prefix KV
    # appended fields (ISSUE 16 in-engine speculative decoding)
    "drafted",         # draft tokens proposed this step (0 = plain chunk)
    "accepted",        # tokens emitted by the verify round this step
)

DEFAULT_RING_ENTRIES = 4096
_PHASE_NOTES_PER_MODEL = 64


def _step_dict(e: tuple) -> dict[str, Any]:
    """One ring tuple -> the serialization dict. record() always writes
    full-width tuples, so the common case is a literal build (~3x faster
    than dict(zip) — snapshot() materializes tail*models of these and is
    budgeted at < 5 ms for 128 tenant rings); short tuples (deserialized
    from pre-ISSUE-9 dumps) fall back to zip."""
    if len(e) == 16:
        return {
            "t_wall": e[0], "engine": e[1], "step_ms": e[2], "chunk": e[3],
            "active": e[4], "admitted": e[5], "retired": e[6],
            "pages_used": e[7], "pages_free": e[8], "wasted": e[9],
            "queue_depth": e[10], "oldest_wait_ms": e[11],
            "pages_shared": e[12], "prefix_hits": e[13],
            "drafted": e[14], "accepted": e[15],
        }
    return dict(zip(STEP_FIELDS, e))


class _Ring:
    """Lock-free fixed-size ring of step tuples: one writer-side atomic
    counter hands out slots, so concurrent writers (coalescer leaders of
    the same model) never block each other; a torn read during snapshot
    costs at most one misordered diagnostic row, never a crash."""

    def __init__(self, entries: int) -> None:
        self.entries = entries
        self.buf: list[tuple | None] = [None] * entries
        self._ctr = itertools.count()
        self.written = 0  # monotonic-ish total (racy, diagnostics only)

    def append(self, rec: tuple) -> None:
        i = next(self._ctr)
        self.buf[i % self.entries] = rec
        self.written = i + 1

    def tail(self, n: int) -> list[tuple]:
        """Last ``n`` records, oldest first. Copies only the requested
        window (one or two list slices), not the whole ring: with 128
        tenant rings a full-buffer copy per ring put engine_stats() and
        snapshot() at ~milliseconds each (guarded at < 5 ms total by
        tests/test_flight_recorder.py). Slices are GIL-atomic reference
        copies; a concurrent writer costs at most one misordered row."""
        w = self.written
        n = max(0, min(n, w, self.entries))
        if n == 0:
            return []
        start = (w - n) % self.entries
        stop = w % self.entries
        if start >= stop:  # window wraps (or spans the full ring)
            part = self.buf[start:] + self.buf[:stop]
        else:
            part = self.buf[start:stop]
        return [rec for rec in part if rec is not None]


@lockchecked
class FlightRecorder:
    # Registry entries are checked statically AND dynamically; _rings/_phases
    # carry static-only "# guarded-by:" comments instead because their hot-path
    # readers are deliberately lock-free (see waivers.txt).
    _tpusc_guarded = {
        "_dumped_keys": "_lock",
        "_last_dump": "_lock",
        "_fault_counts": "_lock",
    }

    def __init__(
        self,
        ring_entries: int = DEFAULT_RING_ENTRIES,
        flight_dir: str | None = None,
        max_dumps: int = 16,
        dump_cooldown_s: float = 60.0,
    ) -> None:
        self.ring_entries = max(16, int(ring_entries))
        self.flight_dir = flight_dir
        self.max_dumps = max(1, int(max_dumps))
        self.dump_cooldown_s = float(dump_cooldown_s)
        self._lock = threading.Lock()        # structure mutations only
        self._rings: dict[str, _Ring] = {}  # guarded-by: _lock
        self._phases: dict[str, collections.deque] = {}  # guarded-by: _lock
        self._marks: dict[str, float] = {}
        self._dump_seq = itertools.count()
        self._dumped_keys: collections.deque = collections.deque(maxlen=256)
        self._last_dump: dict[tuple, float] = {}
        # scenario-lab fault tally (lab/faults.py note_fault): kind -> count
        # of injections fired this process. Rides the recorder, not Metrics,
        # so engine-only harnesses without a registry still get scorecard
        # fault counts.
        self._fault_counts: dict[str, int] = {}
        # latest conversation-KV tier stats (cache/conversation_kv.py
        # _update_gauges): parked counts/bytes/hit-rate. Rides the recorder
        # so /monitoring/engine and tools/engine_dump.py surface the tier
        # without a separate endpoint.
        self._conversation_kv: dict[str, Any] | None = None

    def configure(
        self,
        flight_dir: str | None = None,
        ring_entries: int | None = None,
        max_dumps: int | None = None,
        dump_cooldown_s: float | None = None,
    ) -> None:
        """Apply config to the process-wide recorder (server startup). An
        empty/None ``flight_dir`` keeps dumps disabled; existing rings keep
        their size (resizing would drop the history worth keeping)."""
        with self._lock:
            if flight_dir is not None:
                self.flight_dir = flight_dir or None
            if ring_entries is not None:
                self.ring_entries = max(16, int(ring_entries))
            if max_dumps is not None:
                self.max_dumps = max(1, int(max_dumps))
            if dump_cooldown_s is not None:
                self.dump_cooldown_s = float(dump_cooldown_s)

    def install_slow_hook(self, tracer: Any) -> None:
        """Hook the tracer's slow-trace retention path: every root span
        that crosses ``slow_threshold_s`` (the same tail-sampling gate that
        keeps the trace findable) also triggers one engine dump, deduped by
        trace id so one breached request is exactly one file."""
        tracer.slow_hook = self._on_slow_trace

    def _on_slow_trace(self, span: Any) -> None:
        self.dump(
            "slo_breach",
            dedup_key=("slo", span.trace_id),
            trace_id=span.trace_id,
            root_span=span.name,
            duration_s=round(span.duration_s, 6),
            attrs=dict(span.attrs),
        )

    # -- hot path ------------------------------------------------------------
    def _ring(self, model: str) -> _Ring:
        ring = self._rings.get(model)
        if ring is None:
            with self._lock:
                ring = self._rings.setdefault(model, _Ring(self.ring_entries))
        return ring

    def record(
        self,
        model: str,
        engine: str,
        step_ms: float,
        chunk: int,
        active: int,
        admitted: int,
        retired: int,
        pages_used: int = 0,
        pages_free: int = 0,
        wasted: int = 0,
        queue_depth: int = 0,
        oldest_wait_ms: float = 0.0,
        pages_shared: int = 0,
        prefix_hits: int = 0,
        drafted: int = 0,
        accepted: int = 0,
    ) -> None:
        self._ring(model).append((
            time.time(), engine, round(step_ms, 4), chunk, active, admitted,
            retired, pages_used, pages_free, wasted, queue_depth,
            round(oldest_wait_ms, 3), pages_shared, prefix_hits,
            drafted, accepted,
        ))

    def note_phases(
        self,
        model: str,
        engine: str,
        phases: dict[str, float],
        trace_id: str | None = None,
    ) -> None:
        """Mirror one request's phase clocks (the same values observed into
        ``tpusc_request_phase_seconds``) so dumps carry exact per-request
        attribution for the triggering window."""
        dq = self._phases.get(model)
        if dq is None:
            with self._lock:
                dq = self._phases.setdefault(
                    model, collections.deque(maxlen=_PHASE_NOTES_PER_MODEL)
                )
        dq.append({
            "t_wall": time.time(),
            "engine": engine,
            "trace_id": trace_id or "",
            "phases": {k: round(v, 6) for k, v in phases.items()},
        })

    def observe_watermark(self, key: str, value: float) -> float:
        """Track a high-water mark; returns the current peak so the call
        site can mirror it into its Prometheus peak gauge."""
        cur = self._marks.get(key, 0.0)
        if value > cur:
            self._marks[key] = value
            return float(value)
        return float(cur)

    # -- read side -----------------------------------------------------------
    def watermarks(self, reset: bool = False) -> dict[str, float]:
        with self._lock:
            out = dict(self._marks)
            if reset:
                self._marks.clear()
        return out

    @staticmethod
    def _window(entries: list[tuple]) -> dict[str, Any]:
        """Aggregate a step window: goodput = useful / total computed
        step-slots (useful = active*chunk - wasted), the one-number answer
        to "is the engine's compute going to live requests"."""
        # single pass (not one generator sweep per aggregate): this runs
        # per model per snapshot, so at 128 tenant rings the constant matters
        total = wasted = admitted = hits = 0
        drafted = accepted = spec_slots = 0
        step_ms = 0.0
        max_depth = 0
        max_wait = 0.0
        max_shared = 0
        for e in entries:
            total += e[4] * e[3]                        # active * chunk
            wasted += e[9]
            admitted += e[5]
            step_ms += e[2]
            if e[10] > max_depth:
                max_depth = e[10]
            if e[11] > max_wait:
                max_wait = e[11]
            # appended fields may be absent in entries deserialized from old
            # dumps — treat short tuples as zero, same as a dense engine
            if len(e) > 12 and e[12] > max_shared:
                max_shared = e[12]
            if len(e) > 13:
                hits += e[13]
            if len(e) > 15 and e[14]:
                # speculative steps only: acceptance = emitted tokens over
                # the round's emission capacity (active * (spec+1) slots)
                drafted += e[14]
                accepted += e[15]
                spec_slots += e[4] * e[3]
        return {
            "steps": len(entries),
            "step_slots": total,
            "wasted_steps": wasted,
            "goodput": round((total - wasted) / total, 6) if total else 1.0,
            "step_ms_sum": round(step_ms, 3),
            "max_queue_depth": max_depth,
            "max_oldest_wait_ms": max_wait,
            "admitted": admitted,
            "prefix_hits": hits,
            "prefix_hit_rate": round(hits / admitted, 6) if admitted else 0.0,
            "max_pages_shared": max_shared,
            "drafted": drafted,
            "accepted": accepted,
            "spec_acceptance": (
                round(accepted / spec_slots, 6) if spec_slots else 0.0
            ),
        }

    def note_conversation_kv(self, stats: dict[str, Any]) -> None:
        """Record the conversation-KV tier's latest stats row (called by
        the tier on every put/evict/promote — a dict swap, not a merge, so
        the cost is one assignment under the lock)."""
        with self._lock:
            self._conversation_kv = dict(stats)

    def conversation_kv_stats(self) -> dict[str, Any] | None:
        with self._lock:
            return dict(self._conversation_kv) if self._conversation_kv else None

    def note_fault(self, kind: str) -> None:
        """Tally one scenario-lab fault injection (lab/faults.py). Cheap on
        purpose: injections happen at most a handful per drill, never on a
        per-token path."""
        with self._lock:
            self._fault_counts[kind] = self._fault_counts.get(kind, 0) + 1

    def fault_counts(self) -> dict[str, int]:
        """Snapshot of the per-kind injection tally (scorecards diff two
        snapshots around a cell replay)."""
        with self._lock:
            return dict(self._fault_counts)

    def engine_stats(self, tail: int = 32) -> dict[str, float]:
        """Cheap cross-model aggregate for the fleet status plane
        (cluster/status.py): goodput over the last ``tail`` ring entries,
        the summed CURRENT queue depth, and the worst current oldest-wait.
        Unlike snapshot() this builds no per-step dicts — a status
        collection must stay well under 1 ms (guarded by
        tests/test_fleet_status.py)."""
        total = 0
        wasted = 0
        depth = 0
        wait_ms = 0.0
        spec_slots = 0
        accepted = 0
        for ring in list(self._rings.values()):
            entries = ring.tail(tail)
            if not entries:
                continue
            for e in entries:
                total += e[4] * e[3]                     # active * chunk
                wasted += e[9]
                if len(e) > 15 and e[14]:
                    spec_slots += e[4] * e[3]
                    accepted += e[15]
            last = entries[-1]
            depth += last[10]
            wait_ms = max(wait_ms, last[11])
        return {
            "goodput": (total - wasted) / total if total else 1.0,
            "queue_depth": depth,
            "oldest_wait_ms": wait_ms,
            # emitted tokens over speculative emission capacity in the
            # window; 0.0 when no spec round ran (spec off or disabled)
            "spec_acceptance": (
                accepted / spec_slots if spec_slots else 0.0
            ),
        }

    def snapshot(
        self,
        tail: int = 64,
        reset_watermarks: bool = False,
        model: str | None = None,
        row_budget: int | None = 2048,
    ) -> dict[str, Any]:
        """JSON-ready engine state: per-model step window + aggregates,
        phase notes, watermarks. The ``/monitoring/engine`` payload.
        ``model`` (the "name@version" ring key) restricts the per-model
        sections to one tenant — the multi-tenant ?model= filter; an
        unknown model yields empty sections plus an explicit
        ``model_found: false`` marker (tools/engine_dump.py renders it), so
        a typo'd tenant is distinguishable from a quiet engine.

        ``row_budget`` caps the TOTAL step rows materialized across models:
        past budget/tail tenants the per-model tail shrinks (floor 8), so a
        128-tenant node still answers /monitoring/engine in < 5 ms
        (tests/test_flight_recorder.py) instead of scaling the payload —
        and the work — linearly with tenant count. Anomaly dumps pass
        ``row_budget=None``: a postmortem wants the full rings."""
        with self._lock:
            rings = dict(self._rings)
            phases = {m: list(dq) for m, dq in self._phases.items()}
        found = model is None or model in rings or model in phases
        if model is not None:
            rings = {m: r for m, r in rings.items() if m == model}
            phases = {m: p for m, p in phases.items() if m == model}
        if row_budget is not None and rings:
            tail = max(8, min(tail, row_budget // len(rings)))
        models: dict[str, Any] = {}
        for name, ring in rings.items():
            entries = ring.tail(tail)
            models[name] = {
                "recorded_steps": ring.written,
                "window": self._window(entries),
                "steps": [_step_dict(e) for e in entries],
            }
        out: dict[str, Any] = {
            "ring_entries": self.ring_entries,
            "models": models,
            "phases": phases,
            "watermarks": self.watermarks(reset=reset_watermarks),
        }
        ckv = self.conversation_kv_stats()
        if ckv is not None:
            out["conversation_kv"] = ckv
        if model is not None:
            out["model_filter"] = model
            out["model_found"] = found
        return out

    # -- anomaly dumps -------------------------------------------------------
    def dump(
        self,
        reason: str,
        dedup_key: tuple | None = None,
        model: str | None = None,
        **context: Any,
    ) -> str | None:
        """Write the full ring + engine state to the spool dir. Returns the
        file path, or None when dumps are disabled / deduped / cooling
        down. Never raises: a failing dump must not fail the request or
        kill the scheduler thread that tripped it."""
        if self.flight_dir is None:
            return None
        now = time.monotonic()
        with self._lock:
            if dedup_key is not None:
                if dedup_key in self._dumped_keys:
                    return None
                self._dumped_keys.append(dedup_key)
            else:
                cool_key = (reason, model or "")
                last = self._last_dump.get(cool_key)
                if last is not None and now - last < self.dump_cooldown_s:
                    return None
                self._last_dump[cool_key] = now
            seq = next(self._dump_seq)
        try:
            payload = self.snapshot(tail=self.ring_entries, row_budget=None)
            payload.update(
                reason=reason,
                model=model or "",
                time_s=time.time(),
                context=context,
            )
            os.makedirs(self.flight_dir, exist_ok=True)
            fname = (
                f"flight_{time.strftime('%Y%m%dT%H%M%S', time.gmtime())}"
                f"_{seq:06d}_{reason}.json"
            )
            path = os.path.join(self.flight_dir, fname)
            tmp = path + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, separators=(",", ":"), default=str)
            os.replace(tmp, path)
            self._prune_dumps()
            log.warning("flight recorder dumped %s -> %s", reason, path)
            return path
        except Exception as e:  # noqa: BLE001 — diagnostics must stay non-fatal
            log.warning("flight dump for %s failed: %s", reason, e)
            return None

    def list_dumps(self) -> list[str]:
        if self.flight_dir is None or not os.path.isdir(self.flight_dir):
            return []
        return sorted(
            f for f in os.listdir(self.flight_dir)
            if f.startswith("flight_") and f.endswith(".json")
        )

    def _prune_dumps(self) -> None:
        """Bound the spool dir: names embed (utc timestamp, global seq) so
        lexical order IS write order — delete oldest beyond max_dumps."""
        files = self.list_dumps()
        for f in files[: max(0, len(files) - self.max_dumps)]:
            try:
                os.remove(os.path.join(self.flight_dir, f))
            except OSError:
                pass

    def clear(self) -> None:
        with self._lock:
            self._rings.clear()
            self._phases.clear()
            self._marks.clear()
            self._dumped_keys.clear()
            self._last_dump.clear()


# Process-wide default (same rationale as utils/tracing.TRACER): recording
# is always on and bounded; dumps arm only when server startup configures a
# flight_dir. Tests snapshot/clear or construct their own instances.
RECORDER = FlightRecorder()

"""Ahead-of-time warming on ring assignment.

The reference has no such feature: after a membership change, a remapped
model is cold-loaded by its first request (cluster.go:116-130 — recovery is
emergent from the miss path, SURVEY §3.4). With inference in-process that
first request pays HBM transfer + (possibly) compile, so SURVEY §7 hard
part (a) makes assignment-time warming load-bearing for the <=2 s cold
target. Policy:

  - when membership changes, each local chip group warms the models it now
    OWNS (self among the key's replica set) and already has in its local
    disk cache — the artifact read is free, ``ensure_servable`` pins params
    and the family-shared executable before traffic arrives;
  - owned-but-not-on-disk models are NOT fetched: warming everything a node
    owns would stampede the store on every remap, and the LRU would evict
    most of it unused;
  - no-longer-owned resident models are left alone — stragglers age out of
    the LRU exactly like the reference's implicit elasticity.

Warm work runs on one daemon thread (the device serializes loads anyway)
and always against the LATEST membership snapshot: a remap arriving
mid-sweep restarts the sweep rather than queueing stale work.
"""

from __future__ import annotations

import threading

from tfservingcache_tpu.models.registry import resident_bytes_estimate
from tfservingcache_tpu.utils.logging import get_logger

log = get_logger("cluster.warmer")


class AssignmentWarmer:
    def __init__(self, cluster, groups: list[tuple[str, object]],
                 metrics=None) -> None:
        """``cluster`` needs ``find_nodes_for_key``; ``groups`` pairs each
        local ring-member ident with its group's CacheManager."""
        self.cluster = cluster
        self.groups = groups
        self.metrics = metrics
        self._wake = threading.Event()
        self._stop = False
        self._generation = 0
        self.warmed = 0  # observability (tests + logs)
        self._thread = threading.Thread(
            target=self._work_loop, name="tpusc-warmer", daemon=True
        )
        self._thread.start()

    def on_update(self, _nodes) -> None:
        """Cluster callback: runs on the update path, so it only wakes the
        worker — never touches the provider or the device inline."""
        self._generation += 1
        self._wake.set()

    def _work_loop(self) -> None:
        while True:
            self._wake.wait()
            self._wake.clear()
            if self._stop:
                return
            gen = self._generation
            try:
                self._sweep(gen)
            except Exception:  # noqa: BLE001 - advisory work must not die
                log.exception("assignment warm sweep failed")

    def _sweep(self, gen: int) -> None:
        for ident, manager in self.groups:
            # list is MRU-first, so capacity (below) is spent on the models
            # most likely to be asked for first
            for mid in manager.disk_cache.list_models():
                if self._stop or self._generation != gen:
                    return  # newer membership: restart against it
                owners = {
                    n.ident for n in self.cluster.find_nodes_for_key(mid.key)
                }
                if ident not in owners:
                    continue
                # re-check on disk right before warming: a concurrent LRU
                # eviction since the listing would otherwise send
                # ensure_servable down the MISS path — a provider fetch this
                # policy promises not to make (a remaining hairline race is
                # acceptable: warming is advisory)
                cached = manager.disk_cache.get(mid)
                if cached is None:
                    continue
                # bound the sweep by free resident capacity: when a node
                # owns more cached models than fit in HBM (the multi-tenant
                # norm), warming past the cap would evict actively-serving
                # models — and this sweep's own earlier warms — churning
                # live traffic right after a remap (ADVICE r3 medium)
                runtime = getattr(manager, "runtime", None)
                headroom = getattr(runtime, "resident_headroom", None)
                if headroom is not None and not runtime.is_loaded(mid):
                    free_slots, free_bytes = headroom()
                    # device bytes, not disk bytes: an int8 artifact
                    # dequantizes on device to 2-4x its disk size (ADVICE r4)
                    est = (
                        resident_bytes_estimate(cached.path)
                        or manager.disk_cache.size_of(mid)
                        or 0
                    )
                    if (free_slots is not None and free_slots <= 0) or (
                        est > free_bytes
                    ):
                        log.info(
                            "warm sweep for %s stopped at resident capacity "
                            "(%s slots free, %d bytes free, next needs ~%d)",
                            ident, free_slots, free_bytes, est,
                        )
                        break  # MRU-first: everything after is colder
                try:
                    manager.ensure_servable(mid)
                    self.warmed += 1
                    if self.metrics is not None:
                        self.metrics.assignment_warms.inc()
                except Exception as e:  # noqa: BLE001
                    # a failed warm costs nothing: the request path retries
                    log.warning("assignment warm of %s failed: %s", mid, e)

    def close(self) -> None:
        """Blocking (call via ``asyncio.to_thread`` from a loop). An
        in-flight ensure_servable cannot be interrupted; on join timeout the
        daemon thread finishes its one model and exits at the next check —
        its errors are swallowed by the per-model try."""
        self._stop = True
        self._generation += 1  # abort the sweep at its next model boundary
        self._wake.set()
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # an in-flight cold load outlived the join budget: teardown
            # proceeds, so name the race loudly instead of letting the
            # straggler fail silently against a closing backend (ADVICE r3)
            log.warning(
                "warmer thread still mid-load at close; it exits at the next "
                "model boundary and its errors are swallowed"
            )

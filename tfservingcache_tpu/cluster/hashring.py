"""Consistent hash ring.

Reference equivalent: the stathat.com/c/consistent dependency wrapped by
ClusterConnection (pkg/taskhandler/cluster.go:66-130, go.mod:25) — the
reference's entire "distributed scheduler" (SURVEY.md §2 C13). Re-designed
rather than ported: 64-bit blake2b points (crc32's 32-bit space causes
visible imbalance), ~160 virtual nodes per member, bisect lookups, and a
``get_n`` that walks the ring for N *distinct* members (replicasPerModel
semantics, cluster.go:116-130).

Keys are ``name##version`` routing keys (taskhandler.go:84-92); members are
node identity strings ``host:restPort:grpcPort`` (cluster.go:142-164).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from tfservingcache_tpu.utils.lockcheck import lockchecked


def _point(data: str) -> int:
    return int.from_bytes(hashlib.blake2b(data.encode(), digest_size=8).digest(), "big")


@lockchecked
class HashRing:
    # Guarded-field registry (tools/tpusc_check TPUSC001 + TPUSC_LOCKCHECK=1).
    _tpusc_guarded = {
        "_points": "_lock",
        "_owners": "_lock",
        "_members": "_lock",
    }

    def __init__(self, vnodes: int = 160) -> None:
        self.vnodes = vnodes
        self._lock = threading.Lock()
        self._points: list[int] = []        # sorted hash points
        self._owners: list[str] = []        # owner member per point (parallel)
        self._members: set[str] = set()

    # -- membership ---------------------------------------------------------
    def set_members(self, members: list[str]) -> None:
        """Atomic full replacement (reference consistent.Set on every
        membership delta, cluster.go:104-113 — the whole ring is rebuilt so
        watch-event ordering can't corrupt incremental state)."""
        pairs: list[tuple[int, str]] = []
        for m in set(members):
            for i in range(self.vnodes):
                pairs.append((_point(f"{m}#{i}"), m))
        pairs.sort()
        with self._lock:
            self._points = [p for p, _ in pairs]
            self._owners = [o for _, o in pairs]
            self._members = set(members)

    @property
    def members(self) -> set[str]:
        with self._lock:
            return set(self._members)

    def __len__(self) -> int:
        with self._lock:
            return len(self._members)

    # -- lookup -------------------------------------------------------------
    def get_n(self, key: str, n: int) -> list[str]:
        """N distinct members for ``key``, walking clockwise from the key's
        point. n is clamped to the member count; n<1 treated as 1 (reference
        FindNodeForKey's max(replicas,1), cluster.go:116-118)."""
        n = max(n, 1)
        with self._lock:
            if not self._points:
                return []
            n = min(n, len(self._members))
            idx = bisect.bisect_left(self._points, _point(key)) % len(self._points)
            found: list[str] = []
            seen: set[str] = set()
            for step in range(len(self._points)):
                owner = self._owners[(idx + step) % len(self._points)]
                if owner not in seen:
                    seen.add(owner)
                    found.append(owner)
                    if len(found) == n:
                        break
            return found

    def get(self, key: str) -> str | None:
        nodes = self.get_n(key, 1)
        return nodes[0] if nodes else None
